"""HTTP/1 protocol filters for the router stacks.

Ref: router/http filters — FramingFilter (dup/conflicting Content-Length
-> 4xx/502), StripHopByHopHeadersFilter, ViaHeaderAppenderFilter,
AddForwardedHeader.scala:185 (RFC 7239), ProxyRewriteFilter (absolute-URI
proxy requests), and linkerd/protocol/http LinkerdHeaders ``l5d-dst-*``
context headers (LinkerdHeaders.scala:49-502) + ServerConfig clearContext
(ClearContext.scala).
"""

from __future__ import annotations

from typing import List, Optional
from urllib.parse import urlsplit

from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.router.service import Filter, Service

VIA_VALUE = "1.1 linkerd"

# RFC 7230 §6.1 + TTwitter legacy set (StripHopByHopHeadersFilter.scala)
HOP_BY_HOP = frozenset({
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailer", "transfer-encoding", "upgrade",
    "proxy-connection",
})

L5D_CTX_PREFIX = "l5d-ctx-"
L5D_DST_SERVICE = "l5d-dst-service"
L5D_DST_CLIENT = "l5d-dst-client"
L5D_DST_RESIDUAL = "l5d-dst-residual"
L5D_REQID = "l5d-reqid"


class FramingFilter(Filter[Request, Response]):
    """Reject messages with conflicting Content-Length headers
    (request-smuggling defence; ref: FramingFilter.scala — 4xx for
    requests, 502 for responses)."""

    @staticmethod
    def _bad(msg: "Request | Response") -> bool:
        lens = {v.strip() for v in msg.headers.get_all("content-length")}
        return len(lens) > 1

    async def apply(self, req: Request, service: Service) -> Response:
        if self._bad(req):
            return Response(status=400,
                            body=b"conflicting Content-Length headers")
        rsp = await service(req)
        if self._bad(rsp):
            return Response(status=502,
                            body=b"upstream sent conflicting Content-Length")
        return rsp


class StripHopByHopHeadersFilter(Filter[Request, Response]):
    """Remove hop-by-hop headers (and anything named by Connection)
    in both directions (ref: StripHopByHopHeadersFilter.scala)."""

    @staticmethod
    def _strip(msg) -> None:
        named = set()
        for v in msg.headers.get_all("connection"):
            named.update(t.strip().lower() for t in v.split(",") if t.strip())
        for name in HOP_BY_HOP | named:
            msg.headers.remove(name)

    async def apply(self, req: Request, service: Service) -> Response:
        self._strip(req)
        rsp = await service(req)
        self._strip(rsp)
        return rsp


class ViaHeaderAppenderFilter(Filter[Request, Response]):
    """Append ``Via: 1.1 linkerd`` on request and response
    (ref: ViaHeaderAppenderFilter.scala)."""

    @staticmethod
    def _append(msg) -> None:
        existing = msg.headers.get("via")
        msg.headers.set("Via", f"{existing}, {VIA_VALUE}"
                        if existing else VIA_VALUE)

    async def apply(self, req: Request, service: Service) -> Response:
        self._append(req)
        rsp = await service(req)
        self._append(rsp)
        return rsp


class AddForwardedHeaderFilter(Filter[Request, Response]):
    """RFC 7239 ``Forwarded: for=...;by=...`` (ref:
    AddForwardedHeader.scala:185; config-gated, off by default since it
    adds per-request allocation)."""

    @staticmethod
    def _elem(addr: Optional[tuple]) -> str:
        if not addr:
            return "unknown"
        host = addr[0]
        if ":" in host:  # IPv6 must be bracketed+quoted per RFC 7239
            return f'"[{host}]"'
        return host

    async def apply(self, req: Request, service: Service) -> Response:
        client = req.ctx.get("client_addr")
        server = req.ctx.get("server_addr")
        elem = f"for={self._elem(client)};by={self._elem(server)}"
        existing = req.headers.get("forwarded")
        req.headers.set("Forwarded",
                        f"{existing}, {elem}" if existing else elem)
        return await service(req)


class ProxyRewriteFilter(Filter[Request, Response]):
    """Accept absolute-URI (proxy-form) requests: rewrite to origin-form
    and set Host from the URI authority (ref: ProxyRewriteFilter.scala)."""

    async def apply(self, req: Request, service: Service) -> Response:
        if req.uri.startswith("http://") or req.uri.startswith("https://"):
            parts = urlsplit(req.uri)
            if parts.netloc:
                req.headers.set("Host", parts.netloc)
                path = parts.path or "/"
                if parts.query:
                    path += f"?{parts.query}"
                req.uri = path
        return await service(req)


class ClearContextFilter(Filter[Request, Response]):
    """Strip inbound linkerd context headers at the server edge
    (ref: ServerConfig clearContext -> ClearContext.scala) so untrusted
    callers can't inject trace ids or dtab overrides."""

    async def apply(self, req: Request, service: Service) -> Response:
        doomed = [n for n, _ in req.headers.items()
                  if n.lower().startswith("l5d-")]
        for n in doomed:
            req.headers.remove(n)
        return await service(req)


class DstHeadersFilter(Filter[Request, Response]):
    """Client-side ``l5d-dst-*`` headers telling the next hop how this
    request was routed (ref: LinkerdHeaders.Dst, LinkerdHeaders.scala)."""

    def __init__(self, client_id: str):
        self._client_id = client_id

    async def apply(self, req: Request, service: Service) -> Response:
        dst = req.ctx.get("dst")
        if dst is not None:
            req.headers.set(L5D_DST_SERVICE, dst.path.show)
        req.headers.set(L5D_DST_CLIENT, self._client_id)
        return await service(req)
