"""HTTP request identifiers: request -> logical Dst path.

Reference parity: router/http identifiers (MethodAndHostIdentifier.scala:51,
PathIdentifier, HeaderIdentifier, StaticIdentifier) and linkerd's default
``io.l5d.header.token`` (Host header token). Each is a config dataclass
registered under the ``identifier`` category; ``mk(prefix)`` builds the
callable used by RoutingService.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from linkerd_tpu.config import register
from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.protocol.http.message import Request
from linkerd_tpu.router.binding import DstPath
from linkerd_tpu.router.routing import (
    IdentificationError, Identifier, parse_local_dtab,
)


def _clean_host(host: Optional[str]) -> str:
    if not host:
        raise IdentificationError("no Host header")
    return host.split(":", 1)[0].lower()


@register("identifier", "io.l5d.header.token")
@dataclass
class HeaderTokenIdentifier:
    """``/<prefix>/<token>`` from a header (default Host), the linkerd
    default HTTP identifier."""

    header: str = "Host"

    def mk(self, prefix: Path, base_dtab: Dtab) -> Identifier:
        def identify(req: Request) -> DstPath:
            if self.header.lower() == "host":
                token = _clean_host(req.host)
            else:
                token = req.headers.get(self.header) or ""
            if not token:
                raise IdentificationError(f"no {self.header} header")
            # a token with slashes is a path; otherwise one segment
            p = Path.read(token) if token.startswith("/") else Path.of(token)
            return DstPath(prefix + p, base_dtab, parse_local_dtab(req))

        return identify


@register("identifier", "io.l5d.methodAndHost")
@dataclass
class MethodAndHostIdentifier:
    """``/<prefix>/1.1/<METHOD>/<host>`` (ref: MethodAndHostIdentifier.scala)."""

    httpUriInDst: bool = False

    def mk(self, prefix: Path, base_dtab: Dtab) -> Identifier:
        def identify(req: Request) -> DstPath:
            host = _clean_host(req.host)
            version = "1.1" if req.version == "HTTP/1.1" else "1.0"
            p = prefix + Path.of(version, req.method, host)
            if self.httpUriInDst:
                p = p + Path.read(req.path)
            return DstPath(p, base_dtab, parse_local_dtab(req))

        return identify


@register("identifier", "io.l5d.path")
@dataclass
class PathIdentifier:
    """``/<prefix>/<first-N-uri-segments>`` (ref: PathIdentifier.scala)."""

    segments: int = 1
    consume: bool = False

    def mk(self, prefix: Path, base_dtab: Dtab) -> Identifier:
        def identify(req: Request) -> DstPath:
            segs = Path.read(req.path)
            if len(segs) < self.segments:
                raise IdentificationError(
                    f"uri {req.path!r} has fewer than {self.segments} segments")
            taken = segs.take(self.segments)
            if self.consume:
                rest = segs.drop(self.segments)
                q = req.uri.find("?")
                query = req.uri[q:] if q >= 0 else ""
                req.uri = rest.show + query
            return DstPath(prefix + taken, base_dtab, parse_local_dtab(req))

        return identify


@register("identifier", "io.l5d.header")
@dataclass
class HeaderIdentifier:
    """Path read verbatim from a header (ref: HeaderIdentifier.scala)."""

    header: str = "l5d-name"

    def mk(self, prefix: Path, base_dtab: Dtab) -> Identifier:
        def identify(req: Request) -> DstPath:
            raw = req.headers.get(self.header)
            if not raw:
                raise IdentificationError(f"no {self.header} header")
            try:
                p = Path.read(raw)
            except ValueError as e:
                raise IdentificationError(str(e)) from None
            return DstPath(prefix + p, base_dtab, parse_local_dtab(req))

        return identify


@register("identifier", "io.l5d.static")
@dataclass
class StaticIdentifier:
    """Every request to one logical path (ref: StaticIdentifier.scala)."""

    path: str = "/svc/default"

    def mk(self, prefix: Path, base_dtab: Dtab) -> Identifier:
        dst_path = Path.read(self.path)

        def identify(req: Request) -> DstPath:
            return DstPath(dst_path, base_dtab, parse_local_dtab(req))

        return identify


def compose_identifiers(ids: List[Identifier]) -> Identifier:
    """Try identifiers in order; first success wins
    (ref: HttpConfig.scala:232-236 identifier list composition)."""

    def identify(req: Request) -> DstPath:
        errs = []
        for ident in ids:
            try:
                return ident(req)
            except IdentificationError as e:
                errs.append(str(e))
        raise IdentificationError("; ".join(errs) or "no identifier matched")

    return identify
