"""Per-router request-logger plugin chain (the ``logger`` SPI).

Ref: linkerd/protocol/http/.../HttpLoggerConfig.scala — router configs
carry ``loggers: [{kind: ...}, ...]``; each kind materializes a filter
inserted into the client stack per request (the plugin point istio's
mixer logger uses, IstioLogger.scala). Kinds here:

- ``io.l5d.http.debug`` — logs one line per request/response pair at a
  configurable level (method, uri, dst, status, latency).
- ``io.l5d.http.file`` — appends JSON lines to a file off the event
  loop (same QueueListener pattern as the access log).
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass
from typing import Optional

from linkerd_tpu.config import ConfigError, register
from linkerd_tpu.router.service import Filter, Service

log = logging.getLogger("linkerd_tpu.reqlog")


class DebugLogger(Filter):
    def __init__(self, level: int, logger_name: str):
        self._level = level
        self._log = logging.getLogger(logger_name)

    async def apply(self, req, service: Service):
        t0 = time.monotonic()
        status = "err"
        try:
            rsp = await service(req)
            status = rsp.status
            return rsp
        finally:
            if self._log.isEnabledFor(self._level):
                dst = req.ctx.get("dst")
                self._log.log(
                    self._level, "%s %s dst=%s -> %s (%.1fms)",
                    req.method, req.uri,
                    dst.path.show if dst is not None else "-",
                    status, (time.monotonic() - t0) * 1e3)


@register("logger", "io.l5d.http.debug")
@dataclass
class DebugLoggerConfig:
    """Log every request/response line to a python logger at
    ``level`` — the zero-dependency debugging tap."""

    level: str = "DEBUG"       # DEBUG | INFO | WARNING
    logger: str = "linkerd_tpu.reqlog"

    def mk(self) -> Filter:
        level = logging.getLevelName(self.level.upper())
        if not isinstance(level, int):
            raise ConfigError(f"io.l5d.http.debug: bad level {self.level!r}")
        return DebugLogger(level, self.logger)


def mk_file_emit(path: str):
    """Off-event-loop line sink: (emit, close). One QueueListener thread
    drains a SimpleQueue into a FileHandler; the logger is standalone
    (NOT registered with logging.getLogger — registry entries live
    forever and id()-reuse could attach two handlers to one logger).
    Shared by the access log and the file request-logger."""
    import queue as _queue
    from logging.handlers import QueueHandler, QueueListener

    q: _queue.SimpleQueue = _queue.SimpleQueue()
    logger = logging.Logger("linkerd_tpu.filesink", logging.INFO)
    logger.addHandler(QueueHandler(q))
    fh = logging.FileHandler(path)
    fh.setFormatter(logging.Formatter("%(message)s"))
    listener = QueueListener(q, fh)
    listener.start()

    def close() -> None:
        listener.stop()
        fh.close()

    return logger.info, close


class FileLogger(Filter):
    """JSON-lines request log, written off the event loop."""

    def __init__(self, path: str):
        self._emit, self._close = mk_file_emit(path)

    def close(self) -> None:
        self._close()

    async def apply(self, req, service: Service):
        t0 = time.monotonic()
        status: Optional[int] = None
        try:
            rsp = await service(req)
            status = rsp.status
            return rsp
        finally:
            dst = req.ctx.get("dst")
            self._emit(json.dumps({
                "ts": round(time.time(), 3),
                "method": req.method,
                "uri": req.uri,
                "dst": dst.path.show if dst is not None else None,
                "status": status,
                "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
            }))


@register("logger", "io.l5d.http.file")
@dataclass
class FileLoggerConfig:
    """Apache-combined-format access log appended to ``path``."""

    path: str = ""

    def mk(self) -> Filter:
        if not self.path:
            raise ConfigError("io.l5d.http.file logger needs path")
        return FileLogger(self.path)
