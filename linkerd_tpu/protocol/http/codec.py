"""HTTP/1.1 wire codec over asyncio streams.

Reads/writes request and response messages with Content-Length and chunked
transfer-encoding bodies, enforcing max header/body sizes (ref: the
reference's maxHeadersKB / maxRequestKB / maxResponseKB config,
HttpConfig.scala:192-249, and the FramingFilter's dup-Content-Length
rejection, linkerd/protocol/http/.../FramingFilter.scala).
"""

from __future__ import annotations

import asyncio
from typing import Optional, Tuple

from linkerd_tpu.protocol.http.message import Headers, Request, Response

try:  # native head parser fast path (falls back to pure python)
    from linkerd_tpu import native as _native
except ImportError:  # pragma: no cover
    _native = None

MAX_LINE = 8 * 1024
MAX_HEADERS_BYTES = 64 * 1024
MAX_BODY = 8 * 1024 * 1024


class HttpCodecError(Exception):
    """Malformed message framing; maps to 400 (request) / 502 (response)."""


class BodyTooLarge(HttpCodecError):
    pass


async def _read_line(reader: asyncio.StreamReader) -> bytes:
    try:
        line = await reader.readuntil(b"\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            raise EOFError("connection closed") from None
        raise HttpCodecError("truncated line") from None
    except asyncio.LimitOverrunError:
        raise HttpCodecError("line too long") from None
    line = line[:-2]
    if len(line) > MAX_LINE:
        raise HttpCodecError("line too long")
    return line


def _parse_request_line(line: bytes) -> Tuple[str, str, str]:
    """One shared implementation for the streaming and block paths
    (CRLF already stripped)."""
    if len(line) > MAX_LINE:
        raise HttpCodecError("line too long")
    if b"\n" in line or b"\r" in line:
        # a bare LF/CR inside a line is a parser-differential smuggling
        # vector (lines are CRLF-delimited; embedded ones re-serialize
        # as new lines downstream)
        raise HttpCodecError("bare CR/LF in request line")
    parts = line.decode("latin-1").split(" ")
    if len(parts) != 3:
        raise HttpCodecError(f"malformed request line: {line[:64]!r}")
    method, uri, version = parts
    if version not in ("HTTP/1.1", "HTTP/1.0"):
        raise HttpCodecError(f"unsupported version: {version!r}")
    return method, uri, version


def _parse_header_line(line: bytes, headers: Headers, total: int) -> int:
    """Validate + add one header line (CRLF stripped); returns the new
    running byte total. Shared by streaming and block paths."""
    if len(line) > MAX_LINE:
        raise HttpCodecError("line too long")
    if b"\n" in line or b"\r" in line:
        raise HttpCodecError("bare CR/LF in header line")
    total += len(line)
    if total > MAX_HEADERS_BYTES:
        raise HttpCodecError("headers too large")
    if line[0:1] in (b" ", b"\t"):
        raise HttpCodecError("obsolete header folding rejected")
    idx = line.find(b":")
    if idx <= 0:
        raise HttpCodecError(f"malformed header line: {line[:64]!r}")
    name = line[:idx].decode("latin-1").strip()
    value = line[idx + 1:].decode("latin-1").strip()
    if not name or any(c in name for c in " \t"):
        raise HttpCodecError(f"malformed header name: {name!r}")
    headers.add(name, value)
    return total


async def _read_headers(reader: asyncio.StreamReader) -> Headers:
    headers = Headers()
    total = 0
    while True:
        line = await _read_line(reader)
        if not line:
            return headers
        total = _parse_header_line(line, headers, total)


def _body_framing(headers: Headers) -> Tuple[str, int]:
    """Returns ("chunked", 0) | ("length", n) | ("none", 0).

    Duplicate, differing Content-Length headers are rejected outright
    (request-smuggling guard — ref: FramingFilter semantics).
    """
    te = [v.lower() for v in headers.get_all("transfer-encoding")]
    if te:
        if any("chunked" in v for v in te):
            if headers.get_all("content-length"):
                raise HttpCodecError("both Transfer-Encoding and Content-Length")
            return ("chunked", 0)
        raise HttpCodecError(f"unsupported transfer-encoding: {te}")
    cls = headers.get_all("content-length")
    if not cls:
        return ("none", 0)
    vals = set(cls)
    if len(vals) > 1:
        raise HttpCodecError("conflicting Content-Length headers")
    try:
        n = int(next(iter(vals)))
    except ValueError:
        raise HttpCodecError(f"bad Content-Length: {cls[0]!r}") from None
    if n < 0:
        raise HttpCodecError("negative Content-Length")
    return ("length", n)


async def _read_body(reader: asyncio.StreamReader, framing: Tuple[str, int],
                     max_body: int = MAX_BODY) -> bytes:
    kind, n = framing
    if kind == "none":
        return b""
    if kind == "length":
        if n > max_body:
            raise BodyTooLarge(f"body {n} > {max_body}")
        try:
            return await reader.readexactly(n)
        except asyncio.IncompleteReadError:
            raise HttpCodecError("truncated body") from None
    # chunked
    chunks = []
    total = 0
    while True:
        size_line = await _read_line(reader)
        # chunk extensions after ';' are ignored
        size_s = size_line.split(b";", 1)[0].strip()
        try:
            size = int(size_s, 16)
        except ValueError:
            raise HttpCodecError(f"bad chunk size: {size_line[:32]!r}") from None
        if size == 0:
            # trailers (ignored) until blank line
            while True:
                t = await _read_line(reader)
                if not t:
                    break
            return b"".join(chunks)
        total += size
        if total > max_body:
            raise BodyTooLarge(f"chunked body > {max_body}")
        try:
            chunks.append(await reader.readexactly(size))
            crlf = await reader.readexactly(2)
        except asyncio.IncompleteReadError:
            raise HttpCodecError("truncated chunk") from None
        if crlf != b"\r\n":
            raise HttpCodecError("bad chunk terminator")


def _parse_head_bytes(head: bytes) -> Tuple[str, str, str, Headers]:
    """Pure-Python head parsing over an in-memory block; same rules as
    the streaming path via the shared line parsers."""
    lines = head.split(b"\r\n")
    # head ends with CRLFCRLF -> two trailing empties
    while lines and not lines[-1]:
        lines.pop()
    if not lines:
        raise HttpCodecError("empty request head")
    method, uri, version = _parse_request_line(lines[0])
    headers = Headers()
    total = 0
    for line in lines[1:]:
        total = _parse_header_line(line, headers, total)
    return method, uri, version, headers


async def read_request(reader: asyncio.StreamReader,
                       max_body: int = MAX_BODY) -> Request:
    """Read one request; raises EOFError on clean close before a request.

    Fast path: the whole head is block-read (one readuntil) and parsed by
    the native C parser (linkerd_tpu.native); the line-by-line pure-Python
    path handles native-unavailable and anything the strict native parser
    refuses, so error behavior is unchanged.
    """
    native = _native  # read once: the global may be toggled at runtime
    if native is not None and native.available():
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.IncompleteReadError as e:
            if not e.partial:
                raise EOFError("connection closed") from None
            raise HttpCodecError("truncated head") from None
        except asyncio.LimitOverrunError:
            raise HttpCodecError("head too large") from None
        if len(head) > MAX_HEADERS_BYTES + MAX_LINE:
            raise HttpCodecError("head too large")
        parsed = native.parse_http1_head(head)
        if parsed is not None:
            method, uri, version, header_list = parsed
            if version not in ("HTTP/1.1", "HTTP/1.0"):
                raise HttpCodecError(f"unsupported version: {version!r}")
            # enforce the pure-Python path's running-total cap so both
            # parsers accept exactly the same inputs (the block check
            # above allows up to MAX_HEADERS_BYTES + MAX_LINE)
            first_eol = head.find(b"\r\n")
            total = len(head) - first_eol - 4 - 2 * len(header_list)
            if total > MAX_HEADERS_BYTES:
                raise HttpCodecError("headers too large")
            headers = Headers(header_list)
        else:
            # native refused (stricter caps or malformed): re-parse the
            # already-consumed head with the pure-Python rules so accept/
            # reject behavior and error text match the fallback path
            method, uri, version, headers = _parse_head_bytes(head)
        body = await _read_body(reader, _body_framing(headers), max_body)
        return Request(method=method, uri=uri, version=version,
                       headers=headers, body=body)
    line = await _read_line(reader)
    method, uri, version = _parse_request_line(line)
    headers = await _read_headers(reader)
    body = await _read_body(reader, _body_framing(headers), max_body)
    return Request(method=method, uri=uri, version=version,
                   headers=headers, body=body)


async def read_response(reader: asyncio.StreamReader, request_method: str = "GET",
                        max_body: int = MAX_BODY) -> Response:
    line = await _read_line(reader)
    parts = line.decode("latin-1").split(" ", 2)
    if len(parts) < 2 or not parts[0].startswith("HTTP/"):
        raise HttpCodecError(f"malformed status line: {line[:64]!r}")
    version = parts[0]
    try:
        status = int(parts[1])
    except ValueError:
        raise HttpCodecError(f"bad status: {parts[1]!r}") from None
    reason = parts[2] if len(parts) > 2 else ""
    headers = await _read_headers(reader)
    if request_method == "HEAD" or status in (204, 304) \
            or 100 <= status < 200 \
            or (request_method == "CONNECT" and 200 <= status < 300):
        # a 2xx to CONNECT switches to tunnel mode: what follows the
        # header block is tunnel payload, never a response body
        body = b""
    else:
        framing = _body_framing(headers)
        if framing[0] == "none" and headers.get("content-length") is None:
            # No framing info: body runs to EOF (HTTP/1.0 style)
            conn = (headers.get("connection") or "").lower()
            if "close" in conn or version == "HTTP/1.0":
                body = await reader.read(max_body + 1)
                if len(body) > max_body:
                    raise BodyTooLarge("eof-delimited body too large")
            else:
                body = b""
        else:
            body = await _read_body(reader, framing, max_body)
    return Response(status=status, reason=reason, version=version,
                    headers=headers, body=body)


def _ensure_length(headers: Headers, body: bytes) -> None:
    if headers.get("transfer-encoding") is None and (
            body or headers.get("content-length") is None):
        headers.set("Content-Length", str(len(body)))


def write_request(writer: asyncio.StreamWriter, req: Request) -> None:
    _ensure_length(req.headers, req.body)
    lines = [f"{req.method} {req.uri} {req.version}\r\n"]
    lines += [f"{k}: {v}\r\n" for k, v in req.headers]
    lines.append("\r\n")
    writer.write("".join(lines).encode("latin-1") + req.body)


def write_response(writer: asyncio.StreamWriter, rsp: Response) -> None:
    if rsp.status not in (204, 304) and not (100 <= rsp.status < 200):
        _ensure_length(rsp.headers, rsp.body)
    lines = [f"{rsp.version} {rsp.status} {rsp.reason}\r\n"]
    lines += [f"{k}: {v}\r\n" for k, v in rsp.headers]
    lines.append("\r\n")
    writer.write("".join(lines).encode("latin-1") + rsp.body)


async def write_streaming_response(writer: asyncio.StreamWriter,
                                   rsp: Response) -> None:
    """Write a chunked response from ``rsp.body_stream`` (an async iterator
    of bytes), draining after every chunk so watchers see updates live."""
    rsp.headers.remove("content-length")
    rsp.headers.set("Transfer-Encoding", "chunked")
    lines = [f"{rsp.version} {rsp.status} {rsp.reason}\r\n"]
    lines += [f"{k}: {v}\r\n" for k, v in rsp.headers]
    lines.append("\r\n")
    writer.write("".join(lines).encode("latin-1"))
    await writer.drain()
    async for chunk in rsp.body_stream:
        if not chunk:
            continue
        writer.write(f"{len(chunk):x}\r\n".encode("latin-1")
                     + chunk + b"\r\n")
        await writer.drain()
    writer.write(b"0\r\n\r\n")
    await writer.drain()
