"""HTTP/1.1 message model.

Order-preserving, case-insensitive multimap headers (proxies must preserve
header order and repetition — ref: the reference routes finagle-http
messages through header-rewriting filters like AddForwardedHeader.scala,
StripHopByHopHeadersFilter.scala).
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Tuple


class Headers:
    """Ordered, case-insensitive multimap of header fields."""

    __slots__ = ("_items",)

    def __init__(self, items: Iterable[Tuple[str, str]] = ()):
        self._items: List[Tuple[str, str]] = [(k, v) for k, v in items]

    # -- reads ------------------------------------------------------------
    def get(self, name: str, default: Optional[str] = None) -> Optional[str]:
        ln = name.lower()
        for k, v in self._items:
            if k.lower() == ln:
                return v
        return default

    def get_all(self, name: str) -> List[str]:
        ln = name.lower()
        return [v for k, v in self._items if k.lower() == ln]

    def contains(self, name: str) -> bool:
        return self.get(name) is not None

    def items(self) -> List[Tuple[str, str]]:
        return list(self._items)

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    # -- writes -----------------------------------------------------------
    def add(self, name: str, value: str) -> None:
        self._items.append((name, str(value)))

    def set(self, name: str, value: str) -> None:
        self.remove(name)
        self.add(name, value)

    def remove(self, name: str) -> int:
        ln = name.lower()
        before = len(self._items)
        self._items = [(k, v) for k, v in self._items if k.lower() != ln]
        return before - len(self._items)

    def copy(self) -> "Headers":
        return Headers(self._items)

    def __repr__(self) -> str:
        return f"Headers({self._items!r})"


class Request:
    __slots__ = ("method", "uri", "version", "headers", "body", "ctx")

    def __init__(self, method: str = "GET", uri: str = "/",
                 version: str = "HTTP/1.1",
                 headers: Optional[Headers] = None,
                 body: bytes = b""):
        self.method = method
        self.uri = uri
        self.version = version
        self.headers = headers if headers is not None else Headers()
        self.body = body
        # Per-request context (ref: finagle Contexts / DstPathCtx etc.);
        # carries Dst, trace info, response class through the stack.
        self.ctx: Dict[str, object] = {}

    @property
    def host(self) -> Optional[str]:
        return self.headers.get("host")

    @property
    def path(self) -> str:
        """URI path without query string."""
        uri = self.uri
        # absolute-form (proxy) URIs: strip scheme://authority
        if uri.startswith("http://") or uri.startswith("https://"):
            rest = uri.split("://", 1)[1]
            slash = rest.find("/")
            uri = rest[slash:] if slash >= 0 else "/"
        q = uri.find("?")
        return uri[:q] if q >= 0 else uri

    def __repr__(self) -> str:
        return f"Request({self.method} {self.uri})"


class Response:
    __slots__ = ("status", "reason", "version", "headers", "body",
                 "body_stream", "ctx")

    def __init__(self, status: int = 200, reason: Optional[str] = None,
                 version: str = "HTTP/1.1",
                 headers: Optional[Headers] = None,
                 body: bytes = b"",
                 body_stream: Optional[object] = None):
        self.status = status
        self.reason = reason if reason is not None else REASONS.get(status, "Unknown")
        self.version = version
        self.headers = headers if headers is not None else Headers()
        self.body = body
        # async iterator of bytes -> Transfer-Encoding: chunked streaming
        # (the watch=true control-API path, ref: HttpControlService
        # streaming responses). When set, ``body`` is ignored.
        self.body_stream = body_stream
        self.ctx: Dict[str, object] = {}

    def __repr__(self) -> str:
        return f"Response({self.status})"


REASONS = {
    100: "Continue", 101: "Switching Protocols",
    200: "OK", 201: "Created", 202: "Accepted", 204: "No Content",
    206: "Partial Content", 301: "Moved Permanently", 302: "Found",
    303: "See Other", 304: "Not Modified", 307: "Temporary Redirect",
    308: "Permanent Redirect",
    400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
    404: "Not Found", 405: "Method Not Allowed", 408: "Request Timeout",
    409: "Conflict", 410: "Gone", 411: "Length Required",
    413: "Payload Too Large", 414: "URI Too Long", 429: "Too Many Requests",
    431: "Request Header Fields Too Large",
    500: "Internal Server Error", 501: "Not Implemented", 502: "Bad Gateway",
    503: "Service Unavailable", 504: "Gateway Timeout",
    505: "HTTP Version Not Supported",
}
