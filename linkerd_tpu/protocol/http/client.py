"""HTTP/1.1 client with keep-alive connection pooling.

Reference parity: the client-side stack's connection pool
(ref: hostConnectionPool config, ClientConfig.scala; finagle's
WatermarkPool/CachingPool). One pool per concrete endpoint; idle
connections are reused FIFO, created on demand up to ``max_connections``,
and reaped after ``idle_ttl`` seconds.
"""

from __future__ import annotations

import asyncio
import time
from typing import List, Optional, Tuple

from linkerd_tpu.protocol.http import codec
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.router.service import Service, Status


class _Conn:
    __slots__ = ("reader", "writer", "last_used")

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.last_used = time.monotonic()

    @property
    def closed(self) -> bool:
        return self.writer.is_closing()

    def close(self) -> None:
        try:
            self.writer.close()
        except (OSError, RuntimeError):  # transport already detached
            pass


class HttpClient(Service[Request, Response]):
    """A pooled HTTP/1.1 client Service for one host:port endpoint."""

    def __init__(self, host: str, port: int,
                 max_connections: int = 64,
                 idle_ttl: float = 60.0,
                 connect_timeout: float = 3.0,
                 max_body: int = codec.MAX_BODY,
                 ssl_context=None,
                 server_hostname: Optional[str] = None):
        self.host = host
        self.port = port
        self.max_connections = max_connections
        self.idle_ttl = idle_ttl
        self.connect_timeout = connect_timeout
        self.max_body = max_body
        # TLS origination (ref: TlsClientConfig.scala; per-client tls in
        # ClientConfig.scala). server_hostname carries the (possibly
        # PathMatcher-substituted) commonName for SNI + verification.
        self.ssl_context = ssl_context
        self.server_hostname = server_hostname
        self._idle: List[_Conn] = []
        self._n_open = 0
        self._waiters: asyncio.Queue = asyncio.Queue()
        self._sem = asyncio.Semaphore(max_connections)
        self._closed = False
        # live instrumentation for balancers (pending = in-flight requests)
        self.pending = 0

    @property
    def status(self) -> Status:
        return Status.CLOSED if self._closed else Status.OPEN

    async def _checkout(self) -> _Conn:
        now = time.monotonic()
        while self._idle:
            conn = self._idle.pop()
            if conn.closed or now - conn.last_used > self.idle_ttl:
                conn.close()
                self._n_open -= 1
                self._sem.release()
                continue
            return conn
        await self._sem.acquire()
        try:
            kw = {}
            if self.ssl_context is not None:
                kw["ssl"] = self.ssl_context
                if self.server_hostname is not None:
                    kw["server_hostname"] = self.server_hostname
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port, **kw),
                self.connect_timeout)
        except Exception:
            self._sem.release()
            raise
        self._n_open += 1
        return _Conn(reader, writer)

    def _checkin(self, conn: _Conn, reusable: bool) -> None:
        if reusable and not self._closed and not conn.closed:
            conn.last_used = time.monotonic()
            self._idle.append(conn)
        else:
            conn.close()
            self._n_open -= 1
            self._sem.release()

    async def __call__(self, req: Request) -> Response:
        if self._closed:
            raise ConnectionError(f"client {self.host}:{self.port} closed")
        if req.headers.get("host") is None:
            req.headers.set("Host", f"{self.host}:{self.port}")
        conn = await self._checkout()
        if self._closed:
            # close() ran while we were checking out/connecting: the
            # entry guard above is stale. Surrender the connection
            # instead of dispatching on a closed client (the fresh
            # socket would otherwise outlive close() forever).
            self._checkin(conn, reusable=False)
            raise ConnectionError(f"client {self.host}:{self.port} closed")
        self.pending += 1
        try:
            codec.write_request(conn.writer, req)
            await conn.writer.drain()
            rsp = await codec.read_response(conn.reader, req.method,
                                            self.max_body)
        except BaseException:
            self._checkin(conn, reusable=False)
            self.pending -= 1
            raise
        self.pending -= 1
        if rsp.status == 101 or (req.method == "CONNECT"
                                 and 200 <= rsp.status < 300):
            # protocol switch: the connection IS the tunnel now. Hand
            # the raw streams to the server edge for byte relay; the
            # conn never returns to the pool (tunnel_done releases its
            # slot when the relay ends).
            rsp.ctx["tunnel"] = (conn.reader, conn.writer)
            rsp.ctx["tunnel_done"] = lambda: self._checkin(
                conn, reusable=False)
            return rsp
        reusable = (
            (rsp.headers.get("connection") or "").lower() != "close"
            and (req.headers.get("connection") or "").lower() != "close"
            and rsp.version == "HTTP/1.1"
        )
        self._checkin(conn, reusable)
        return rsp

    async def close(self) -> None:
        self._closed = True
        for conn in self._idle:
            conn.close()
        self._idle.clear()
