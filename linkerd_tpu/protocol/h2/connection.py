"""HTTP/2 connection engine: multiplexed streams on one asyncio transport.

Reference parity: finagle/h2/.../netty4/Netty4DispatcherBase.scala,
Netty4ClientDispatcher.scala, Netty4ServerDispatcher.scala (stream-id
allocation, GOAWAY, ping) and Netty4StreamTransport.scala:53-70 (the RFC
7540 §5.1 stream state machine). One engine class serves both roles; the
client allocates odd stream ids, the server even (we never push).

Flow control: the peer's send rate into us is bounded by the windows we
advertise; credit returns when the application release()s DataFrames
(the reference's Stream.release() semantics, Stream.scala:20). Our send
rate is bounded by peer windows; senders block on a condition until
WINDOW_UPDATE arrives.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from linkerd_tpu.core.tasks import monitor, spawn
from linkerd_tpu.protocol.h2 import frames, hpack
from linkerd_tpu.protocol.h2.frames import (
    CONNECTION_PREFACE, DEFAULT_INITIAL_WINDOW, DEFAULT_MAX_FRAME_SIZE,
    H2ProtocolError,
)
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
from linkerd_tpu.protocol.h2.stream import (
    DataFrame, H2Stream, StreamReset, Trailers,
)

log = logging.getLogger(__name__)

# We advertise a 1MB stream window (SETTINGS) and grow the connection
# window to 4MB — long-haul streams shouldn't stall on the default 64KB
# (ref: flow-control window params, finagle/h2/.../param.scala).
LOCAL_INITIAL_WINDOW = 1 << 20
LOCAL_CONN_WINDOW = 4 << 20
MAX_HEADER_LIST = 64 * 1024
# Deferred-credit thresholds: WINDOW_UPDATEs are batched until this much
# credit is pending, collapsing the per-DATA-frame update chatter (2 tiny
# frames per received chunk) into one update per ~half window.
CONN_CREDIT_THRESHOLD = LOCAL_CONN_WINDOW // 4
STREAM_CREDIT_THRESHOLD = LOCAL_INITIAL_WINDOW // 2
# Transport write buffer size above which senders yield to drain().
WRITE_HIGH_WATER = 256 * 1024
READ_CHUNK = 1 << 18


class _StreamState:
    __slots__ = ("id", "recv_stream", "send_window", "recv_window",
                 "send_closed", "recv_closed", "got_headers",
                 "response_fut", "pump_task", "reset_sent",
                 "pending_credit")

    def __init__(self, sid: int, send_window: int, recv_window: int):
        self.id = sid
        self.recv_stream = H2Stream()
        self.send_window = send_window
        self.recv_window = recv_window
        self.send_closed = False
        self.recv_closed = False
        self.got_headers = False      # first HEADERS seen (vs trailers)
        self.response_fut: Optional[asyncio.Future] = None
        self.pump_task: Optional[asyncio.Task] = None
        self.reset_sent = False
        self.pending_credit = 0       # released but not yet WINDOW_UPDATEd


class H2Connection:
    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, is_client: bool,
                 handler: Optional[Callable[[H2Request],
                                            Awaitable[H2Response]]] = None,
                 huffman: bool = False,
                 initial_window: int = LOCAL_INITIAL_WINDOW,
                 max_frame: int = DEFAULT_MAX_FRAME_SIZE,
                 max_header_list: int = MAX_HEADER_LIST,
                 max_concurrent_streams: Optional[int] = None,
                 preface_consumed: bool = False,
                 initial_data: bytes = b"",
                 observer=None):
        self._reader = reader
        self._writer = writer
        self.is_client = is_client
        self._handler = handler
        # stream sentinel (server side): an H2FrameObserver fed every
        # DATA / WINDOW_UPDATE / RST so long-lived streams are scored
        # mid-flight (linkerd_tpu/streams); None = no stream scoring
        self._observer = observer.bind(self) if observer is not None \
            else None
        # server side: the listener already consumed the client preface
        # while sniffing prior-knowledge h2c vs an h1 Upgrade
        # (ref: ServerUpgradeHandler.scala channelRead); bytes it
        # over-read past the preface seed the frame loop
        self._preface_consumed = preface_consumed
        self._initial_data = initial_data
        # advertised SETTINGS (ref: finagle/h2 param.scala — configurable
        # per router via initialStreamWindowBytes/maxFrameBytes/
        # maxHeaderListBytes/maxConcurrentStreamsPerConnection)
        self._local_initial_window = initial_window
        self._local_max_frame = max_frame
        self._max_header_list = max_header_list
        self._max_concurrent = max_concurrent_streams
        self._stream_credit_threshold = max(1, initial_window // 2)
        # the connection window must dominate the stream window or a
        # single long-haul stream stalls below its advertised window
        self._local_conn_window = max(LOCAL_CONN_WINDOW, 4 * initial_window)
        self._conn_credit_threshold = max(1, self._local_conn_window // 4)
        self._encoder = hpack.Encoder(huffman=huffman)
        self._decoder = hpack.Decoder()
        self._streams: Dict[int, _StreamState] = {}
        self._next_stream_id = 1 if is_client else 2
        self._send_window = DEFAULT_INITIAL_WINDOW
        self._recv_window = DEFAULT_INITIAL_WINDOW
        self._peer_initial_window = DEFAULT_INITIAL_WINDOW
        self._peer_max_frame = DEFAULT_MAX_FRAME_SIZE
        self._window_cond = asyncio.Condition()
        self._read_task: Optional[asyncio.Task] = None
        self._closed = False
        self.goaway_received = False
        self._last_peer_stream = 0
        self._settings_acked = asyncio.Event()
        self._handler_tasks: set = set()
        self._refused: set = set()  # recently REFUSED_STREAM ids
        self._peer_max_concurrent: Optional[int] = None
        self._slot_waiters: List[asyncio.Future] = []
        # contiguous header-block assembly state
        self._hdr_accum: Optional[Tuple[int, int, bytearray]] = None
        # write coalescing: frames written within one event-loop iteration
        # are batched into a single transport write (one send() syscall)
        self._wbuf = bytearray()
        self._flush_scheduled = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._pending_conn_credit = 0

    # ── coalesced writes ─────────────────────────────────────────────────
    def _write(self, data: bytes) -> None:
        self._wbuf += data
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._do_flush)

    def _do_flush(self) -> None:
        self._flush_scheduled = False
        if self._wbuf:
            data, self._wbuf = self._wbuf, bytearray()
            try:
                self._writer.write(data)
            except (OSError, RuntimeError):  # transport torn down
                pass

    async def _drain(self) -> None:
        """Flush now; apply backpressure only when the transport buffer is
        actually backed up (plain drain() is an unconditional await)."""
        self._do_flush()
        try:
            if (self._writer.transport.get_write_buffer_size()
                    > WRITE_HIGH_WATER):
                await self._writer.drain()
        except (OSError, RuntimeError):  # transport torn down mid-drain
            pass

    # ── lifecycle ────────────────────────────────────────────────────────
    async def start(self) -> "H2Connection":
        self._loop = asyncio.get_running_loop()
        settings = [
            (frames.SETTINGS_INITIAL_WINDOW_SIZE,
             self._local_initial_window),
            (frames.SETTINGS_MAX_FRAME_SIZE, self._local_max_frame),
            (frames.SETTINGS_MAX_HEADER_LIST_SIZE, self._max_header_list),
        ]
        if self._max_concurrent is not None:
            settings.append((frames.SETTINGS_MAX_CONCURRENT_STREAMS,
                             self._max_concurrent))
        if self.is_client:
            self._write(CONNECTION_PREFACE)
            settings.append((frames.SETTINGS_ENABLE_PUSH, 0))
        elif not self._preface_consumed:
            preface = await self._reader.readexactly(len(CONNECTION_PREFACE))
            if preface != CONNECTION_PREFACE:
                raise H2ProtocolError(frames.PROTOCOL_ERROR, "bad preface")
        self._write(frames.pack_settings(settings))
        self._write(frames.pack_window_update(
            0, self._local_conn_window - DEFAULT_INITIAL_WINDOW))
        self._recv_window = self._local_conn_window
        await self._drain()
        # a crashed read loop must be loud: it looks exactly like a hung
        # peer from the application side
        self._read_task = monitor(
            self._loop.create_task(self._read_loop()), what="h2-read-loop")
        return self

    @property
    def is_closed(self) -> bool:
        return self._closed

    @property
    def active_streams(self) -> int:
        return len(self._streams)

    async def close(self, code: int = frames.NO_ERROR) -> None:
        first = not self._closed
        self._closed = True
        if first:
            try:
                self._wbuf += frames.pack_goaway(self._last_peer_stream, code)
                self._do_flush()
                await self._drain()
            except (OSError, RuntimeError):  # peer already gone
                pass
        if self._read_task is not None and not self._read_task.done():
            self._read_task.cancel()
            try:
                await self._read_task
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001 — already closing, but
                log.debug("h2 read loop exit on close: %r", e)  # be loud-ish
        self._fail_all(StreamReset(frames.CANCEL, "connection closed"))
        if self._observer is not None:
            self._observer.close()
        for t in list(self._handler_tasks):
            t.cancel()
        # Always close the transport, even if the read loop already marked
        # us closed on EOF — a still-attached transport wedges
        # Server.wait_closed().
        try:
            self._writer.close()
        except (OSError, RuntimeError):  # transport already detached
            pass

    def _fail_all(self, err: StreamReset) -> None:
        for st in list(self._streams.values()):
            st.recv_stream.reset(err.error_code, str(err))
            if st.response_fut is not None and not st.response_fut.done():
                st.response_fut.set_exception(
                    StreamReset(err.error_code, str(err)))
            if st.pump_task is not None:
                st.pump_task.cancel()
        self._streams.clear()
        for w in self._slot_waiters:
            if not w.done():
                w.set_result(None)
        self._slot_waiters.clear()
        # wake any senders blocked on flow-control so they observe closure
        spawn(self._notify_windows(), what="h2-notify-windows-close")

    # ── client API ───────────────────────────────────────────────────────
    async def request(self, req: H2Request) -> H2Response:
        """Dispatch one request; resolves when response HEADERS arrive.

        The response body streams through rsp.stream afterwards
        (ref: Netty4ClientDispatcher request/response offer).
        """
        assert self.is_client
        if self._closed or self.goaway_received:
            raise ConnectionError("h2 connection closed/goaway")
        # honor the peer's advertised concurrent-stream limit: wait for a
        # slot instead of provoking REFUSED_STREAM failures
        while (self._peer_max_concurrent is not None
               and len(self._streams) >= self._peer_max_concurrent):
            waiter = asyncio.get_running_loop().create_future()
            self._slot_waiters.append(waiter)
            await waiter
            if self._closed or self.goaway_received:
                raise ConnectionError("h2 connection closed/goaway")
        sid = self._next_stream_id
        self._next_stream_id += 2
        st = _StreamState(sid, self._peer_initial_window,
                          self._local_initial_window)
        st.response_fut = asyncio.get_running_loop().create_future()
        self._streams[sid] = st

        body = _poll_const_body(req.stream)
        if body is not None:
            data, trailers = body
            if trailers is None and not data:
                self._send_headers(sid, req.to_header_list(), end_stream=True)
            else:
                self._send_headers(sid, req.to_header_list(),
                                   end_stream=False)
                if data:
                    await self._send_data(st, data, eos=trailers is None)
                if trailers is not None:
                    self._send_headers(sid, trailers, end_stream=True)
            st.send_closed = True
            await self._drain()
        else:
            self._send_headers(sid, req.to_header_list(), end_stream=False)
            await self._drain()
            st.pump_task = asyncio.get_running_loop().create_task(
                self._pump_out(st, req.stream))
        try:
            rsp: H2Response = await st.response_fut
        except BaseException:
            if st.pump_task is not None:
                st.pump_task.cancel()
            if not st.reset_sent and sid in self._streams:
                self._rst(st, frames.CANCEL)
            raise
        return rsp

    # ── internals: sending ───────────────────────────────────────────────
    def _send_headers(self, sid: int, header_list: List[Tuple[str, str]],
                      end_stream: bool) -> None:
        # encode + write must not interleave with another encode (shared
        # HPACK dynamic table); both are synchronous here, which is the
        # serialization (single event loop, no await between them).
        block = self._encoder.encode(header_list)
        flags = frames.FLAG_END_HEADERS | (
            frames.FLAG_END_STREAM if end_stream else 0)
        max_frag = self._peer_max_frame
        if len(block) <= max_frag:
            self._write(frames.pack_frame(
                frames.HEADERS, flags, sid, block))
        else:
            first, rest = block[:max_frag], block[max_frag:]
            self._write(frames.pack_frame(
                frames.HEADERS,
                flags & ~frames.FLAG_END_HEADERS, sid, first))
            while rest:
                frag, rest = rest[:max_frag], rest[max_frag:]
                cflags = frames.FLAG_END_HEADERS if not rest else 0
                self._write(frames.pack_frame(
                    frames.CONTINUATION, cflags, sid, frag))

    async def _pump_out(self, st: _StreamState, stream: H2Stream) -> None:
        """Copy an app stream into DATA/trailer frames w/ flow control."""
        try:
            while not stream.at_end:
                frame = await stream.read()
                if isinstance(frame, Trailers):
                    self._send_headers(st.id, frame.headers, end_stream=True)
                    st.send_closed = True
                    await self._drain()
                    break
                await self._send_data(st, frame.data, frame.eos)
                frame.release()
                if frame.eos:
                    st.send_closed = True
        except StreamReset as e:
            if not st.reset_sent:
                self._rst(st, e.error_code)
            # peer RST'd an active stream: the producer must see it too
            stream.reset(e.error_code, "consumer gone")
        except (ConnectionError, asyncio.CancelledError):
            # consumer is gone (peer RST / connection teardown): reset the
            # app-side source so long-lived producers (e.g. gRPC watch
            # streams) observe the death instead of pumping into the void
            stream.reset(frames.CANCEL, "consumer gone")
        except Exception:  # noqa: BLE001
            log.exception("h2 outbound pump failed (stream %d)", st.id)
            if not st.reset_sent:
                self._rst(st, frames.INTERNAL_ERROR)
            stream.reset(frames.INTERNAL_ERROR, "pump failed")
        finally:
            self._maybe_gc(st)

    async def _send_data(self, st: _StreamState, data: bytes,
                         eos: bool) -> None:
        if eos and not data:
            # an empty END_STREAM DATA frame consumes no flow-control
            # credit, so it may be sent even when a window is negative
            # (peer shrank SETTINGS_INITIAL_WINDOW_SIZE, RFC 7540 §6.9.2)
            if st.reset_sent or st.id not in self._streams:
                raise StreamReset(frames.STREAM_CLOSED, "stream reset")
            self._write(frames.pack_frame(
                frames.DATA, frames.FLAG_END_STREAM, st.id, b""))
            await self._drain()
            return
        view = memoryview(data)
        offset = 0
        while offset < len(data):
            if self._closed:
                raise ConnectionError("connection closed")
            n = max(0, min(len(data) - offset, self._peer_max_frame,
                           self._send_window, st.send_window))
            if st.reset_sent or st.id not in self._streams:
                raise StreamReset(frames.STREAM_CLOSED, "stream reset")
            if n <= 0:
                async with self._window_cond:
                    await self._window_cond.wait()
                continue
            chunk = bytes(view[offset:offset + n])
            offset += n
            last = offset >= len(data)
            self._send_window -= n
            st.send_window -= n
            self._write(frames.pack_frame(
                frames.DATA,
                frames.FLAG_END_STREAM if (eos and last) else 0,
                st.id, chunk))
            await self._drain()
            if last:
                break

    def _rst(self, st: _StreamState, code: int) -> None:
        st.reset_sent = True
        self._wake_slot()
        if not self._closed:
            try:
                self._write(frames.pack_rst(st.id, code))
            except (OSError, RuntimeError):  # transport torn down
                pass
        st.recv_stream.reset(code)
        self._streams.pop(st.id, None)
        if self._observer is not None:
            self._observer.on_close(st.id)

    def shed_stream(self, sid: int,
                    code: int = frames.ENHANCE_YOUR_CALM) -> bool:
        """Mid-stream actuation entry point (stream sentinel): RST a
        live stream without touching the connection. Returns False when
        the stream is already gone."""
        st = self._streams.get(sid)
        if st is None or self._closed:
            return False
        if st.pump_task is not None:
            st.pump_task.cancel()
        if st.response_fut is not None and not st.response_fut.done():
            st.response_fut.set_exception(
                StreamReset(code, "stream shed"))
        self._rst(st, code)
        return True

    async def _notify_windows(self) -> None:
        async with self._window_cond:
            self._window_cond.notify_all()

    def _conn_credit(self, n: int) -> None:
        """Batch connection-level WINDOW_UPDATEs until a threshold of
        credit is pending (the stream-update twin lives in _on_data)."""
        self._recv_window += n
        self._pending_conn_credit += n
        if self._pending_conn_credit >= self._conn_credit_threshold:
            self._write(frames.pack_window_update(
                0, self._pending_conn_credit))
            self._pending_conn_credit = 0

    # ── internals: receiving ─────────────────────────────────────────────
    async def _read_loop(self) -> None:
        # Batched frame parsing: read whatever the transport has (many
        # frames arrive per wakeup under load) and walk complete frames in
        # the buffer — two readexactly() awaits per frame becomes one
        # read() per TCP burst.
        read = self._reader.read
        buf = bytearray(self._initial_data)
        self._initial_data = b""
        # seeded bytes must be processed BEFORE the first read: waiting
        # for more transport data while the peer's SETTINGS already sit
        # in the buffer would deadlock the handshake
        skip_read = bool(buf)
        FrameHeader = frames.FrameHeader
        CONTINUATION = frames.CONTINUATION
        try:
            while not self._closed:
                if skip_read:
                    skip_read = False
                else:
                    chunk = await read(READ_CHUNK)
                    if not chunk:
                        raise EOFError("connection closed by peer")
                    buf += chunk
                pos = 0
                n = len(buf)
                while n - pos >= 9:
                    length = (buf[pos] << 16) | (buf[pos + 1] << 8) | buf[pos + 2]
                    if length > self._local_max_frame + 1024:
                        raise H2ProtocolError(frames.FRAME_SIZE_ERROR,
                                              f"frame too large: {length}")
                    end = pos + 9 + length
                    if n < end:
                        break
                    ftype = buf[pos + 3]
                    fh = FrameHeader(
                        length, ftype, buf[pos + 4],
                        ((buf[pos + 5] << 24) | (buf[pos + 6] << 16)
                         | (buf[pos + 7] << 8) | buf[pos + 8]) & 0x7FFFFFFF)
                    payload = bytes(buf[pos + 9:end]) if length else b""
                    pos = end
                    # CONTINUATION contiguity (RFC 7540 §6.2)
                    if self._hdr_accum is not None and ftype != CONTINUATION:
                        raise H2ProtocolError(frames.PROTOCOL_ERROR,
                                              "expected CONTINUATION")
                    await self._dispatch(fh, payload)
                if pos:
                    del buf[:pos]
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, EOFError):
            self._closed = True
            self._fail_all(StreamReset(frames.CANCEL, "connection lost"))
            try:
                self._writer.close()
            except (OSError, RuntimeError):  # transport already detached
                pass
        except asyncio.CancelledError:
            raise
        except H2ProtocolError as e:
            log.warning("h2 protocol error: %s", e)
            self._closed = True
            try:
                self._write(frames.pack_goaway(
                    self._last_peer_stream, e.code))
                await self._drain()
                self._writer.close()
            except (OSError, RuntimeError):  # peer already gone
                pass
            self._fail_all(StreamReset(frames.PROTOCOL_ERROR, str(e)))
        except Exception:  # noqa: BLE001
            log.exception("h2 read loop crashed")
            self._closed = True  # l5d: ignore[await-atomicity] — monotonic teardown flag in an exclusive except arm; the loop test re-reads it every iteration and close() is idempotent
            self._fail_all(StreamReset(frames.INTERNAL_ERROR, "read loop"))

    async def _dispatch(self, fh: frames.FrameHeader, payload: bytes) -> None:
        t = fh.type
        if t == frames.DATA:
            await self._on_data(fh, payload)
        elif t == frames.HEADERS:
            payload = frames.strip_padding(fh.flags, payload)
            if fh.flags & frames.FLAG_PRIORITY:
                payload = payload[5:]
            if fh.flags & frames.FLAG_END_HEADERS:
                self._on_header_block(fh.stream_id, payload,
                                      bool(fh.flags & frames.FLAG_END_STREAM))
            else:
                self._hdr_accum = (fh.stream_id,
                                   fh.flags & frames.FLAG_END_STREAM,
                                   bytearray(payload))
        elif t == frames.CONTINUATION:
            if self._hdr_accum is None or self._hdr_accum[0] != fh.stream_id:
                raise H2ProtocolError(frames.PROTOCOL_ERROR,
                                      "unexpected CONTINUATION")
            sid, es_flag, buf = self._hdr_accum
            buf += payload
            if len(buf) > self._max_header_list * 2:
                raise H2ProtocolError(frames.ENHANCE_YOUR_CALM,
                                      "header block too large")
            if fh.flags & frames.FLAG_END_HEADERS:
                self._hdr_accum = None
                self._on_header_block(sid, bytes(buf), bool(es_flag))
        elif t == frames.SETTINGS:
            if fh.flags & frames.FLAG_ACK:
                self._settings_acked.set()
                return
            self._apply_settings(frames.unpack_settings(payload))
            self._write(frames.pack_settings([], ack=True))
        elif t == frames.WINDOW_UPDATE:
            if len(payload) != 4:
                raise H2ProtocolError(frames.FRAME_SIZE_ERROR, "bad WU size")
            inc = int.from_bytes(payload, "big") & 0x7FFFFFFF
            if inc == 0:
                raise H2ProtocolError(frames.PROTOCOL_ERROR, "WU of 0")
            if fh.stream_id == 0:
                self._send_window += inc
            else:
                st = self._streams.get(fh.stream_id)
                if st is not None:
                    st.send_window += inc
                    if self._observer is not None:
                        self._observer.on_frame(
                            st.id, 1, 0)  # FRAME_WINDOW_UPDATE
            await self._notify_windows()
        elif t == frames.RST_STREAM:
            code = int.from_bytes(payload[:4], "big")
            st = self._streams.pop(fh.stream_id, None)
            if st is not None and self._observer is not None:
                # a peer reset is the anomaly signal itself; fold it in
                # before the slot is retired
                self._observer.on_frame(st.id, 2, 0)  # FRAME_ANOMALY
                self._observer.on_close(st.id)
            if st is not None:
                st.reset_sent = True  # no further sends on this stream
                st.recv_stream.reset(code, f"peer RST ({code:#x})")
                if st.response_fut is not None and not st.response_fut.done():
                    st.response_fut.set_exception(StreamReset(code, "peer RST"))
                if st.pump_task is not None:
                    st.pump_task.cancel()
                # wake any sender parked on flow control for this stream
                await self._notify_windows()
        elif t == frames.PING:
            if not fh.flags & frames.FLAG_ACK:
                self._write(frames.pack_ping(payload[:8], ack=True))
        elif t == frames.GOAWAY:
            self.goaway_received = True
            last_sid = int.from_bytes(payload[:4], "big") & 0x7FFFFFFF
            # fail only streams the peer will never process
            for sid in list(self._streams):
                if self.is_client and sid > last_sid:
                    st = self._streams.pop(sid)
                    err = StreamReset(frames.REFUSED_STREAM, "goaway")
                    st.recv_stream.reset(err.error_code, str(err))
                    if st.response_fut is not None and not st.response_fut.done():
                        st.response_fut.set_exception(err)
        elif t in (frames.PRIORITY, frames.PUSH_PROMISE):
            if t == frames.PUSH_PROMISE:
                raise H2ProtocolError(frames.PROTOCOL_ERROR,
                                      "push not enabled")
        # unknown frame types are ignored (RFC 7540 §4.1)

    async def _on_data(self, fh: frames.FrameHeader, payload: bytes) -> None:
        data = frames.strip_padding(fh.flags, payload)
        flow = len(payload)  # padding counts toward flow control
        self._recv_window -= flow
        eos = bool(fh.flags & frames.FLAG_END_STREAM)
        st = self._streams.get(fh.stream_id)
        if st is None or st.recv_closed:
            # stream gone (e.g. reset); return the connection credit we
            # just consumed (local accounting AND the peer's view)
            if flow:
                self._conn_credit(flow)
            return
        st.recv_window -= flow
        if st.recv_window < 0 or self._recv_window < 0:
            if self._observer is not None:
                # flow-control violation is a stream anomaly (the
                # feature the sentinel keys hostile senders on)
                self._observer.on_frame(st.id, 2, 0)  # FRAME_ANOMALY
            raise H2ProtocolError(frames.FLOW_CONTROL_ERROR,
                                  "peer overran window")
        if self._observer is not None:
            self._observer.on_frame(st.id, 0, flow)  # FRAME_DATA
            if st.id not in self._streams:
                # the sentinel shed this stream mid-sample: return the
                # connection credit this frame consumed (it will never
                # be offered, so release() can't) and stop delivering
                if flow:
                    self._conn_credit(flow)
                return
        sid = st.id

        def credit(n: int, _sid: int = sid) -> None:
            # called from app-land release(); returns window to the peer.
            # Credit is batched (thresholded) rather than sent per frame.
            if self._closed:
                return
            try:
                self._conn_credit(n)
                stt = self._streams.get(_sid)
                if stt is not None and not stt.recv_closed:
                    stt.recv_window += n
                    stt.pending_credit += n
                    if stt.pending_credit >= self._stream_credit_threshold:
                        self._write(frames.pack_window_update(
                            _sid, stt.pending_credit))
                        stt.pending_credit = 0
            except Exception as e:  # noqa: BLE001 — app-land release()
                # must never throw into the consumer, but a failed
                # credit return wedges the peer's send window: say so
                log.debug("h2 credit return failed (stream %d): %r",
                          _sid, e)

        st.recv_stream.offer(DataFrame(data, eos, release=credit))
        if eos:
            st.recv_closed = True
            self._maybe_gc(st)

    def _on_header_block(self, sid: int, block: bytes, end_stream: bool) -> None:
        try:
            headers = self._decoder.decode(block)
        except hpack.HpackError as e:
            raise H2ProtocolError(frames.COMPRESSION_ERROR, str(e)) from e
        st = self._streams.get(sid)
        if self.is_client:
            if st is None:
                return  # stale/reset stream
            if not st.got_headers:
                st.got_headers = True
                status = next((v for n, v in headers if n == ":status"), "200")
                if status.startswith("1"):  # 1xx interim: not final
                    st.got_headers = False
                    return
                rsp = H2Response.from_header_list(headers)
                rsp.stream = st.recv_stream
                if end_stream:
                    st.recv_stream.offer(DataFrame(b"", eos=True))
                    st.recv_closed = True
                if st.response_fut is not None and not st.response_fut.done():
                    st.response_fut.set_result(rsp)
                self._maybe_gc(st)
            else:  # trailers
                st.recv_stream.offer(Trailers(headers))
                st.recv_closed = True
                self._maybe_gc(st)
        else:
            if st is None:
                if sid in self._refused:
                    return  # trailing frames of a refused stream (§5.1)
                if sid <= self._last_peer_stream or sid % 2 == 0:
                    raise H2ProtocolError(frames.PROTOCOL_ERROR,
                                          f"bad stream id {sid}")
                self._last_peer_stream = sid
                if (self._max_concurrent is not None
                        and len(self._streams) >= self._max_concurrent):
                    # over our advertised limit: refuse, not kill the conn
                    self._write(frames.pack_rst(sid, frames.REFUSED_STREAM))
                    if len(self._refused) > 64:
                        self._refused.clear()
                    self._refused.add(sid)
                    return
                st = _StreamState(sid, self._peer_initial_window,
                                  self._local_initial_window)
                st.got_headers = True
                self._streams[sid] = st
                req = H2Request.from_header_list(headers)
                req.stream = st.recv_stream
                if end_stream:
                    st.recv_stream.offer(DataFrame(b"", eos=True))
                    st.recv_closed = True
                task = asyncio.get_running_loop().create_task(
                    self._serve_stream(st, req))
                self._handler_tasks.add(task)
                task.add_done_callback(self._handler_tasks.discard)
            else:  # request trailers
                st.recv_stream.offer(Trailers(headers))
                st.recv_closed = True

    async def _serve_stream(self, st: _StreamState, req: H2Request) -> None:
        """Run the app handler for one server stream and write its response
        (ref: Netty4ServerDispatcher serve)."""
        try:
            rsp = await self._handler(req)
        except StreamReset as e:
            self._rst(st, e.error_code)
            return
        except Exception:  # noqa: BLE001
            log.exception("h2 handler error (stream %d)", st.id)
            if st.id in self._streams and not self._closed:
                self._send_headers(st.id, [(":status", "500")],
                                   end_stream=True)
                st.send_closed = True
                try:
                    await self._drain()
                except (OSError, RuntimeError):  # peer already gone
                    pass
                self._maybe_gc(st)
            return
        if self._closed or st.id not in self._streams:
            return
        body = _poll_const_body(rsp.stream)
        try:
            if body is not None:
                data, trailers = body
                if trailers is None:
                    if data:
                        self._send_headers(st.id, rsp.to_header_list(),
                                           end_stream=False)
                        await self._send_data(st, data, eos=True)
                    else:
                        self._send_headers(st.id, rsp.to_header_list(),
                                           end_stream=True)
                else:
                    self._send_headers(st.id, rsp.to_header_list(),
                                       end_stream=False)
                    if data:
                        await self._send_data(st, data, eos=False)
                    self._send_headers(st.id, trailers, end_stream=True)
                st.send_closed = True
                await self._drain()
                self._maybe_gc(st)
            else:
                self._send_headers(st.id, rsp.to_header_list(),
                                   end_stream=False)
                await self._drain()
                await self._pump_out(st, rsp.stream)
        except (ConnectionError, asyncio.CancelledError):
            pass

    def _maybe_gc(self, st: _StreamState) -> None:
        if st.recv_closed and st.send_closed:
            self._streams.pop(st.id, None)
            self._wake_slot()
            if self._observer is not None:
                self._observer.on_close(st.id)

    def _wake_slot(self) -> None:
        while self._slot_waiters:
            w = self._slot_waiters.pop(0)
            if not w.done():
                w.set_result(None)
                break

    def adopt_upgraded_request(self, req: H2Request,
                               body: bytes = b"") -> None:
        """RFC 7540 §3.2: after a 101 Switching Protocols, the HTTP/1.1
        request that carried ``Upgrade: h2c`` becomes stream 1,
        half-closed (remote); its response goes out as h2 frames on
        stream 1 (ref: Netty's Http2FrameCodec server upgrade path wired
        by ServerUpgradeHandler.scala:38-41)."""
        st = _StreamState(1, self._peer_initial_window,
                          self._local_initial_window)
        st.got_headers = True
        self._streams[1] = st
        self._last_peer_stream = max(self._last_peer_stream, 1)
        req.stream = st.recv_stream
        st.recv_stream.offer(DataFrame(body, eos=True))
        st.recv_closed = True
        task = asyncio.get_running_loop().create_task(
            self._serve_stream(st, req))
        self._handler_tasks.add(task)
        task.add_done_callback(self._handler_tasks.discard)

    def apply_upgrade_settings(self, payload: bytes) -> None:
        """Apply the decoded HTTP2-Settings header payload (the client's
        SETTINGS, carried in the h1 upgrade request) before any h2 frame
        arrives (RFC 7540 §3.2.1)."""
        self._apply_settings(frames.unpack_settings(payload))

    def _apply_settings(self, settings: List[Tuple[int, int]]) -> None:
        for key, value in settings:
            if key == frames.SETTINGS_INITIAL_WINDOW_SIZE:
                if value > frames.MAX_WINDOW:
                    raise H2ProtocolError(frames.FLOW_CONTROL_ERROR,
                                          "window > 2^31-1")
                delta = value - self._peer_initial_window
                self._peer_initial_window = value
                for st in self._streams.values():
                    st.send_window += delta
            elif key == frames.SETTINGS_MAX_FRAME_SIZE:
                if not (16384 <= value <= (1 << 24) - 1):
                    raise H2ProtocolError(frames.PROTOCOL_ERROR,
                                          "bad max frame size")
                self._peer_max_frame = value
            elif key == frames.SETTINGS_MAX_CONCURRENT_STREAMS:
                self._peer_max_concurrent = value
            elif key == frames.SETTINGS_HEADER_TABLE_SIZE:
                self._encoder.set_max_table_size(value)
        spawn(self._notify_windows(), what="h2-notify-windows-settings")


def _poll_const_body(stream: H2Stream):
    """(body, trailers|None) if the stream is fully buffered right now,
    else None (must pump live). Lets unary messages skip the pump task."""
    try:
        q = stream._q  # noqa: SLF001 — engine-internal fast path
        items = list(q)
    except Exception:  # noqa: BLE001
        return None
    if not items or not getattr(items[-1], "eos", False):
        return None
    chunks: List[bytes] = []
    trailers = None
    for it in items:
        if isinstance(it, Trailers):
            trailers = it.headers
        elif isinstance(it, DataFrame):
            chunks.append(it.data)
        else:
            return None
    # drain the queue so at_end bookkeeping stays consistent, returning
    # each frame's flow credit (frames may originate from another h2
    # connection when a handler forwards a received stream)
    while q:
        item = q.popleft()
        if isinstance(item, DataFrame):
            item.release()
    stream.at_end = True
    return b"".join(chunks), trailers
