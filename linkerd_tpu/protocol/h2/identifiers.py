"""h2 request identifiers: H2Request -> logical Dst path.

Ref: linkerd/protocol/h2 identifiers — HeaderTokenIdentifier (default
``:authority``, H2Config.scala identifier default) and HeaderPathIdentifier.
Registered under the ``h2identifier`` category; the h2 router's default is
``io.l5d.header.token``.
"""

from __future__ import annotations

from dataclasses import dataclass

from linkerd_tpu.config import register
from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.protocol.h2.messages import H2Request
from linkerd_tpu.router.binding import DstPath
from linkerd_tpu.router.routing import (
    IdentificationError, Identifier, parse_local_dtab,
)

# parse_local_dtab only touches headers.get_all, which h2 Headers
# provides, so the HTTP/1 implementation is shared verbatim
_local_dtab = parse_local_dtab


@register("h2identifier", "io.l5d.header.token")
@dataclass
class H2HeaderTokenIdentifier:
    """``/<prefix>/<token>`` from a header; default ``:authority``
    (ref: HeaderTokenIdentifier.scala — the h2 default)."""

    header: str = ":authority"

    def mk(self, prefix: Path, base_dtab: Dtab) -> Identifier:
        def identify(req: H2Request) -> DstPath:
            if self.header == ":authority":
                token = (req.authority or "").split(":", 1)[0].lower()
            else:
                token = req.headers.get(self.header.lower()) or ""
            if not token:
                raise IdentificationError(f"no {self.header} header")
            p = Path.read(token) if token.startswith("/") else Path.of(token)
            return DstPath(prefix + p, base_dtab, _local_dtab(req))

        return identify


@register("h2identifier", "io.l5d.header.path")
@dataclass
class H2HeaderPathIdentifier:
    """``/<prefix>/<first-N-:path-segments>``
    (ref: HeaderPathIdentifier.scala)."""

    segments: int = 1

    def mk(self, prefix: Path, base_dtab: Dtab) -> Identifier:
        def identify(req: H2Request) -> DstPath:
            path_part = req.path.split("?", 1)[0]
            segs = [s for s in path_part.split("/") if s]
            if len(segs) < self.segments:
                raise IdentificationError(
                    f":path has fewer than {self.segments} segments")
            return DstPath(prefix + Path(segs[:self.segments]),
                           base_dtab, _local_dtab(req))

        return identify
