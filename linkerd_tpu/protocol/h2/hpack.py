"""HPACK header compression (RFC 7541).

Reference parity: the reference delegates HPACK to Netty's codec inside its
patched H2FrameCodec (finagle/h2/.../netty4/H2FrameCodec.scala); here it is
implemented natively: static + dynamic tables, integer/string primitives,
and the Appendix-B Huffman code (decode always supported; encoding is
optional and off by default — sending literal strings is always legal).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

try:  # native Huffman fast path (falls back to pure python)
    from linkerd_tpu import native as _native
except ImportError:  # pragma: no cover
    _native = None


class HpackError(Exception):
    """A COMPRESSION_ERROR-grade decoding failure (RFC 7540 §4.3)."""


# RFC 7541 Appendix A — the 61-entry static table.
STATIC_TABLE: List[Tuple[str, str]] = [
    (":authority", ""),
    (":method", "GET"),
    (":method", "POST"),
    (":path", "/"),
    (":path", "/index.html"),
    (":scheme", "http"),
    (":scheme", "https"),
    (":status", "200"),
    (":status", "204"),
    (":status", "206"),
    (":status", "304"),
    (":status", "400"),
    (":status", "404"),
    (":status", "500"),
    ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"),
    ("accept-language", ""),
    ("accept-ranges", ""),
    ("accept", ""),
    ("access-control-allow-origin", ""),
    ("age", ""),
    ("allow", ""),
    ("authorization", ""),
    ("cache-control", ""),
    ("content-disposition", ""),
    ("content-encoding", ""),
    ("content-language", ""),
    ("content-length", ""),
    ("content-location", ""),
    ("content-range", ""),
    ("content-type", ""),
    ("cookie", ""),
    ("date", ""),
    ("etag", ""),
    ("expect", ""),
    ("expires", ""),
    ("from", ""),
    ("host", ""),
    ("if-match", ""),
    ("if-modified-since", ""),
    ("if-none-match", ""),
    ("if-range", ""),
    ("if-unmodified-since", ""),
    ("last-modified", ""),
    ("link", ""),
    ("location", ""),
    ("max-forwards", ""),
    ("proxy-authenticate", ""),
    ("proxy-authorization", ""),
    ("range", ""),
    ("referer", ""),
    ("refresh", ""),
    ("retry-after", ""),
    ("server", ""),
    ("set-cookie", ""),
    ("strict-transport-security", ""),
    ("transfer-encoding", ""),
    ("user-agent", ""),
    ("vary", ""),
    ("via", ""),
    ("www-authenticate", ""),
]

_STATIC_FULL: Dict[Tuple[str, str], int] = {}
_STATIC_NAME: Dict[str, int] = {}
for _i, (_n, _v) in enumerate(STATIC_TABLE):
    _STATIC_FULL.setdefault((_n, _v), _i + 1)
    _STATIC_NAME.setdefault(_n, _i + 1)


# RFC 7541 Appendix B — Huffman code: (code, bit-length) per symbol 0..256
# (256 = EOS). Correctness is asserted by the Kraft-equality self-check at
# import and by curl/grpc interop tests (their nghttp2 peers always encode).
HUFFMAN_TABLE: List[Tuple[int, int]] = [
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12),
    (0x1ff9, 13), (0x15, 6), (0xf8, 8), (0x7fa, 11),
    (0x3fa, 10), (0x3fb, 10), (0xf9, 8), (0x7fb, 11),
    (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1a, 6), (0x1b, 6), (0x1c, 6), (0x1d, 6),
    (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10),
    (0x1ffa, 13), (0x21, 6), (0x5d, 7), (0x5e, 7),
    (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6a, 7),
    (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7),
    (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xfc, 8), (0x73, 7), (0xfd, 8), (0x1ffb, 13),
    (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5),
    (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28),
    (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20),
    (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23),
    (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23),
    (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23),
    (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23),
    (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23),
    (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23),
    (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24),
    (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22),
    (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21),
    (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24),
    (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23),
    (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21),
    (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23),
    (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22),
    (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23),
    (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19),
    (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25),
    (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27),
    (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25),
    (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27),
    (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24),
    (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26),
    (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27),
    (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21),
    (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23),
    (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25),
    (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23),
    (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26),
    (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27),
    (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27),
    (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26),
    (0x3fffffff, 30),
]

# Canonical-code self-check: a complete prefix code satisfies Kraft equality.
assert len(HUFFMAN_TABLE) == 257
assert abs(sum(2.0 ** -bits for _, bits in HUFFMAN_TABLE) - 1.0) < 1e-9, \
    "huffman table is not a complete prefix code"


def _build_decode_tree() -> list:
    # Binary trie as nested [left, right]; leaves are symbol ints.
    root: list = [None, None]
    for sym, (code, bits) in enumerate(HUFFMAN_TABLE):
        node = root
        for i in range(bits - 1, -1, -1):
            b = (code >> i) & 1
            if i == 0:
                node[b] = sym
            else:
                if node[b] is None:
                    node[b] = [None, None]
                node = node[b]
    return root


_DECODE_TREE = _build_decode_tree()


def huffman_decode(data: bytes) -> bytes:
    native_out = _native.huffman_decode(data) if _native is not None else None
    if native_out is not None:
        return native_out
    # pure-python path: also reached for malformed input so the precise
    # HpackError below is raised
    out = bytearray()
    node = _DECODE_TREE
    # Track bits consumed since the last emitted symbol for padding checks.
    pad_bits = 0
    pad_ones = True
    for byte in data:
        for i in range(7, -1, -1):
            b = (byte >> i) & 1
            pad_bits += 1
            pad_ones = pad_ones and b == 1
            nxt = node[b]
            if nxt is None:
                raise HpackError("invalid huffman sequence")
            if isinstance(nxt, int):
                if nxt == 256:
                    raise HpackError("EOS symbol in huffman data")
                out.append(nxt)
                node = _DECODE_TREE
                pad_bits = 0
                pad_ones = True
            else:
                node = nxt
    # RFC 7541 §5.2: padding must be <8 bits of the EOS prefix (all ones).
    if pad_bits >= 8 or not pad_ones:
        raise HpackError("invalid huffman padding")
    return bytes(out)


def huffman_encode(data: bytes) -> bytes:
    native_out = _native.huffman_encode(data) if _native is not None else None
    if native_out is not None:
        return native_out
    acc = 0
    nbits = 0
    out = bytearray()
    for byte in data:
        code, bits = HUFFMAN_TABLE[byte]
        acc = (acc << bits) | code
        nbits += bits
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        # pad with EOS-prefix ones
        out.append(((acc << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


def encode_int(value: int, prefix_bits: int, flags: int = 0) -> bytes:
    """RFC 7541 §5.1 integer representation."""
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([flags | value])
    out = bytearray([flags | limit])
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    if pos >= len(data):
        raise HpackError("truncated integer")
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer continuation")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 35:
            raise HpackError("integer overflow")
        if not (b & 0x80):
            return value, pos


def _decode_string(data: bytes, pos: int) -> Tuple[str, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    if pos + length > len(data):
        raise HpackError("truncated string data")
    raw = data[pos:pos + length]
    pos += length
    if huff:
        raw = huffman_decode(raw)
    try:
        return raw.decode("utf-8"), pos
    except UnicodeDecodeError:
        return raw.decode("latin-1"), pos


def _encode_string(s: str, huffman: bool) -> bytes:
    raw = s.encode("utf-8")
    if huffman:
        enc = huffman_encode(raw)
        if len(enc) < len(raw):
            return encode_int(len(enc), 7, 0x80) + enc
    return encode_int(len(raw), 7, 0x00) + raw


class _DynamicTable:
    """FIFO dynamic table with size accounting (RFC 7541 §4)."""

    def __init__(self, max_size: int = 4096):
        self.entries: List[Tuple[str, str]] = []  # newest first
        self.size = 0
        self.max_size = max_size

    @staticmethod
    def entry_size(name: str, value: str) -> int:
        return len(name.encode()) + len(value.encode()) + 32

    def add(self, name: str, value: str) -> None:
        need = self.entry_size(name, value)
        self.entries.insert(0, (name, value))
        self.size += need
        self._evict()
        if need > self.max_size:
            # entry larger than the table empties it (RFC 7541 §4.4)
            self.entries.clear()
            self.size = 0

    def resize(self, max_size: int) -> None:
        self.max_size = max_size
        self._evict()

    def _evict(self) -> None:
        while self.size > self.max_size and self.entries:
            n, v = self.entries.pop()
            self.size -= self.entry_size(n, v)

    def get(self, idx: int) -> Tuple[str, str]:
        """1-based index into the combined address space."""
        if 1 <= idx <= len(STATIC_TABLE):
            return STATIC_TABLE[idx - 1]
        didx = idx - len(STATIC_TABLE) - 1
        if 0 <= didx < len(self.entries):
            return self.entries[didx]
        raise HpackError(f"index {idx} out of table range")

    def find(self, name: str, value: str) -> Tuple[Optional[int], Optional[int]]:
        """(full-match index, name-match index), 1-based combined space."""
        full = _STATIC_FULL.get((name, value))
        name_only = _STATIC_NAME.get(name)
        if full is not None:
            return full, name_only
        for i, (n, v) in enumerate(self.entries):
            if n == name:
                idx = len(STATIC_TABLE) + i + 1
                if v == value:
                    return idx, idx
                if name_only is None:
                    name_only = idx
        return None, name_only


_CACHE_CAP = 512           # entry bound on the steady-state block caches
_CACHE_MAX_BLOCK = 2048    # don't cache oversized (peer-controlled) blocks
_CACHE_MAX_BYTES = 256 * 1024  # per-connection byte bound (decoder keys
                               # are peer-supplied: bound memory, not just
                               # entries)


class Decoder:
    def __init__(self, max_table_size: int = 4096):
        self._table = _DynamicTable(max_table_size)
        self._settings_max = max_table_size
        # steady-state fast path: an identical block decodes identically
        # as long as the dynamic table hasn't changed. Blocks that mutate
        # the table are never cached (and invalidate everything, since
        # dynamic indices shift); on the repeated header sets of a live
        # connection this skips parsing entirely.
        self._cache: dict = {}
        self._cache_bytes = 0

    def set_max_table_size(self, size: int) -> None:
        """Apply our SETTINGS_HEADER_TABLE_SIZE (the encoder must shrink
        to at most this via a dynamic-table-size-update)."""
        self._settings_max = size
        if size < self._table.max_size:
            self._table.resize(size)
        self._cache.clear()

    def decode(self, data: bytes) -> List[Tuple[str, str]]:
        cached = self._cache.get(data)
        if cached is not None:
            return list(cached)
        headers: List[Tuple[str, str]] = []
        mutated = False
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed field
                idx, pos = decode_int(data, pos, 7)
                if idx == 0:
                    raise HpackError("zero index")
                headers.append(self._table.get(idx))
            elif b & 0x40:  # literal with incremental indexing
                idx, pos = decode_int(data, pos, 6)
                name = (self._table.get(idx)[0] if idx
                        else None)
                if name is None:
                    name, pos = _decode_string(data, pos)
                value, pos = _decode_string(data, pos)
                self._table.add(name, value)
                mutated = True
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update
                size, pos = decode_int(data, pos, 5)
                if size > self._settings_max:
                    raise HpackError(
                        f"table size update {size} exceeds settings "
                        f"{self._settings_max}")
                self._table.resize(size)
                mutated = True
            else:  # literal without indexing (0x00) / never indexed (0x10)
                idx, pos = decode_int(data, pos, 4)
                name = self._table.get(idx)[0] if idx else None
                if name is None:
                    name, pos = _decode_string(data, pos)
                value, pos = _decode_string(data, pos)
                headers.append((name, value))
        if mutated:
            self._cache.clear()
            self._cache_bytes = 0
        elif len(data) <= _CACHE_MAX_BLOCK:
            if (len(self._cache) >= _CACHE_CAP
                    or self._cache_bytes >= _CACHE_MAX_BYTES):
                self._cache.clear()
                self._cache_bytes = 0
            self._cache[bytes(data)] = tuple(headers)
            self._cache_bytes += len(data)
        return headers


class Encoder:
    def __init__(self, max_table_size: int = 4096, huffman: bool = False):
        self._table = _DynamicTable(max_table_size)
        self.huffman = huffman
        self._pending_resize: Optional[int] = None
        # steady-state fast path (mirror of Decoder._cache): a header
        # list that encodes without inserting into the dynamic table
        # yields the same block until the table next changes
        self._cache: dict = {}

    def set_max_table_size(self, size: int) -> None:
        """Honor the peer's SETTINGS_HEADER_TABLE_SIZE: emit a size update
        in the next header block (RFC 7541 §6.3)."""
        size = min(size, 4096)
        self._pending_resize = size
        self._table.resize(size)
        self._cache.clear()

    _NEVER_INDEX = frozenset({"authorization", "cookie", "set-cookie"})

    def encode(self, headers: List[Tuple[str, str]]) -> bytes:
        key = tuple(headers)
        if self._pending_resize is None:
            cached = self._cache.get(key)
            if cached is not None:
                return cached
        out = bytearray()
        inserted = False
        if self._pending_resize is not None:
            out += encode_int(self._pending_resize, 5, 0x20)
            self._pending_resize = None
            inserted = True  # the size-update prefix must not be cached
        for name, value in headers:
            name = name.lower()
            full, name_idx = self._table.find(name, value)
            if full is not None:
                out += encode_int(full, 7, 0x80)
                continue
            if name in self._NEVER_INDEX:
                # sensitive: literal never-indexed (RFC 7541 §6.2.3)
                if name_idx is not None:
                    out += encode_int(name_idx, 4, 0x10)
                else:
                    out += encode_int(0, 4, 0x10)
                    out += _encode_string(name, self.huffman)
                out += _encode_string(value, self.huffman)
                continue
            # literal with incremental indexing
            if name_idx is not None:
                out += encode_int(name_idx, 6, 0x40)
            else:
                out += encode_int(0, 6, 0x40)
                out += _encode_string(name, self.huffman)
            out += _encode_string(value, self.huffman)
            self._table.add(name, value)
            inserted = True
        block = bytes(out)
        if inserted:
            # dynamic indices shifted: previously cached blocks are stale
            self._cache.clear()
        else:
            if len(self._cache) >= _CACHE_CAP:
                self._cache.clear()
            self._cache[key] = block
        return block
