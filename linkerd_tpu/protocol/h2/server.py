"""HTTP/2 server listener (prior-knowledge h2c, or TLS with ALPN h2).

Reference parity: finagle/h2/.../H2.scala server side +
Netty4H2Listener.scala. Each accepted connection runs one H2Connection
engine dispatching streams into the Service.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from linkerd_tpu.protocol.h2.connection import H2Connection
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
from linkerd_tpu.router.service import Service

log = logging.getLogger(__name__)


class H2Server:
    def __init__(self, service: Service[H2Request, H2Response],
                 host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None,
                 max_concurrency: Optional[int] = None,
                 h2_settings: Optional[dict] = None):
        self.service = service
        self.host = host
        self.port = port
        if ssl_context is not None:
            ssl_context.set_alpn_protocols(["h2"])
        self.ssl_context = ssl_context
        self._h2_settings = dict(h2_settings or {})
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()
        # admission control (ref: maxConcurrentRequests ->
        # RequestSemaphoreFilter, Server.scala:89-97)
        self._sem = (asyncio.Semaphore(max_concurrency)
                     if max_concurrency else None)

    @property
    def bound_port(self) -> int:
        assert self._server is not None, "server not started"
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> "H2Server":
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port, ssl=self.ssl_context)
        return self

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
        # Close live connections BEFORE wait_closed(): on Python >=3.12.1
        # wait_closed blocks until every connection handler returns, and
        # handlers run for the life of their connection's read loop.
        for conn in list(self._conns):
            await conn.close()
        if self._server is not None:
            await self._server.wait_closed()

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        conn = H2Connection(reader, writer, is_client=False,
                            **self._h2_settings,
                            handler=self._dispatch)
        self._conns.add(conn)
        try:
            await conn.start()
            # the connection lives as long as its read loop
            await asyncio.shield(conn._read_task)  # noqa: SLF001
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        finally:
            self._conns.discard(conn)
            await conn.close()

    async def _dispatch(self, req: H2Request) -> H2Response:
        try:
            if self._sem is not None:
                if self._sem.locked():
                    return H2Response(status=503, body=b"too many requests")
                async with self._sem:
                    return await self.service(req)
            return await self.service(req)
        except Exception as e:  # noqa: BLE001 — last-resort responder
            log.debug("h2 service error: %r", e)
            return H2Response(status=502, body=repr(e).encode())


async def serve_h2(service: Service, host: str = "127.0.0.1",
                   port: int = 0, **kw) -> H2Server:
    return await H2Server(service, host, port, **kw).start()
