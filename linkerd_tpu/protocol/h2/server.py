"""HTTP/2 server listener (prior-knowledge h2c, or TLS with ALPN h2).

Reference parity: finagle/h2/.../H2.scala server side +
Netty4H2Listener.scala. Each accepted connection runs one H2Connection
engine dispatching streams into the Service.
"""

from __future__ import annotations

import asyncio
import functools
import logging
from typing import Optional

from linkerd_tpu.protocol.h2.connection import H2Connection
from linkerd_tpu.protocol.h2.frames import REFUSED_STREAM
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
from linkerd_tpu.protocol.h2.stream import StreamReset
from linkerd_tpu.protocol.tls import sni_of
from linkerd_tpu.router.service import Service

log = logging.getLogger(__name__)


class H2Server:
    def __init__(self, service: Service[H2Request, H2Response],
                 host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None,
                 max_concurrency: Optional[int] = None,
                 h2_settings: Optional[dict] = None,
                 stream_observer_factory=None):
        self.service = service
        # stream sentinel (streamScoring): one fresh H2FrameObserver
        # per accepted connection, sharing the router's sentinel
        self._mk_observer = stream_observer_factory
        self.host = host
        self.port = port
        if ssl_context is not None:
            ssl_context.set_alpn_protocols(["h2"])
        self.ssl_context = ssl_context
        self._h2_settings = dict(h2_settings or {})
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()
        # admission control (ref: maxConcurrentRequests ->
        # RequestSemaphoreFilter, Server.scala:89-97)
        self._sem = (asyncio.Semaphore(max_concurrency)
                     if max_concurrency else None)

    @property
    def bound_port(self) -> int:
        assert self._server is not None, "server not started"
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> "H2Server":
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port, ssl=self.ssl_context)
        return self

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
        # Close live connections BEFORE wait_closed(): on Python >=3.12.1
        # wait_closed blocks until every connection handler returns, and
        # handlers run for the life of their connection's read loop.
        for conn in list(self._conns):
            await conn.close()
        if self._server is not None:
            await self._server.wait_closed()

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        # Sniff prior-knowledge h2c (connection preface) vs an HTTP/1.1
        # request upgrading with ``Upgrade: h2c`` + HTTP2-Settings on the
        # SAME port (ref: ServerUpgradeHandler.scala:1-70).
        from linkerd_tpu.protocol.h2.frames import CONNECTION_PREFACE

        upgraded = None
        try:
            buf = b""
            while (len(buf) < len(CONNECTION_PREFACE)
                   and CONNECTION_PREFACE.startswith(buf)):
                chunk = await reader.read(len(CONNECTION_PREFACE) - len(buf))
                if not chunk:
                    writer.close()
                    return
                buf += chunk
            surplus = b""
            if buf != CONNECTION_PREFACE:
                upgraded = await self._h1_upgrade(buf, reader, writer)
                if upgraded is None:
                    return  # answered (426 / 4xx) and closed
                # after the 101 the client sends the h2 preface; it may
                # have been coalesced with the upgrade request, and any
                # bytes past it are already h2 frames
                data = upgraded[3]
                while len(data) < len(CONNECTION_PREFACE):
                    chunk = await reader.read(
                        len(CONNECTION_PREFACE) - len(data))
                    if not chunk:
                        writer.close()
                        return
                    data += chunk
                if data[:len(CONNECTION_PREFACE)] != CONNECTION_PREFACE:
                    writer.close()
                    return
                surplus = data[len(CONNECTION_PREFACE):]
        except (ConnectionError, asyncio.IncompleteReadError):
            writer.close()
            return
        # SNI is a per-connection fact: read it once, stamp it on every
        # stream's request (tenantIdentifier: sni on the Python data
        # plane; the native h2 engine surfaces the same name natively)
        sni = sni_of(writer)
        handler = self._dispatch
        if sni is not None:
            handler = functools.partial(self._dispatch, sni=sni)
        conn = H2Connection(reader, writer, is_client=False,
                            **self._h2_settings,
                            handler=handler,
                            preface_consumed=True,
                            initial_data=surplus,
                            observer=(self._mk_observer()
                                      if self._mk_observer else None))
        self._conns.add(conn)
        try:
            if upgraded is not None:
                req, body, settings_payload, _ = upgraded
                conn.apply_upgrade_settings(settings_payload)
            await conn.start()
            if upgraded is not None:
                conn.adopt_upgraded_request(req, body)
            # the connection lives as long as its read loop
            await asyncio.shield(conn._read_task)  # noqa: SLF001
        except asyncio.CancelledError:
            pass
        except Exception as e:  # noqa: BLE001 — read loop already logged
            log.debug("h2 connection serve exit: %r", e)  # the details
        finally:
            self._conns.discard(conn)
            await conn.close()

    # headers that must not cross the h1 -> h2 translation (RFC 7540
    # §8.1.2.2 connection-specific headers + the upgrade machinery)
    _H1_ONLY = frozenset({
        "connection", "upgrade", "http2-settings", "host", "keep-alive",
        "proxy-connection", "transfer-encoding", "te",
    })

    async def _h1_upgrade(self, buf: bytes, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter):
        """Parse one h1 request; 101-switch when it upgrades to h2c.

        -> (H2Request, body, settings_payload) on success; None when the
        connection was answered and closed here (non-upgrade h1 gets 426
        Upgrade Required — this port speaks h2)."""
        import base64

        from linkerd_tpu.protocol.h2.messages import H2Request

        def respond(status: int, reason: str, extra: str = "") -> None:
            writer.write((f"HTTP/1.1 {status} {reason}\r\n{extra}"
                          f"Content-Length: 0\r\n"
                          f"Connection: close\r\n\r\n").encode())
            writer.close()

        data = buf
        while b"\r\n\r\n" not in data:
            if len(data) > 64 * 1024:
                respond(431, "Request Header Fields Too Large")
                return None
            chunk = await reader.read(65536)
            if not chunk:
                writer.close()
                return None
            data += chunk
        end = data.index(b"\r\n\r\n") + 4
        head, rest = data[:end], data[end:]
        try:
            # the SAME strict head parser as the http server (shared
            # line rules, header caps) — no second h1 parser to drift
            from linkerd_tpu.protocol.http.codec import _parse_head_bytes
            method, uri, version, headers = _parse_head_bytes(head)
        except Exception:  # noqa: BLE001 — malformed head
            respond(400, "Bad Request")
            return None
        if not version.startswith("HTTP/1"):
            respond(400, "Bad Request")
            return None

        conn_tokens = {t.strip().lower()
                       for t in (headers.get("connection") or "").split(",")
                       if t.strip()}
        settings_b64 = headers.get("http2-settings")
        if ("upgrade" not in conn_tokens
                or (headers.get("upgrade") or "").lower() != "h2c"
                or settings_b64 is None):
            respond(426, "Upgrade Required",
                    "Upgrade: h2c\r\nConnection: Upgrade\r\n")
            return None
        try:
            pad = -len(settings_b64) % 4
            settings_payload = base64.urlsafe_b64decode(
                settings_b64 + "=" * pad)
        except Exception:  # noqa: BLE001
            respond(400, "Bad Request")
            return None
        if headers.get("transfer-encoding") is not None:
            respond(400, "Bad Request")
            return None
        try:
            n_body = int(headers.get("content-length") or 0)
        except ValueError:
            respond(400, "Bad Request")
            return None
        if n_body < 0:
            respond(400, "Bad Request")
            return None
        if n_body > 1 << 20:
            respond(413, "Payload Too Large")
            return None
        while len(rest) < n_body:
            chunk = await reader.read(n_body - len(rest))
            if not chunk:
                writer.close()
                return None
            rest += chunk
        # bytes past the body belong to the h2 connection (a client may
        # coalesce its preface with the upgrade request)
        body, leftover = rest[:n_body], rest[n_body:]

        writer.write(b"HTTP/1.1 101 Switching Protocols\r\n"
                     b"Connection: Upgrade\r\nUpgrade: h2c\r\n\r\n")
        await writer.drain()

        # strip connection-specific headers, including any the client
        # nominated in Connection (RFC 7230 §6.1 / RFC 7540 §8.1.2.2)
        drop = self._H1_ONLY | conn_tokens
        h2_headers = [(":method", method), (":scheme", "http"),
                      (":authority", headers.get("host") or ""),
                      (":path", uri)]
        h2_headers.extend((n.lower(), v) for n, v in headers.items()
                          if n.lower() not in drop)
        return H2Request.from_header_list(h2_headers), body, \
            settings_payload, leftover

    async def _dispatch(self, req: H2Request,
                        sni: Optional[str] = None) -> H2Response:
        if sni is not None:
            req.ctx["sni"] = sni
        try:
            if self._sem is not None:
                if self._sem.locked():
                    # shed with a RETRYABLE signal: RST_STREAM
                    # REFUSED_STREAM tells the peer the stream was never
                    # processed (not a synthesized 503 body the client
                    # can't distinguish from an app error)
                    raise StreamReset(REFUSED_STREAM,
                                      "server concurrency limit")
                async with self._sem:
                    return await self.service(req)
            return await self.service(req)
        except StreamReset:
            # surfaces as an RST_STREAM frame (_serve_stream), keeping
            # the refusal's error code on the wire
            raise
        except Exception as e:  # noqa: BLE001 — last-resort responder
            log.debug("h2 service error: %r", e)
            return H2Response(status=502, body=repr(e).encode())


async def serve_h2(service: Service, host: str = "127.0.0.1",
                   port: int = 0, **kw) -> H2Server:
    return await H2Server(service, host, port, **kw).start()
