"""Stream-aware h2 response classification, including gRPC.

Ref: finagle/h2 service/H2Classifiers.scala (classification over
``H2ReqRepFrame`` — a response is judged on its *final frame*, because for
gRPC success/failure lives in the ``grpc-status`` trailer) and
linkerd/protocol/h2 grpc/GrpcClassifier.scala:77 (kinds
``io.l5d.h2.grpc.{default,alwaysRetryable,neverRetryable,
retryableStatusCodes}``).

An H2Classifier has two phases:
- ``early(req, rsp)``: a verdict from response headers alone, or None if
  the stream end is needed (gRPC always needs trailers);
- ``classify(req, rsp, trailers, exc)``: the final verdict.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from linkerd_tpu.config import register
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
from linkerd_tpu.protocol.h2.stream import (
    RST_REFUSED_STREAM, StreamReset, Trailers,
)
from linkerd_tpu.router.classifiers import (
    IDEMPOTENT_METHODS, READ_METHODS, SUCCESS_CLASS_HEADER, ResponseClass,
)

GRPC_STATUS = "grpc-status"
# gRPC codes the default classifier deems safe to retry
# (GrpcClassifier.scala default: UNAVAILABLE)
RETRYABLE_GRPC_CODES = frozenset({14})


class H2Classifier:
    def early(self, req: H2Request,
              rsp: Optional[H2Response]) -> Optional[ResponseClass]:
        """Verdict from headers alone, or None to wait for stream end."""
        return None

    def classify(self, req: H2Request, rsp: Optional[H2Response],
                 trailers: Optional[Trailers],
                 exc: Optional[BaseException]) -> ResponseClass:
        raise NotImplementedError


def _grpc_code(rsp: Optional[H2Response],
               trailers: Optional[Trailers]) -> Optional[int]:
    """grpc-status from trailers, or headers (Trailers-Only)."""
    raw = None
    if trailers is not None:
        for k, v in trailers.headers:
            if k == GRPC_STATUS:
                raw = v
    if raw is None and rsp is not None:
        raw = rsp.headers.get(GRPC_STATUS)
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _refused(exc: Optional[BaseException]) -> bool:
    """RST_STREAM REFUSED_STREAM: the peer never processed the stream
    (RFC 7540 §8.1.4 explicitly blesses retrying it), so refusal is
    retryable regardless of method idempotence."""
    return (isinstance(exc, StreamReset)
            and exc.error_code == RST_REFUSED_STREAM)


class _StatusClassifier(H2Classifier):
    """HTTP-status based classification; retryability by method policy."""

    def __init__(self, retryable_methods: frozenset):
        self._retryable = retryable_methods

    def early(self, req, rsp):
        if rsp is None:
            return None
        if rsp.status < 500:
            return ResponseClass.SUCCESS
        if req.method in self._retryable:
            return ResponseClass.RETRYABLE_FAILURE
        return ResponseClass.FAILURE

    def classify(self, req, rsp, trailers, exc):
        if exc is not None:
            if _refused(exc):
                return ResponseClass.RETRYABLE_FAILURE
            return (ResponseClass.RETRYABLE_FAILURE
                    if req.method in self._retryable
                    else ResponseClass.FAILURE)
        got = self.early(req, rsp)
        assert got is not None
        return got


@register("h2classifier", "io.l5d.h2.nonRetryable5XX")
@dataclass
class H2NonRetryable5XX:
    """5xx is failure, never retryable (h2 twin of
    io.l5d.http.nonRetryable5XX)."""

    def mk(self) -> H2Classifier:
        return _StatusClassifier(frozenset())


@register("h2classifier", "io.l5d.h2.retryableRead5XX")
@dataclass
class H2RetryableRead5XX:
    """5xx on read methods (GET/HEAD/OPTIONS/TRACE) is retryable."""

    def mk(self) -> H2Classifier:
        return _StatusClassifier(READ_METHODS)


@register("h2classifier", "io.l5d.h2.retryableIdempotent5XX")
@dataclass
class H2RetryableIdempotent5XX:
    """5xx on idempotent methods (reads + PUT/DELETE) is retryable."""

    def mk(self) -> H2Classifier:
        return _StatusClassifier(IDEMPOTENT_METHODS)


class _AllSuccessfulClassifier(H2Classifier):
    """Every response (any status) is a success; transport errors fail
    NON-retryably, matching the http twin (router/classifiers.py
    io.l5d.http.allSuccessful) — the request may have had side effects
    before the transport died (ref: h2 AllSuccessfulInitializer)."""

    def early(self, req, rsp):
        return ResponseClass.SUCCESS if rsp is not None else None

    def classify(self, req, rsp, trailers, exc):
        if exc is not None:
            return ResponseClass.FAILURE
        return ResponseClass.SUCCESS


@register("h2classifier", "io.l5d.h2.allSuccessful")
@dataclass
class H2AllSuccessful:
    """Every response is a success; only transport errors fail (and
    non-retryably — side effects may have happened)."""

    def mk(self) -> H2Classifier:
        return _AllSuccessfulClassifier()


class _SuccessClassClassifier(H2Classifier):
    """Trust the downstream router's ``l5d-success-class`` response
    header (stamped by its H2ClassifierFilter); defer to the wrapped
    classifier when absent/garbled. A failure verdict keeps the
    fallback's retryability analysis (h2 twin of
    io.l5d.http.successClass; ref router/h2/.../ClassifierFilter.scala:23)."""

    def __init__(self, inner: H2Classifier):
        self._inner = inner

    def _header_success(self, rsp: Optional[H2Response]) -> Optional[bool]:
        if rsp is None:
            return None
        hdr = rsp.headers.get(SUCCESS_CLASS_HEADER)
        if hdr is None:
            return None
        try:
            return float(hdr) >= 0.5
        except ValueError:
            return None

    def early(self, req, rsp):
        success = self._header_success(rsp)
        if success:
            return ResponseClass.SUCCESS
        if success is None:
            return self._inner.early(req, rsp)
        # downstream says failed: let classify() decide retryability
        return None

    def classify(self, req, rsp, trailers, exc):
        success = self._header_success(rsp)
        if success:
            return ResponseClass.SUCCESS
        rc = self._inner.classify(req, rsp, trailers, exc)
        if success is False and not rc.is_failure:
            return ResponseClass.FAILURE
        return rc


@register("h2classifier", "io.l5d.h2.successClass")
@dataclass
class H2SuccessClass:
    """Trust a downstream linkerd's l5d-success-class verdict; fall back
    to the wrapped kind when the header is absent."""

    fallback: str = "io.l5d.h2.nonRetryable5XX"

    def mk(self) -> H2Classifier:
        from linkerd_tpu.config import lookup
        return _SuccessClassClassifier(
            lookup("h2classifier", self.fallback)().mk())


class _GrpcClassifier(H2Classifier):
    """Success iff grpc-status == 0; retryability of failures per policy.
    Falls back to HTTP-status classification for non-gRPC responses."""

    def __init__(self, retryable_codes: frozenset, always: bool = False,
                 never: bool = False):
        self._codes = retryable_codes
        self._always = always
        self._never = never

    def _failure(self, code: int) -> ResponseClass:
        if self._never:
            return ResponseClass.FAILURE
        if self._always or code in self._codes:
            return ResponseClass.RETRYABLE_FAILURE
        return ResponseClass.FAILURE

    def classify(self, req, rsp, trailers, exc):
        if exc is not None:
            if self._never:
                return ResponseClass.FAILURE
            if self._always or _refused(exc):
                return ResponseClass.RETRYABLE_FAILURE
            return ResponseClass.FAILURE
        code = _grpc_code(rsp, trailers)
        if code is None:
            # not gRPC: treat like HTTP status
            if rsp is not None and rsp.status < 500:
                return ResponseClass.SUCCESS
            return self._failure(-1)
        if code == 0:
            return ResponseClass.SUCCESS
        return self._failure(code)


@register("h2classifier", "io.l5d.h2.grpc.default")
@dataclass
class GrpcDefault:
    """grpc-status 0 is success; the conventionally-safe codes
    (UNAVAILABLE, ...) retry."""

    def mk(self) -> H2Classifier:
        return _GrpcClassifier(RETRYABLE_GRPC_CODES)


@register("h2classifier", "io.l5d.h2.grpc.alwaysRetryable")
@dataclass
class GrpcAlwaysRetryable:
    """Any non-zero grpc-status retries."""

    def mk(self) -> H2Classifier:
        return _GrpcClassifier(frozenset(), always=True)


@register("h2classifier", "io.l5d.h2.grpc.neverRetryable")
@dataclass
class GrpcNeverRetryable:
    """No grpc-status ever retries."""

    def mk(self) -> H2Classifier:
        return _GrpcClassifier(frozenset(), never=True)


@register("h2classifier", "io.l5d.h2.grpc.retryableStatusCodes")
@dataclass
class GrpcRetryableStatusCodes:
    """Exactly the listed grpc-status codes retry."""

    retryableStatusCodes: List[int] = field(default_factory=list)

    def mk(self) -> H2Classifier:
        return _GrpcClassifier(frozenset(self.retryableStatusCodes))
