"""HTTP/2 for the TPU-native mesh: hand-written codec + stream engine.

Reference parity: finagle/h2 (the reference's largest subsystem, ~2,900
LoC on raw Netty4 Http2Frames — H2.scala, Stream.scala,
netty4/Netty4StreamTransport.scala RFC7540 state machine). Here the whole
wire layer — HPACK, framing, flow control, stream lifecycle — is
implemented natively on asyncio transports, keeping the reference's
pull-based Stream/release() semantics that retry-buffering and
stream-stats depend on.
"""

from linkerd_tpu.protocol.h2.messages import (  # noqa: F401
    H2Request, H2Response, Headers as H2Headers,
)
from linkerd_tpu.protocol.h2.stream import (  # noqa: F401
    DataFrame, H2Stream, StreamReset, Trailers,
)
