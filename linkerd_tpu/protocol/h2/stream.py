"""Pull-based HTTP/2 stream model with explicit release() flow control.

Reference parity: finagle/h2/.../Stream.scala:20-246 (pull-based frame
stream; consumers release() each Data frame, which returns flow-control
credit upstream) and BufferedStream.scala:29 (bounded replay buffer that
makes a stream retryable). The release() callback is how WINDOW_UPDATEs
propagate: the connection wires it to its window accounting.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, List, Optional, Tuple


class StreamReset(Exception):
    """The stream was reset (RST_STREAM or connection error).

    Reference parity: finagle/h2 Error.scala Reset ADT (Cancel, Refused,
    InternalError, ...).
    """

    def __init__(self, error_code: int = 0x8, message: str = ""):
        super().__init__(message or f"stream reset (code {error_code})")
        self.error_code = error_code


from linkerd_tpu.protocol.h2.frames import (  # noqa: E402
    CANCEL as RST_CANCEL,
    FLOW_CONTROL_ERROR as RST_FLOW_CONTROL_ERROR,
    INTERNAL_ERROR as RST_INTERNAL_ERROR,
    NO_ERROR as RST_NO_ERROR,
    PROTOCOL_ERROR as RST_PROTOCOL_ERROR,
    REFUSED_STREAM as RST_REFUSED_STREAM,
    STREAM_CLOSED as RST_STREAM_CLOSED,
)


class DataFrame:
    """A chunk of stream data; ``release()`` returns its flow credit."""

    __slots__ = ("data", "eos", "_release")

    def __init__(self, data: bytes, eos: bool = False,
                 release: Optional[Callable[[int], None]] = None):
        self.data = data
        self.eos = eos
        self._release = release

    def release(self) -> None:
        r, self._release = self._release, None
        if r is not None and self.data:
            r(len(self.data))

    def __repr__(self) -> str:
        return f"DataFrame({len(self.data)}B, eos={self.eos})"


class Trailers:
    """End-of-stream trailing headers (gRPC status rides here)."""

    __slots__ = ("headers",)
    eos = True

    def __init__(self, headers: List[Tuple[str, str]]):
        self.headers = headers

    def release(self) -> None:
        return

    def __repr__(self) -> str:
        return f"Trailers({self.headers})"


Frame = "DataFrame | Trailers"


class H2Stream:
    """An async pull queue of DataFrame/Trailers.

    Producers ``offer`` frames; the consumer ``read()``s them one at a
    time. A reset propagates to both sides. ``at_end`` is True once a
    frame with eos has been read.

    Implemented on a plain deque + single-waiter future (streams have
    exactly one consumer) — measurably cheaper per stream than an
    asyncio.Queue on the request hot path.
    """

    __slots__ = ("_q", "_waiter", "_reset", "at_end", "_ended_write")

    def __init__(self) -> None:
        from collections import deque
        self._q = deque()
        self._waiter: Optional[asyncio.Future] = None
        self._reset: Optional[StreamReset] = None
        self.at_end = False
        self._ended_write = False

    def _wake(self) -> None:
        w = self._waiter
        if w is not None:
            self._waiter = None
            if not w.done():
                w.set_result(None)

    # -- producer ---------------------------------------------------------
    def offer(self, frame) -> None:
        if self._reset is not None or self._ended_write:
            frame.release()  # don't strand flow credit
            return
        if frame.eos:
            self._ended_write = True
        self._q.append(frame)
        self._wake()

    def reset(self, error_code: int = RST_CANCEL, message: str = "") -> None:
        if self._reset is None:
            self._reset = StreamReset(error_code, message)
            self._q.append(self._reset)
            self._wake()

    # -- consumer ---------------------------------------------------------
    async def read(self):
        """Next frame; raises StreamReset after a reset."""
        while True:
            item = self.read_nowait()
            if item is not None:
                return item
            if self._reset is not None:
                raise self._reset
            self._waiter = asyncio.get_running_loop().create_future()
            await self._waiter

    def read_nowait(self):
        """Next frame if one is queued, else None (never suspends) —
        lets consumers that would otherwise wrap read() in wait_for (a
        task + timer per call) take the common already-buffered frames
        synchronously."""
        if self.at_end:
            raise EOFError("stream already ended")
        if not self._q:
            return None
        item = self._q.popleft()
        if isinstance(item, StreamReset):
            self._q.append(item)  # keep terminal state observable
            raise item
        if item.eos:
            self.at_end = True
        return item

    async def read_all(self, max_bytes: int = 1 << 26) -> Tuple[bytes, Optional[Trailers]]:
        """Drain the stream into (body, trailers) — the unary-message path."""
        chunks: List[bytes] = []
        total = 0
        trailers: Optional[Trailers] = None
        while not self.at_end:
            frame = await self.read()
            if isinstance(frame, Trailers):
                trailers = frame
            else:
                total += len(frame.data)
                if total > max_bytes:
                    self.reset(RST_CANCEL, "body too large")
                    raise StreamReset(RST_CANCEL, "body too large")
                chunks.append(frame.data)
                frame.release()
        return b"".join(chunks), trailers

    @property
    def is_reset(self) -> bool:
        return self._reset is not None


def stream_of(body: bytes = b"",
              trailers: Optional[List[Tuple[str, str]]] = None) -> H2Stream:
    """A pre-filled stream (the Stream.const of the reference)."""
    s = H2Stream()
    if trailers is not None:
        if body:
            s.offer(DataFrame(body, eos=False))
        s.offer(Trailers(trailers))
    else:
        s.offer(DataFrame(body, eos=True))
    return s


async def pump(src: H2Stream,
               write: Callable[["DataFrame | Trailers"], Awaitable[None]]
               ) -> None:
    """Copy frames from ``src`` into an async writer until EOS."""
    while not src.at_end:
        frame = await src.read()
        await write(frame)


class BufferedStream:
    """Tees a source stream while buffering up to ``capacity`` bytes so the
    consumer can be replayed (enables retrying streaming requests).

    Reference parity: finagle/h2/.../BufferedStream.scala:29 (8KB default);
    used by router/h2 ClassifiedRetryFilter.scala:237. Once the buffer
    overflows, ``discard_buffer()`` semantics apply: no further forks.
    """

    DEFAULT_CAPACITY = 8 * 1024

    def __init__(self, source: H2Stream, capacity: int = DEFAULT_CAPACITY):
        self._source = source
        self.capacity = capacity
        self._buffer: List = []  # (bytes, eos) | Trailers
        self._buffered_bytes = 0
        self.overflowed = False
        self._pump_task: Optional[asyncio.Task] = None
        self._forks: List[H2Stream] = []
        self._done = False

    def fork(self) -> H2Stream:
        """A fresh consumer stream replaying the buffer then following live.

        Raises RuntimeError once the buffer has overflowed.
        """
        if self.overflowed:
            raise RuntimeError("buffer discarded (overflow); cannot fork")
        out = H2Stream()
        for item in self._buffer:
            if isinstance(item, Trailers):
                out.offer(Trailers(list(item.headers)))
            else:
                data, eos = item
                out.offer(DataFrame(data, eos))
        self._forks.append(out)
        if self._pump_task is None and not self._done:
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump())
        return out

    async def _pump(self) -> None:
        try:
            while not self._source.at_end:
                frame = await self._source.read()
                if isinstance(frame, Trailers):
                    self._record(frame)
                    for f in self._forks:
                        f.offer(Trailers(list(frame.headers)))
                else:
                    self._record((frame.data, frame.eos))
                    for f in self._forks:
                        f.offer(DataFrame(frame.data, frame.eos))
                    # Credit flows back as soon as we've buffered — the
                    # buffer bound (not the consumer) is the backpressure.
                    frame.release()
            self._done = True
        except StreamReset as e:
            for f in self._forks:
                f.reset(e.error_code, str(e))

    def _record(self, item) -> None:
        size = len(item[0]) if isinstance(item, tuple) else 0
        if self._buffered_bytes + size > self.capacity:
            self.overflowed = True
            self._buffer.clear()
        elif not self.overflowed:
            self._buffer.append(item)
            self._buffered_bytes += size

    def release_buffer(self) -> None:
        """Stop buffering (no further forks) but keep pumping live frames
        to existing forks — used once a response is committed and replay
        will never be needed (ref: BufferedStream discardBuffer)."""
        self.overflowed = True
        self._buffer.clear()
        self._buffered_bytes = 0

    def unfork(self, stream: H2Stream) -> None:
        """Detach an abandoned consumer (e.g. a failed attempt's request
        stream) so its queue stops accumulating frames."""
        if stream in self._forks:
            self._forks.remove(stream)

    async def close(self) -> None:
        if self._pump_task is not None:
            self._pump_task.cancel()
            try:
                await self._pump_task
            except (asyncio.CancelledError, StreamReset):
                pass
