"""HTTP/2 client: one multiplexed connection per endpoint.

Reference parity: finagle/h2/.../H2.scala:29 — the client uses a
SingletonPool: all streams to an endpoint multiplex over a single h2
connection, re-established on failure.
"""

from __future__ import annotations

import asyncio
from typing import List, Optional

from linkerd_tpu.core.tasks import spawn
from linkerd_tpu.protocol.h2.connection import H2Connection
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
from linkerd_tpu.router.service import Service, Status


class H2Client(Service[H2Request, H2Response]):
    """A singleton-connection h2 client for one host:port endpoint."""

    def __init__(self, host: str, port: int,
                 connect_timeout: float = 3.0,
                 ssl_context=None, server_hostname: Optional[str] = None,
                 h2_settings: Optional[dict] = None):
        self.host = host
        self.port = port
        self.connect_timeout = connect_timeout
        if ssl_context is not None:
            ssl_context.set_alpn_protocols(["h2"])
        self.ssl_context = ssl_context
        self.server_hostname = server_hostname
        self._h2_settings = dict(h2_settings or {})
        self._conn: Optional[H2Connection] = None
        self._connecting: Optional[asyncio.Future] = None
        # GOAWAY drain: replaced connections park here until their
        # in-flight streams (at/below the peer's last_stream_id) finish
        self._draining: List[H2Connection] = []
        self._closed = False
        self.pending = 0  # live balancer instrumentation

    @property
    def status(self) -> Status:
        return Status.CLOSED if self._closed else Status.OPEN

    def _retire(self, conn: H2Connection) -> None:
        """Park a GOAWAY'd/closed conn for drain instead of leaking it.

        The engine already failed only streams above last_stream_id; the
        rest finish on the old socket while new requests ride a fresh
        conn. A watcher closes the parked conn once it empties (GOAWAY
        drain — not abort — per the reference's SingletonPool rebuild)."""
        self._draining.append(conn)

        async def _watch() -> None:
            try:
                while conn.active_streams and not conn.is_closed:
                    await asyncio.sleep(0.05)
                await conn.close()
            finally:
                if conn in self._draining:
                    self._draining.remove(conn)

        spawn(_watch(), what="h2-client-goaway-drain")

    async def _get_conn(self) -> H2Connection:
        cur = self._conn  # l5d: ignore[await-atomicity] — singleton dedup: concurrent connects serialize on _connecting, and the _closed re-check below covers the only concurrent writer (close)
        if cur is not None and not cur.is_closed \
                and not cur.goaway_received:
            return cur
        if cur is not None:
            # GOAWAY'd/dead singleton: retire it for drain (synchronous
            # pop — no await between the read above and here)
            self._conn = None
            self._retire(cur)
        if self._connecting is not None:
            return await asyncio.shield(self._connecting)
        loop = asyncio.get_running_loop()
        self._connecting = loop.create_future()
        try:
            kw = {}
            if self.ssl_context is not None:
                kw["ssl"] = self.ssl_context
                if self.server_hostname is not None:
                    kw["server_hostname"] = self.server_hostname
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port, **kw),
                self.connect_timeout)
            conn = H2Connection(reader, writer, is_client=True,
                                **self._h2_settings)
            await conn.start()
            if self._closed:
                # close() ran during the handshake: the entry guard is
                # stale, and the fresh connection (socket + read loop)
                # must not outlive its client
                await conn.close()
                raise ConnectionError(
                    f"h2 client {self.host}:{self.port} closed")
            # singleton reconnect: concurrent callers dedup through
            # _connecting; close-vs-connect is handled by the re-check
            self._conn = conn  # l5d: ignore[await-atomicity] — only this path (serialized by _connecting) assigns a live conn; close() was just re-checked above
            self._connecting.set_result(conn)
            return conn
        except BaseException as e:
            self._connecting.set_exception(e)
            fut, self._connecting = self._connecting, None
            # consume the exception if nobody else awaited it
            fut.exception()
            raise
        finally:
            if self._connecting is not None and self._connecting.done():
                self._connecting = None

    async def __call__(self, req: H2Request) -> H2Response:
        if self._closed:
            raise ConnectionError(f"h2 client {self.host}:{self.port} closed")
        if not req.authority:
            # :authority is mandatory for gRPC peers (grpc-go/grpcio
            # reject requests without it); default to the endpoint
            req.authority = f"{self.host}:{self.port}"
        conn = await self._get_conn()
        if self._closed:
            # close() ran while we were connecting: the entry guard is
            # stale and the request must not ride a dead client
            raise ConnectionError(
                f"h2 client {self.host}:{self.port} closed")
        self.pending += 1
        try:
            return await conn.request(req)
        finally:
            self.pending -= 1

    async def close(self) -> None:
        self._closed = True
        # detach before awaiting: a connect finishing during the await
        # must find _conn already cleared (it re-checks _closed and
        # closes its own socket), not re-cache over our teardown
        conn, self._conn = self._conn, None
        if conn is not None:
            await conn.close()
        draining, self._draining = self._draining, []
        for conn in draining:
            await conn.close()
