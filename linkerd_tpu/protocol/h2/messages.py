"""HTTP/2 request/response message model.

Reference parity: finagle/h2/.../Message.scala, Method.scala, Status.scala —
messages carry pseudo-header fields plus a Headers list and an H2Stream
body. Header names are kept lowercase (RFC 7540 §8.1.2).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from linkerd_tpu.protocol.h2.stream import H2Stream, stream_of


class Headers:
    """An ordered multi-map of lowercase header names."""

    __slots__ = ("_items",)

    def __init__(self, items: Optional[Iterable[Tuple[str, str]]] = None):
        self._items: List[Tuple[str, str]] = [
            (n.lower(), v) for n, v in (items or [])]

    def get(self, name: str) -> Optional[str]:
        name = name.lower()
        for n, v in self._items:
            if n == name:
                return v
        return None

    def get_all(self, name: str) -> List[str]:
        name = name.lower()
        return [v for n, v in self._items if n == name]

    def set(self, name: str, value: str) -> None:
        name = name.lower()
        self.remove(name)
        self._items.append((name, value))

    def add(self, name: str, value: str) -> None:
        self._items.append((name.lower(), value))

    def remove(self, name: str) -> None:
        name = name.lower()
        self._items = [(n, v) for n, v in self._items if n != name]

    def items(self) -> List[Tuple[str, str]]:
        return list(self._items)

    def __contains__(self, name: str) -> bool:
        return self.get(name) is not None

    def __len__(self) -> int:
        return len(self._items)

    def copy(self) -> "Headers":
        return Headers(self._items)

    def __repr__(self) -> str:
        return f"Headers({self._items!r})"


class H2Request:
    """An h2 request: pseudo-headers + headers + pull-stream body."""

    __slots__ = ("scheme", "method", "authority", "path", "headers",
                 "stream", "ctx")

    def __init__(self, method: str = "GET", path: str = "/",
                 authority: str = "", scheme: str = "http",
                 headers: Optional[Headers] = None,
                 stream: Optional[H2Stream] = None,
                 body: Optional[bytes] = None):
        self.method = method
        self.path = path
        self.authority = authority
        self.scheme = scheme
        self.headers = headers if headers is not None else Headers()
        if stream is None:
            stream = stream_of(body or b"")
        self.stream = stream
        self.ctx: Dict[str, object] = {}

    def to_header_list(self) -> List[Tuple[str, str]]:
        pseudo = [(":method", self.method), (":scheme", self.scheme)]
        if self.authority:
            pseudo.append((":authority", self.authority))
        pseudo.append((":path", self.path))
        return pseudo + self.headers.items()

    @staticmethod
    def from_header_list(items: List[Tuple[str, str]]) -> "H2Request":
        pseudo: Dict[str, str] = {}
        rest: List[Tuple[str, str]] = []
        for n, v in items:
            if n.startswith(":"):
                pseudo[n] = v
            else:
                rest.append((n, v))
        return H2Request(
            method=pseudo.get(":method", "GET"),
            path=pseudo.get(":path", "/"),
            authority=pseudo.get(":authority", ""),
            scheme=pseudo.get(":scheme", "http"),
            headers=Headers(rest),
            stream=H2Stream(),
        )

    def __repr__(self) -> str:
        return f"H2Request({self.method} {self.authority}{self.path})"


class H2Response:
    __slots__ = ("status", "headers", "stream", "ctx")

    def __init__(self, status: int = 200,
                 headers: Optional[Headers] = None,
                 stream: Optional[H2Stream] = None,
                 body: Optional[bytes] = None,
                 trailers: Optional[List[Tuple[str, str]]] = None):
        self.status = status
        self.headers = headers if headers is not None else Headers()
        if stream is None:
            stream = stream_of(body or b"", trailers)
        self.stream = stream
        self.ctx: Dict[str, object] = {}

    def to_header_list(self) -> List[Tuple[str, str]]:
        return [(":status", str(self.status))] + self.headers.items()

    @staticmethod
    def from_header_list(items: List[Tuple[str, str]]) -> "H2Response":
        status = 200
        rest: List[Tuple[str, str]] = []
        for n, v in items:
            if n == ":status":
                status = int(v)
            elif not n.startswith(":"):
                rest.append((n, v))
        return H2Response(status=status, headers=Headers(rest),
                          stream=H2Stream())

    def __repr__(self) -> str:
        return f"H2Response({self.status})"
