"""HTTP/2 frame codec (RFC 7540 §4-6).

Reference parity: the reference patches Netty's frame codec
(finagle/h2/.../netty4/H2FrameCodec.scala:287); here frames are read and
written directly on asyncio streams. Each frame is a 9-byte header
(24-bit length, type, flags, 31-bit stream id) plus payload.
"""

from __future__ import annotations

import struct
from typing import List, NamedTuple, Tuple

# frame types
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

# flags
FLAG_END_STREAM = 0x1   # DATA, HEADERS
FLAG_ACK = 0x1          # SETTINGS, PING
FLAG_END_HEADERS = 0x4  # HEADERS, CONTINUATION
FLAG_PADDED = 0x8       # DATA, HEADERS
FLAG_PRIORITY = 0x20    # HEADERS

# settings ids
SETTINGS_HEADER_TABLE_SIZE = 0x1
SETTINGS_ENABLE_PUSH = 0x2
SETTINGS_MAX_CONCURRENT_STREAMS = 0x3
SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5
SETTINGS_MAX_HEADER_LIST_SIZE = 0x6

# error codes (RFC 7540 §7)
NO_ERROR = 0x0
PROTOCOL_ERROR = 0x1
INTERNAL_ERROR = 0x2
FLOW_CONTROL_ERROR = 0x3
SETTINGS_TIMEOUT = 0x4
STREAM_CLOSED = 0x5
FRAME_SIZE_ERROR = 0x6
REFUSED_STREAM = 0x7
CANCEL = 0x8
COMPRESSION_ERROR = 0x9
CONNECT_ERROR = 0xA
ENHANCE_YOUR_CALM = 0xB
INADEQUATE_SECURITY = 0xC
HTTP_1_1_REQUIRED = 0xD

DEFAULT_MAX_FRAME_SIZE = 16384
DEFAULT_INITIAL_WINDOW = 65535
MAX_WINDOW = (1 << 31) - 1

CONNECTION_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"


class FrameHeader(NamedTuple):
    length: int
    type: int
    flags: int
    stream_id: int


class H2ProtocolError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(message or f"h2 protocol error {code:#x}")
        self.code = code


def pack_frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    return struct.pack("!I", len(payload))[1:] + bytes(
        [ftype, flags]) + struct.pack("!I", stream_id & 0x7FFFFFFF) + payload


def unpack_header(data: bytes) -> FrameHeader:
    length = (data[0] << 16) | (data[1] << 8) | data[2]
    stream_id = struct.unpack("!I", data[5:9])[0] & 0x7FFFFFFF
    return FrameHeader(length, data[3], data[4], stream_id)


def strip_padding(flags: int, payload: bytes) -> bytes:
    if flags & FLAG_PADDED:
        if not payload:
            raise H2ProtocolError(PROTOCOL_ERROR, "padded frame w/o pad length")
        pad = payload[0]
        if pad >= len(payload):
            raise H2ProtocolError(PROTOCOL_ERROR, "pad length >= payload")
        return payload[1:len(payload) - pad]
    return payload


def pack_settings(settings: List[Tuple[int, int]], ack: bool = False) -> bytes:
    payload = b"".join(struct.pack("!HI", k, v) for k, v in settings)
    return pack_frame(SETTINGS, FLAG_ACK if ack else 0, 0, payload)


def unpack_settings(payload: bytes) -> List[Tuple[int, int]]:
    if len(payload) % 6:
        raise H2ProtocolError(FRAME_SIZE_ERROR, "settings size not 6n")
    return [struct.unpack("!HI", payload[i:i + 6])
            for i in range(0, len(payload), 6)]


def pack_window_update(stream_id: int, increment: int) -> bytes:
    return pack_frame(WINDOW_UPDATE, 0, stream_id, struct.pack("!I", increment))


def pack_rst(stream_id: int, code: int) -> bytes:
    return pack_frame(RST_STREAM, 0, stream_id, struct.pack("!I", code))


def pack_goaway(last_stream_id: int, code: int, debug: bytes = b"") -> bytes:
    return pack_frame(GOAWAY, 0, 0,
                      struct.pack("!II", last_stream_id, code) + debug)


def pack_ping(data: bytes = b"\0" * 8, ack: bool = False) -> bytes:
    return pack_frame(PING, FLAG_ACK if ack else 0, 0, data)
