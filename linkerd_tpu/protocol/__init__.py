"""Wire protocols: HTTP/1.1 now; h2+gRPC and thrift follow (SURVEY.md §7)."""
