"""TTwitter thrift upgrade: trace + dtab context over plain thrift.

Ref: linkerd/protocol/thrift/.../TTwitterClientFilter.scala and
TTwitterServerFilter.scala (both forked from finagle-thrift) and
ThriftInitializer.scala:103 ``attemptTTwitterUpgrade``. Protocol: the
client sends a CALL named ``__can__finagle__trace__v3__`` carrying
ConnectionOptions; an upgraded server replies with UpgradeReply. After
upgrade every request is prefixed with a RequestHeader struct (trace
ids, sampled, client id, dest, dtab delegations) and every reply with a
ResponseHeader struct.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from linkerd_tpu.core import Dtab
from linkerd_tpu.protocol.thrift.binary import (
    Reader, TStruct, Writer, encode_struct, read_struct, write_struct,
)
from linkerd_tpu.protocol.thrift.codec import (
    CALL, REPLY, VERSION_1,
)
from linkerd_tpu.router.tracing import TraceId

CAN_TRACE_METHOD = "__can__finagle__trace__v3__"

_MASK64 = (1 << 64) - 1


class TClientId(TStruct):  # finagle tracing.thrift ClientId
    FIELDS = {"name": (1, "string")}


class TRequestContext(TStruct):
    FIELDS = {"key": (1, "binary"), "value": (2, "binary")}


class TDelegation(TStruct):
    FIELDS = {"src": (1, "string"), "dst": (2, "string")}


class TRequestHeader(TStruct):
    FIELDS = {
        "trace_id": (1, "i64"),
        "span_id": (2, "i64"),
        "parent_span_id": (3, "i64"),
        "sampled": (5, "bool"),
        "client_id": (6, ("struct", TClientId)),
        "flags": (7, "i64"),
        "contexts": (8, ("list", ("struct", TRequestContext))),
        "dest": (9, "string"),
        "delegations": (10, ("list", ("struct", TDelegation))),
    }


class TResponseHeader(TStruct):
    FIELDS: dict = {}


class TConnectionOptions(TStruct):
    FIELDS: dict = {}


class TUpgradeReply(TStruct):
    FIELDS: dict = {}


def _message(name: str, mtype: int, seqid: int, body: bytes) -> bytes:
    import struct
    nb = name.encode("utf-8")
    return (struct.pack(">I", (VERSION_1 | mtype) & 0xFFFFFFFF)
            + struct.pack(">I", len(nb)) + nb
            + struct.pack(">i", seqid) + body)


def encode_upgrade_request(seqid: int = 0) -> bytes:
    return _message(CAN_TRACE_METHOD, CALL, seqid,
                    encode_struct(TConnectionOptions()))


def encode_upgrade_reply(seqid: int) -> bytes:
    return _message(CAN_TRACE_METHOD, REPLY, seqid,
                    encode_struct(TUpgradeReply()))


def mk_request_header(trace: Optional[TraceId] = None,
                      dest: str = "",
                      dtab: Optional[Dtab] = None,
                      client_id: str = "") -> TRequestHeader:
    h = TRequestHeader()
    if trace is not None:
        h.trace_id = trace.trace_id & _MASK64
        h.span_id = trace.span_id & _MASK64
        if trace.parent_id:
            h.parent_span_id = trace.parent_id & _MASK64
        h.sampled = trace.sampled
    else:
        h.trace_id = 0
        h.span_id = 0
    if dest:
        h.dest = dest
    if client_id:
        h.client_id = TClientId(name=client_id)
    if dtab:
        h.delegations = [
            TDelegation(src=d.prefix.show, dst=d.dst.show) for d in dtab]
    return h


def header_trace(h: TRequestHeader) -> Optional[TraceId]:
    if not h.trace_id and not h.span_id:
        return None
    return TraceId(trace_id=h.trace_id or 0, span_id=h.span_id or 0,
                   parent_id=h.parent_span_id or 0,
                   sampled=bool(h.sampled) if h.sampled is not None else True)


def header_dtab(h: TRequestHeader) -> Dtab:
    if not h.delegations:
        return Dtab.empty()
    try:
        return Dtab.read(";".join(
            f"{d.src} => {d.dst}" for d in h.delegations))
    except ValueError:
        return Dtab.empty()


def prepend_struct(s: TStruct, payload: bytes) -> bytes:
    return encode_struct(s) + payload


def peel_struct(cls: type, payload: bytes) -> Tuple[TStruct, bytes]:
    r = Reader(payload)
    obj = read_struct(r, cls)
    return obj, payload[r.pos:]
