"""Thrift server: framed or buffered transport, binary or compact
protocol, pipelined per-connection dispatch.

Ref: finagle-thrift server semantics as used by router/thrift —
requests on one connection dispatch CONCURRENTLY (finagle pipelines
thrift), with responses written back in request order so plain Apache
clients (which match replies positionally, not by seqid) stay correct.
Transport/protocol knobs per ThriftInitializer.scala:47,68-72
(``thriftProtocol``, ``thriftFramed``).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from linkerd_tpu.protocol.thrift.codec import (
    ThriftCall, UnframedReader, encode_exception, encode_exception_for,
    parse_header, read_framed, write_framed,
)
from linkerd_tpu.router.service import Service

from linkerd_tpu.protocol.thrift.ttwitter import (  # noqa: E402
    CAN_TRACE_METHOD as _CAN_TRACE,
)

log = logging.getLogger(__name__)


class ThriftServer:
    def __init__(self, service: Service[ThriftCall, Optional[bytes]],
                 host: str = "127.0.0.1", port: int = 0,
                 ttwitter: bool = True, framed: bool = True,
                 protocol: str = "binary", max_pipelined: int = 32):
        self.service = service
        self.host = host
        self.port = port
        # answer TTwitter upgrade requests; upgraded connections carry
        # RequestHeader/ResponseHeader framing (ref: TTwitterServerFilter).
        # The upgrade protocol itself is framed-binary only.
        self.ttwitter = ttwitter and framed and protocol == "binary"
        self.framed = framed
        self.protocol = protocol
        if protocol not in ("binary", "compact"):
            raise ValueError(f"unknown thrift protocol {protocol!r}")
        if not framed and protocol != "binary":
            raise ValueError("buffered transport requires the binary "
                             "protocol (message-boundary scan)")
        self.max_pipelined = max_pipelined
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()
        self._conn_tasks: set = set()

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> "ThriftServer":
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port)
        return self

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
        for w in list(self._conns):
            try:
                w.close()
            except (OSError, RuntimeError):  # transport already detached
                pass
        for t in list(self._conn_tasks):
            t.cancel()
        if self._server is not None:
            await self._server.wait_closed()

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        upgraded = False  # per-connection TTwitter state
        unframed = (UnframedReader(reader) if not self.framed else None)
        # pipelining: requests dispatch concurrently (bounded); replies
        # are written in REQUEST order via an ordered queue of futures so
        # positional (non-seqid) clients stay correct
        sem = asyncio.Semaphore(self.max_pipelined)
        reply_q: asyncio.Queue = asyncio.Queue()
        pending_tasks: set = set()

        def send(reply: bytes) -> None:
            if self.framed:
                write_framed(writer, reply)
            else:
                writer.write(reply)

        async def write_loop() -> None:
            try:
                while True:
                    fut = await reply_q.get()
                    if fut is None:
                        return
                    reply = await fut
                    if reply is not None:
                        send(reply)
                        await writer.drain()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — write side gone:
                # kill the conn so the read loop unwinds instead of
                # stalling, but leave a trace of WHY the writer died
                log.debug("thrift write loop failed: %r", e)
                try:
                    writer.close()
                except (OSError, RuntimeError):
                    pass

        async def run_one(call: ThriftCall, was_upgraded: bool) -> Optional[bytes]:
            async with sem:
                try:
                    reply = await self.service(call)
                except Exception as e:  # noqa: BLE001 -> thrift exception
                    # encode in the CONNECTION's protocol: a binary-
                    # encoded exception desyncs compact clients
                    reply = encode_exception_for(
                        self.protocol, call.name, call.seqid, repr(e))
                if call.oneway or reply is None:
                    return None
                if was_upgraded:
                    from linkerd_tpu.protocol.thrift import ttwitter as ttw
                    reply = ttw.prepend_struct(ttw.TResponseHeader(), reply)
                return reply

        writer_task = asyncio.get_running_loop().create_task(write_loop())
        try:
            while True:
                payload = (await read_framed(reader) if self.framed
                           else await unframed.read_message())
                if payload is None:
                    return
                ctx: dict = {}
                if upgraded:
                    from linkerd_tpu.protocol.thrift import ttwitter as ttw
                    try:
                        header, payload = ttw.peel_struct(
                            ttw.TRequestHeader, payload)
                    except Exception as e:  # noqa: BLE001 — desynced conn
                        log.debug("bad ttwitter header: %s", e)
                        return
                    trace = ttw.header_trace(header)
                    if trace is not None:
                        ctx["trace"] = trace
                    ctx["dtab"] = ttw.header_dtab(header)
                    if header.dest:
                        ctx["dest"] = header.dest
                    if header.client_id is not None:
                        ctx["clientId"] = header.client_id.name
                try:
                    name, seqid, mtype = parse_header(payload,
                                                      self.protocol)
                except Exception as e:  # noqa: BLE001 - bad frame: drop conn
                    log.debug("bad thrift frame: %s", e)
                    return
                if not upgraded and mtype == 1 and name == _CAN_TRACE \
                        and self.framed and self.protocol == "binary":
                    if self.ttwitter:
                        from linkerd_tpu.protocol.thrift import (
                            ttwitter as ttw,
                        )
                        upgraded = True
                        probe_reply = ttw.encode_upgrade_reply(seqid)
                    else:
                        # never forward the probe downstream: a REPLY from
                        # there would desync BOTH hops. Answer like any
                        # plain thrift server (unknown method).
                        probe_reply = encode_exception(
                            name, seqid, "Invalid method name")
                    # ride the ordered reply queue: a direct write would
                    # overtake replies still pending for earlier
                    # pipelined requests (positional clients pair
                    # replies by order, not seqid)
                    fut = asyncio.get_running_loop().create_future()
                    fut.set_result(probe_reply)
                    reply_q.put_nowait(fut)
                    continue
                call = ThriftCall(payload, name, seqid, mtype, ctx=ctx)
                task = asyncio.get_running_loop().create_task(
                    run_one(call, upgraded))
                pending_tasks.add(task)
                task.add_done_callback(pending_tasks.discard)
                if not call.oneway:
                    reply_q.put_nowait(task)
                # backpressure: don't read unboundedly ahead of dispatch
                if sem.locked():
                    async with sem:
                        pass
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            log.exception("thrift connection handler error")
        finally:
            # drain in-flight replies (bounded), then stop the writer.
            # CancelledError (BaseException) must not skip the cleanup
            # below — re-raise it after the conn is fully torn down.
            cancelled: Optional[BaseException] = None
            try:
                reply_q.put_nowait(None)
                await asyncio.wait_for(writer_task, 5.0)
            except asyncio.CancelledError as e:
                writer_task.cancel()
                cancelled = e
            except Exception:  # noqa: BLE001
                writer_task.cancel()
            for t in list(pending_tasks):
                t.cancel()
            self._conns.discard(writer)
            try:
                writer.close()
            except (OSError, RuntimeError):  # transport already detached
                pass
            if cancelled is not None:
                raise cancelled


async def serve_thrift(service: Service, host: str = "127.0.0.1",
                       port: int = 0) -> ThriftServer:
    return await ThriftServer(service, host, port).start()
