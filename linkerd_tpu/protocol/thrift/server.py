"""Framed-thrift server: per-connection sequential dispatch.

Ref: finagle-thrift server semantics as used by router/thrift — one
request at a time per connection (thrift framed transport is not
multiplexed), responses matched by seqid.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from linkerd_tpu.protocol.thrift.codec import (
    ThriftCall, encode_exception, parse_message_header, read_framed,
    write_framed,
)
from linkerd_tpu.router.service import Service

log = logging.getLogger(__name__)


class ThriftServer:
    def __init__(self, service: Service[ThriftCall, Optional[bytes]],
                 host: str = "127.0.0.1", port: int = 0):
        self.service = service
        self.host = host
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()
        self._conn_tasks: set = set()

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> "ThriftServer":
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port)
        return self

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
        for w in list(self._conns):
            try:
                w.close()
            except Exception:  # noqa: BLE001
                pass
        for t in list(self._conn_tasks):
            t.cancel()
        if self._server is not None:
            await self._server.wait_closed()

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        try:
            while True:
                payload = await read_framed(reader)
                if payload is None:
                    return
                try:
                    name, seqid, mtype = parse_message_header(payload)
                except Exception as e:  # noqa: BLE001 - bad frame: drop conn
                    log.debug("bad thrift frame: %s", e)
                    return
                call = ThriftCall(payload, name, seqid, mtype)
                try:
                    reply = await self.service(call)
                except Exception as e:  # noqa: BLE001 -> thrift exception
                    reply = encode_exception(name, seqid, repr(e))
                if not call.oneway and reply is not None:
                    write_framed(writer, reply)
                    await writer.drain()
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            log.exception("thrift connection handler error")
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass


async def serve_thrift(service: Service, host: str = "127.0.0.1",
                       port: int = 0) -> ThriftServer:
    return await ThriftServer(service, host, port).start()
