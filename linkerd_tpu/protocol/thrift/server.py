"""Framed-thrift server: per-connection sequential dispatch.

Ref: finagle-thrift server semantics as used by router/thrift — one
request at a time per connection (thrift framed transport is not
multiplexed), responses matched by seqid.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from linkerd_tpu.protocol.thrift.codec import (
    ThriftCall, encode_exception, parse_message_header, read_framed,
    write_framed,
)
from linkerd_tpu.router.service import Service

from linkerd_tpu.protocol.thrift.ttwitter import (  # noqa: E402
    CAN_TRACE_METHOD as _CAN_TRACE,
)

log = logging.getLogger(__name__)


class ThriftServer:
    def __init__(self, service: Service[ThriftCall, Optional[bytes]],
                 host: str = "127.0.0.1", port: int = 0,
                 ttwitter: bool = True):
        self.service = service
        self.host = host
        self.port = port
        # answer TTwitter upgrade requests; upgraded connections carry
        # RequestHeader/ResponseHeader framing (ref: TTwitterServerFilter)
        self.ttwitter = ttwitter
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()
        self._conn_tasks: set = set()

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> "ThriftServer":
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port)
        return self

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
        for w in list(self._conns):
            try:
                w.close()
            except Exception:  # noqa: BLE001
                pass
        for t in list(self._conn_tasks):
            t.cancel()
        if self._server is not None:
            await self._server.wait_closed()

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        upgraded = False  # per-connection TTwitter state
        try:
            while True:
                payload = await read_framed(reader)
                if payload is None:
                    return
                ctx: dict = {}
                if upgraded:
                    from linkerd_tpu.protocol.thrift import ttwitter as ttw
                    try:
                        header, payload = ttw.peel_struct(
                            ttw.TRequestHeader, payload)
                    except Exception as e:  # noqa: BLE001 — desynced conn
                        log.debug("bad ttwitter header: %s", e)
                        return
                    trace = ttw.header_trace(header)
                    if trace is not None:
                        ctx["trace"] = trace
                    ctx["dtab"] = ttw.header_dtab(header)
                    if header.dest:
                        ctx["dest"] = header.dest
                    if header.client_id is not None:
                        ctx["clientId"] = header.client_id.name
                try:
                    name, seqid, mtype = parse_message_header(payload)
                except Exception as e:  # noqa: BLE001 - bad frame: drop conn
                    log.debug("bad thrift frame: %s", e)
                    return
                if not upgraded and mtype == 1 and name == _CAN_TRACE:
                    if self.ttwitter:
                        from linkerd_tpu.protocol.thrift import (
                            ttwitter as ttw,
                        )
                        upgraded = True
                        write_framed(writer,
                                     ttw.encode_upgrade_reply(seqid))
                    else:
                        # never forward the probe downstream: a REPLY from
                        # there would desync BOTH hops. Answer like any
                        # plain thrift server (unknown method).
                        write_framed(writer, encode_exception(
                            name, seqid, "Invalid method name"))
                    await writer.drain()
                    continue
                call = ThriftCall(payload, name, seqid, mtype, ctx=ctx)
                try:
                    reply = await self.service(call)
                except Exception as e:  # noqa: BLE001 -> thrift exception
                    reply = encode_exception(name, seqid, repr(e))
                if not call.oneway and reply is not None:
                    if upgraded:
                        from linkerd_tpu.protocol.thrift import (
                            ttwitter as ttw,
                        )
                        reply = ttw.prepend_struct(
                            ttw.TResponseHeader(), reply)
                    write_framed(writer, reply)
                    await writer.drain()
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            log.exception("thrift connection handler error")
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass


async def serve_thrift(service: Service, host: str = "127.0.0.1",
                       port: int = 0) -> ThriftServer:
    return await ThriftServer(service, host, port).start()
