"""Thrift protocol: framed-transport proxying.

Ref: router/thrift (static ``Identifier.scala:34`` — one logical dst per
router), linkerd/protocol/thrift ThriftInitializer.scala:103 (protocol
framed|buffered, attemptTTwitterUpgrade). The router treats messages as
opaque framed payloads but parses the TBinaryProtocol header for the
method name + seqid (stats / response matching).
"""

from linkerd_tpu.protocol.thrift.codec import (
    ThriftCall, parse_message_header, read_framed, write_framed,
)
from linkerd_tpu.protocol.thrift.server import ThriftServer, serve_thrift
from linkerd_tpu.protocol.thrift.client import ThriftClient

__all__ = [
    "ThriftCall", "parse_message_header", "read_framed", "write_framed",
    "ThriftServer", "serve_thrift", "ThriftClient",
]
