"""Thrift transports + protocol message headers.

Framed transport: 4-byte big-endian length prefix per message. Buffered
(unframed) transport: no prefix — message boundaries come from skipping
the TBinaryProtocol struct (ref: ThriftInitializer.scala:68-72
``thriftFramed: false``).

TBinaryProtocol (strict) message header: i32 (VERSION_1 | type),
len-prefixed name, i32 seqid. TCompactProtocol message header: 0x82,
(type<<5 | 1), varint seqid, varint name-len, name (ref:
ThriftInitializer.scala:47 ``thriftProtocol``). The proxy only needs the
header — payloads pass through opaque (ref: router/thrift treats args as
unparsed).
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

VERSION_1 = 0x80010000
VERSION_MASK = 0xFFFF0000

CALL, REPLY, EXCEPTION, ONEWAY = 1, 2, 3, 4

MAX_FRAME = 16 * 1024 * 1024


class ThriftCodecError(Exception):
    pass


@dataclass
class ThriftCall:
    """One framed thrift message with its parsed header."""

    payload: bytes        # the full message (header + args)
    name: str
    seqid: int
    type: int
    ctx: Dict[str, object] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.ctx is None:
            self.ctx = {}

    @property
    def oneway(self) -> bool:
        return self.type == ONEWAY


def parse_message_header(payload: bytes) -> Tuple[str, int, int]:
    """-> (name, seqid, type). Supports strict and legacy encoding."""
    if len(payload) < 4:
        raise ThriftCodecError("message too short")
    first = struct.unpack(">i", payload[:4])[0]
    if first < 0:  # strict: version word
        # python's & on a negative int yields the positive masked value
        if (first & VERSION_MASK) != VERSION_1:
            raise ThriftCodecError(f"bad thrift version {first:#x}")
        mtype = first & 0xFF
        (nlen,) = struct.unpack(">I", payload[4:8])
        name = payload[8:8 + nlen].decode("utf-8")
        (seqid,) = struct.unpack(">i", payload[8 + nlen:12 + nlen])
        return name, seqid, mtype
    # legacy: len-prefixed name, byte type, i32 seqid
    nlen = first
    name = payload[4:4 + nlen].decode("utf-8")
    mtype = payload[4 + nlen]
    (seqid,) = struct.unpack(">i", payload[5 + nlen:9 + nlen])
    return name, seqid, mtype


def encode_exception(name: str, seqid: int, message: str) -> bytes:
    """A TApplicationException(INTERNAL_ERROR) reply frame."""
    nb = name.encode("utf-8")
    mb = message.encode("utf-8")
    out = struct.pack(">I", (VERSION_1 | EXCEPTION) & 0xFFFFFFFF)
    out += struct.pack(">I", len(nb)) + nb
    out += struct.pack(">i", seqid)
    # TApplicationException struct: field 1 message (string), field 2 type
    out += b"\x0b" + struct.pack(">hI", 1, len(mb)) + mb
    out += b"\x08" + struct.pack(">hi", 2, 6)  # INTERNAL_ERROR = 6
    out += b"\x00"  # stop
    return out


COMPACT_PROTOCOL_ID = 0x82
COMPACT_VERSION = 1


def _cvarint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def encode_exception_compact(name: str, seqid: int, message: str) -> bytes:
    """A TApplicationException(INTERNAL_ERROR) reply in TCompactProtocol
    (the binary-protocol encoder would desync compact clients)."""
    nb = name.encode("utf-8")
    mb = message.encode("utf-8")
    out = bytearray([COMPACT_PROTOCOL_ID,
                     (EXCEPTION << 5) | COMPACT_VERSION])
    out += _cvarint(seqid) + _cvarint(len(nb)) + nb
    # compact struct: field 1 message (BINARY=8), field 2 type (I32=5)
    out += bytes([(1 << 4) | 8]) + _cvarint(len(mb)) + mb
    out += bytes([(1 << 4) | 5]) + _cvarint(6 << 1)  # zigzag(6)=12
    out += b"\x00"  # stop
    return bytes(out)


def encode_exception_for(protocol: str, name: str, seqid: int,
                         message: str) -> bytes:
    if protocol == "compact":
        return encode_exception_compact(name, seqid, message)
    return encode_exception(name, seqid, message)


def parse_compact_header(payload: bytes) -> Tuple[str, int, int]:
    """TCompactProtocol message header -> (name, seqid, type)."""
    if len(payload) < 4 or payload[0] != COMPACT_PROTOCOL_ID:
        raise ThriftCodecError("not a compact-protocol message")
    if (payload[1] & 0x1F) != COMPACT_VERSION:
        raise ThriftCodecError(f"bad compact version {payload[1]:#x}")
    mtype = (payload[1] >> 5) & 0x7

    def varint(pos: int) -> Tuple[int, int]:
        shift = v = 0
        while True:
            if pos >= len(payload) or shift > 35:
                raise ThriftCodecError("truncated varint")
            b = payload[pos]
            pos += 1
            v |= (b & 0x7F) << shift
            shift += 7
            if not (b & 0x80):
                return v, pos

    seqid, pos = varint(2)
    nlen, pos = varint(pos)
    name = payload[pos:pos + nlen].decode("utf-8")
    return name, seqid, mtype


def parse_header(payload: bytes, protocol: str = "binary"
                 ) -> Tuple[str, int, int]:
    if protocol == "compact":
        return parse_compact_header(payload)
    return parse_message_header(payload)


# TBinaryProtocol wire type ids (TType)
_T_STOP, _T_BOOL, _T_BYTE, _T_DOUBLE = 0, 2, 3, 4
_T_I16, _T_I32, _T_I64, _T_STRING = 6, 8, 10, 11
_T_STRUCT, _T_MAP, _T_SET, _T_LIST = 12, 13, 14, 15
_FIXED = {_T_BOOL: 1, _T_BYTE: 1, _T_DOUBLE: 8, _T_I16: 2, _T_I32: 4,
          _T_I64: 8}


def _skip_value(b: bytes, pos: int, ttype: int, depth: int = 0) -> int:
    """Skip one TBinaryProtocol value; -> new pos. Raises IndexError when
    truncated (caller treats as 'need more bytes')."""
    if depth > 32:
        raise ThriftCodecError("thrift struct nested too deep")
    fixed = _FIXED.get(ttype)
    if fixed is not None:
        if pos + fixed > len(b):
            raise IndexError
        return pos + fixed
    if ttype == _T_STRING:
        if pos + 4 > len(b):
            raise IndexError
        (n,) = struct.unpack_from(">I", b, pos)
        if n > MAX_FRAME:
            raise ThriftCodecError("string too long")
        if pos + 4 + n > len(b):
            raise IndexError
        return pos + 4 + n
    if ttype == _T_STRUCT:
        while True:
            if pos >= len(b):
                raise IndexError
            ft = b[pos]
            pos += 1
            if ft == _T_STOP:
                return pos
            if pos + 2 > len(b):
                raise IndexError
            pos = _skip_value(b, pos + 2, ft, depth + 1)  # +2: field id
    if ttype == _T_MAP:
        if pos + 6 > len(b):
            raise IndexError
        kt, vt = b[pos], b[pos + 1]
        (n,) = struct.unpack_from(">I", b, pos + 2)
        if n > MAX_FRAME:
            raise ThriftCodecError("map too long")
        pos += 6
        for _ in range(n):
            pos = _skip_value(b, pos, kt, depth + 1)
            pos = _skip_value(b, pos, vt, depth + 1)
        return pos
    if ttype in (_T_SET, _T_LIST):
        if pos + 5 > len(b):
            raise IndexError
        et = b[pos]
        (n,) = struct.unpack_from(">I", b, pos + 1)
        if n > MAX_FRAME:
            raise ThriftCodecError("list too long")
        pos += 5
        for _ in range(n):
            pos = _skip_value(b, pos, et, depth + 1)
        return pos
    raise ThriftCodecError(f"unknown thrift type {ttype}")


def message_length(buf: bytes) -> Optional[int]:
    """Byte length of the complete TBinaryProtocol message at the head of
    ``buf`` (header + args struct), or None when more bytes are needed —
    the unframed (buffered) transport's message-boundary scan."""
    try:
        if len(buf) < 4:
            return None
        first = struct.unpack_from(">i", buf, 0)[0]
        if first < 0:  # strict
            if (first & VERSION_MASK) != VERSION_1:
                raise ThriftCodecError(f"bad thrift version {first:#x}")
            if len(buf) < 8:
                return None
            (nlen,) = struct.unpack_from(">I", buf, 4)
            pos = 8 + nlen + 4  # name + seqid
        else:  # legacy
            nlen = first
            pos = 4 + nlen + 1 + 4  # name + type byte + seqid
        if nlen > MAX_FRAME:
            raise ThriftCodecError("name too long")
        if pos > len(buf):
            return None
        return _skip_value(buf, pos, _T_STRUCT)
    except IndexError:
        return None


class UnframedReader:
    """Accumulates stream bytes and yields whole unframed messages."""

    def __init__(self, reader: asyncio.StreamReader):
        self._reader = reader
        self._buf = bytearray()

    async def read_message(self) -> Optional[bytes]:
        """One complete message; None on clean EOF at a boundary."""
        while True:
            n = message_length(bytes(self._buf))
            if n is not None:
                msg = bytes(self._buf[:n])
                del self._buf[:n]
                return msg
            if len(self._buf) > MAX_FRAME:
                raise ThriftCodecError("unframed message exceeds max")
            chunk = await self._reader.read(65536)
            if not chunk:
                if self._buf:
                    raise ThriftCodecError("EOF mid-message (unframed)")
                return None
            self._buf += chunk


async def read_framed(reader: asyncio.StreamReader) -> Optional[bytes]:
    """One framed message; None on clean EOF."""
    try:
        head = await reader.readexactly(4)
    except asyncio.IncompleteReadError:
        return None
    (n,) = struct.unpack(">I", head)
    if n > MAX_FRAME:
        raise ThriftCodecError(f"frame of {n} bytes exceeds max")
    return await reader.readexactly(n)


def write_framed(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(struct.pack(">I", len(payload)) + payload)
