"""Thrift framed transport + TBinaryProtocol message header.

Framed transport: 4-byte big-endian length prefix per message.
TBinaryProtocol (strict) message header: i32 (VERSION_1 | type),
len-prefixed name, i32 seqid. The proxy only needs the header — payloads
pass through opaque (ref: router/thrift treats args as unparsed).
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

VERSION_1 = 0x80010000
VERSION_MASK = 0xFFFF0000

CALL, REPLY, EXCEPTION, ONEWAY = 1, 2, 3, 4

MAX_FRAME = 16 * 1024 * 1024


class ThriftCodecError(Exception):
    pass


@dataclass
class ThriftCall:
    """One framed thrift message with its parsed header."""

    payload: bytes        # the full message (header + args)
    name: str
    seqid: int
    type: int
    ctx: Dict[str, object] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.ctx is None:
            self.ctx = {}

    @property
    def oneway(self) -> bool:
        return self.type == ONEWAY


def parse_message_header(payload: bytes) -> Tuple[str, int, int]:
    """-> (name, seqid, type). Supports strict and legacy encoding."""
    if len(payload) < 4:
        raise ThriftCodecError("message too short")
    first = struct.unpack(">i", payload[:4])[0]
    if first < 0:  # strict: version word
        # python's & on a negative int yields the positive masked value
        if (first & VERSION_MASK) != VERSION_1:
            raise ThriftCodecError(f"bad thrift version {first:#x}")
        mtype = first & 0xFF
        (nlen,) = struct.unpack(">I", payload[4:8])
        name = payload[8:8 + nlen].decode("utf-8")
        (seqid,) = struct.unpack(">i", payload[8 + nlen:12 + nlen])
        return name, seqid, mtype
    # legacy: len-prefixed name, byte type, i32 seqid
    nlen = first
    name = payload[4:4 + nlen].decode("utf-8")
    mtype = payload[4 + nlen]
    (seqid,) = struct.unpack(">i", payload[5 + nlen:9 + nlen])
    return name, seqid, mtype


def encode_exception(name: str, seqid: int, message: str) -> bytes:
    """A TApplicationException(INTERNAL_ERROR) reply frame."""
    nb = name.encode("utf-8")
    mb = message.encode("utf-8")
    out = struct.pack(">I", (VERSION_1 | EXCEPTION) & 0xFFFFFFFF)
    out += struct.pack(">I", len(nb)) + nb
    out += struct.pack(">i", seqid)
    # TApplicationException struct: field 1 message (string), field 2 type
    out += b"\x0b" + struct.pack(">hI", 1, len(mb)) + mb
    out += b"\x08" + struct.pack(">hi", 2, 6)  # INTERNAL_ERROR = 6
    out += b"\x00"  # stop
    return out


async def read_framed(reader: asyncio.StreamReader) -> Optional[bytes]:
    """One framed message; None on clean EOF."""
    try:
        head = await reader.readexactly(4)
    except asyncio.IncompleteReadError:
        return None
    (n,) = struct.unpack(">I", head)
    if n > MAX_FRAME:
        raise ThriftCodecError(f"frame of {n} bytes exceeds max")
    return await reader.readexactly(n)


def write_framed(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(struct.pack(">I", len(payload)) + payload)
