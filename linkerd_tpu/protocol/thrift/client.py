"""Framed-thrift client: one pooled connection per endpoint, serial
request/response (framed thrift is not multiplexed — finagle pools
connections the same way; ref: ThriftClientPrep).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from linkerd_tpu.protocol.thrift.codec import (
    ThriftCall, read_framed, write_framed,
)
from linkerd_tpu.router.service import Service, Status

log = logging.getLogger(__name__)


class ThriftClient(Service[ThriftCall, Optional[bytes]]):
    def __init__(self, host: str, port: int, connect_timeout: float = 3.0,
                 attempt_ttwitter: bool = False, dest: str = "",
                 client_id: str = "", framed: bool = True,
                 protocol: str = "binary"):
        self.host = host
        self.port = port
        self.connect_timeout = connect_timeout
        # Negotiate the TTwitter upgrade on connect; on success every
        # request carries a RequestHeader with trace + dtab context
        # (ref: TTwitterClientFilter, attemptTTwitterUpgrade). The
        # upgrade protocol is framed-only.
        self.framed = framed
        self.protocol = protocol
        self.attempt_ttwitter = (attempt_ttwitter and framed
                                 and protocol == "binary")
        self.dest = dest
        self.client_id = client_id
        self._unframed_reader = None  # lazy UnframedReader (buffered)
        self._upgraded = False
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._closed = False
        self.pending = 0

    @property
    def status(self) -> Status:
        return Status.CLOSED if self._closed else Status.OPEN

    async def _ensure_conn(self) -> None:
        if self._closed:
            # close() may have run while this exchange queued on _lock;
            # reconnecting now would leak a socket past it
            raise ConnectionError(
                f"thrift client {self.host}:{self.port} closed")
        if self._writer is None or self._writer.is_closing():
            self._upgraded = False
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port),
                self.connect_timeout)
            if self._closed:
                # close() ran during the connect: abandon before
                # installing, or this exchange would dispatch on a
                # closed client and wedge close() behind the lock
                try:
                    writer.close()
                except (OSError, RuntimeError):
                    pass
                raise ConnectionError(
                    f"thrift client {self.host}:{self.port} closed")
            self._reader, self._writer = reader, writer
            if not self.framed:
                from linkerd_tpu.protocol.thrift.codec import UnframedReader
                self._unframed_reader = UnframedReader(self._reader)
            if self.attempt_ttwitter:
                await self._try_upgrade()

    async def _try_upgrade(self) -> None:
        from linkerd_tpu.protocol.thrift import ttwitter as ttw
        from linkerd_tpu.protocol.thrift.codec import (
            REPLY, parse_message_header,
        )
        try:
            write_framed(self._writer, ttw.encode_upgrade_request(0))
            await self._writer.drain()
            reply = await asyncio.wait_for(
                read_framed(self._reader), self.connect_timeout)
            if reply is None:
                raise ConnectionError("closed during ttwitter upgrade")
            _, _, mtype = parse_message_header(reply)
            # EXCEPTION means a plain server: fall back silently
            self._upgraded = mtype == REPLY
        except Exception as e:  # noqa: BLE001
            # ANY failed probe leaves the connection desynced (its reply
            # may still be in flight and could be served to a later
            # caller) — never cache it
            self._teardown()
            raise ConnectionError(
                f"thrift backend lost during upgrade: {e!r}") from None

    def _wrap_request(self, call: ThriftCall) -> bytes:
        from linkerd_tpu.protocol.thrift import ttwitter as ttw
        header = ttw.mk_request_header(
            trace=call.ctx.get("trace"),
            dest=call.ctx.get("dest") or self.dest,
            dtab=call.ctx.get("dtab"),
            client_id=self.client_id)
        return ttw.prepend_struct(header, call.payload)

    async def __call__(self, call: ThriftCall) -> Optional[bytes]:
        self.pending += 1
        try:
            # serial per connection: frame pairs must not interleave
            async with self._lock:
                await self._ensure_conn()
                payload = (self._wrap_request(call) if self._upgraded
                           else call.payload)
                try:
                    if self.framed:
                        write_framed(self._writer, payload)
                    else:
                        self._writer.write(payload)
                    await self._writer.drain()
                    if call.oneway:
                        return None
                    reply = (await read_framed(self._reader)
                             if self.framed else
                             await self._unframed_reader.read_message())
                except (ConnectionResetError, BrokenPipeError,
                        asyncio.IncompleteReadError) as e:
                    self._teardown()
                    raise ConnectionError(f"thrift backend: {e}") from None
                except asyncio.CancelledError:
                    # canceled mid-exchange (e.g. total timeout): the
                    # connection has an in-flight reply -> unusable
                    self._teardown()
                    raise
                if reply is None:
                    self._teardown()
                    raise ConnectionError("thrift backend closed connection")
                if self._upgraded:
                    from linkerd_tpu.protocol.thrift import ttwitter as ttw
                    try:
                        _, reply = ttw.peel_struct(
                            ttw.TResponseHeader, reply)
                    except Exception:  # noqa: BLE001 — desynced
                        self._teardown()
                        raise ConnectionError(
                            "unparseable ttwitter response header")
                # Verify the reply matches this request; a mismatched
                # seqid means a stale/desynced exchange (never serve
                # caller A's payload to caller B).
                try:
                    from linkerd_tpu.protocol.thrift.codec import (
                        parse_header,
                    )
                    _, seqid, _ = parse_header(reply, self.protocol)
                except Exception:  # noqa: BLE001 - unparseable reply
                    self._teardown()
                    raise ConnectionError("unparseable thrift reply")
                if seqid != call.seqid:
                    self._teardown()
                    raise ConnectionError(
                        f"thrift seqid mismatch (got {seqid}, "
                        f"want {call.seqid})")
                return reply
        finally:
            self.pending -= 1

    def _teardown(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except (OSError, RuntimeError):  # transport already detached
                pass
        self._reader = self._writer = self._unframed_reader = None

    async def close(self) -> None:
        # flag first (outside the lock) so exchanges already queued on
        # it observe closure in _ensure_conn instead of reconnecting
        self._closed = True  # l5d: ignore[lock-guard] — monotonic flag set-before-lock: queued exchanges must see it when they win the lock
        # break any wedged in-flight exchange BEFORE waiting for the
        # lock: a peer that blackholes the reply would otherwise hold
        # the lock (and this close) forever. Closing the transport is a
        # read-only poke — the exchange's own error path runs teardown.
        w = self._writer
        if w is not None:
            try:
                w.close()
            except (OSError, RuntimeError):  # transport already detached
                pass
        async with self._lock:
            # serialize the final teardown with a dispatch that was
            # mid-connect when the flag published (its fresh writer
            # must not outlive close)
            self._teardown()  # l5d: ignore[await-atomicity] — the pre-lock read is a fail-fast alias only; this locked teardown re-nulls whatever generation is current
