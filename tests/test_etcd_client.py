"""The standalone etcd v2 client library against the scripted fake
(ref: etcd/.../{Etcd,Key,NodeOp}.scala + EtcdFixture-style tests)."""

import asyncio

import pytest

from linkerd_tpu.etcd import ApiError, EtcdClient, Node, NodeOp
from linkerd_tpu.protocol.http.server import HttpServer
from tests.test_remote_stores import FakeEtcd


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


class TestEtcdKeyOps:
    def test_set_get_cas_delete(self):
        async def go():
            fake = FakeEtcd()
            server = await HttpServer(fake.service()).start()
            etcd = EtcdClient("127.0.0.1", server.bound_port)
            try:
                key = etcd.key("/apps/web")
                op = await key.set("v1")
                assert op.node.value == "v1"
                idx = op.node.modified_index

                got = await key.get()
                assert got.node.value == "v1"
                assert got.node.modified_index == idx

                # CAS: stale prevIndex rejected with COMPARE_FAILED/412
                with pytest.raises(ApiError):
                    await key.set("v2", prev_index=idx - 5)
                await key.set("v2", prev_index=idx)
                assert (await key.get()).node.value == "v2"

                # prevExist=false on an existing key rejected
                with pytest.raises(ApiError):
                    await key.set("v3", prev_exist=False)

                # recursive dir listing flattens to leaves
                await etcd.key("/apps/api").set("v9")
                listing = await etcd.key("/apps").get(recursive=True)
                leaves = {n.key: n.value for n in listing.node.leaves()}
                assert leaves == {"/apps/web": "v2", "/apps/api": "v9"}

                await key.delete()
                with pytest.raises(ApiError) as ei:
                    await key.get()
                assert ei.value.status == 404
            finally:
                await server.close()

        run(go())

    def test_watch_initial_list_then_incremental(self):
        async def go():
            fake = FakeEtcd()
            fake.nodes["/apps/web"] = ("v1", fake.index)
            server = await HttpServer(fake.service()).start()
            etcd = EtcdClient("127.0.0.1", server.bound_port)
            ops = []
            got_initial = asyncio.Event()
            got_change = asyncio.Event()

            def on_op(op: NodeOp):
                ops.append(op)
                if op.action == "get":
                    got_initial.set()
                else:
                    got_change.set()

            watch = etcd.key("/apps").watch(on_op)
            try:
                await asyncio.wait_for(got_initial.wait(), 5)
                assert ops[0].node.leaves()[0].value == "v1"

                # external write arrives incrementally through the watch
                fake._record("set", "/apps/api", "v2")
                fake.nodes["/apps/api"] = ("v2", fake.index)
                await asyncio.wait_for(got_change.wait(), 5)
                change = ops[-1]
                assert change.action == "set"
                assert change.node.key == "/apps/api"
                assert change.node.value == "v2"
            finally:
                watch.stop()
                await server.close()

        run(go())
