"""Cross-interop with the official grpcio implementation.

Ref: grpc/interop — the reference runs the upstream gRPC interop suite
against its own stack (LocalInteropTest, NetworkedEndToEndTest). Here:
our server <- grpcio client, and our client -> grpcio server, over real
sockets, including error-status and server-streaming semantics.
"""

import asyncio
from concurrent import futures

import pytest

grpc = pytest.importorskip("grpc")

from linkerd_tpu.grpc import (  # noqa: E402
    ClientDispatcher, Field, GrpcError, ProtoMessage, Rpc,
    ServerDispatcher, ServiceDef,
)
from linkerd_tpu.protocol.h2.client import H2Client  # noqa: E402
from linkerd_tpu.protocol.h2.server import H2Server  # noqa: E402


class Echo(ProtoMessage):
    FIELDS = {"text": Field(1, "string"), "n": Field(2, "int32")}


SVC = ServiceDef("interop.Echo", [
    Rpc("Say", Echo, Echo),
    Rpc("Count", Echo, Echo, server_streaming=True),
])

# grpcio generic handlers use raw bytes with our wire-compatible codec
def _ser(msg: Echo) -> bytes:
    return msg.encode()


def _deser(raw: bytes) -> Echo:
    return Echo.decode(raw)


class TestGrpcioClientAgainstOurServer:
    def test_unary_stream_and_error(self):
        loop = asyncio.new_event_loop()
        disp = ServerDispatcher()

        async def say(req: Echo) -> Echo:
            if req.text == "nope":
                raise GrpcError.of(5, "not here")
            return Echo(text=f"hi {req.text}")

        async def count(req: Echo):
            async def gen():
                for i in range(req.n):
                    yield Echo(n=i)
            return gen()

        disp.register_all(SVC, {"Say": say, "Count": count})
        server = loop.run_until_complete(H2Server(disp).start())
        port = server.bound_port

        def client_work():
            channel = grpc.insecure_channel(f"127.0.0.1:{port}")
            say_rpc = channel.unary_unary(
                "/interop.Echo/Say", request_serializer=_ser,
                response_deserializer=_deser)
            rep = say_rpc(Echo(text="grpcio"), timeout=10)
            assert rep.text == "hi grpcio"

            with pytest.raises(grpc.RpcError) as ei:
                say_rpc(Echo(text="nope"), timeout=10)
            assert ei.value.code() == grpc.StatusCode.NOT_FOUND
            assert "not here" in ei.value.details()

            count_rpc = channel.unary_stream(
                "/interop.Echo/Count", request_serializer=_ser,
                response_deserializer=_deser)
            got = [m.n for m in count_rpc(Echo(n=4), timeout=10)]
            assert got == [0, 1, 2, 3]
            channel.close()

        # grpcio is blocking: run it in a thread while our loop serves
        task = loop.run_in_executor(None, client_work)
        loop.run_until_complete(asyncio.wait_for(task, 30))
        loop.run_until_complete(server.close())
        loop.close()


class TestOurClientAgainstGrpcioServer:
    def test_unary_stream_and_error(self):
        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == "/interop.Echo/Say":
                    def say(req, ctx):
                        if req.text == "nope":
                            ctx.abort(grpc.StatusCode.NOT_FOUND, "not here")
                        return Echo(text=f"srv {req.text}")
                    return grpc.unary_unary_rpc_method_handler(
                        say, request_deserializer=_deser,
                        response_serializer=_ser)
                if details.method == "/interop.Echo/Count":
                    def count(req, ctx):
                        for i in range(req.n):
                            yield Echo(n=i * 10)
                    return grpc.unary_stream_rpc_method_handler(
                        count, request_deserializer=_deser,
                        response_serializer=_ser)
                return None

        server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        server.add_generic_rpc_handlers([Handler()])
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()

        async def go():
            client = ClientDispatcher(H2Client("127.0.0.1", port))
            rep = await client.unary(SVC, "Say", Echo(text="ours"))
            assert rep.text == "srv ours"

            with pytest.raises(GrpcError) as ei:
                await client.unary(SVC, "Say", Echo(text="nope"))
            assert ei.value.status.code == 5
            assert "not here" in ei.value.status.message

            reps = await client.server_stream(SVC, "Count", Echo(n=3))
            got = [m.n async for m in reps]
            assert got == [0, 10, 20]
            assert reps.status.ok
            await client._svc.close()

        asyncio.run(asyncio.wait_for(go(), 30))
        server.stop(None)


class Payload(ProtoMessage):
    FIELDS = {"body": Field(1, "bytes")}


PING_SVC = ServiceDef("interop.PingPong", [
    Rpc("LargeUnary", Payload, Payload),
    Rpc("ClientStream", Payload, Payload, client_streaming=True),
    Rpc("PingPong", Payload, Payload,
        client_streaming=True, server_streaming=True),
    Rpc("EmptyStream", Payload, Payload,
        client_streaming=True, server_streaming=True),
])


class TestCanonicalInteropCases:
    """The canonical interop-suite shapes (ref: grpc/interop — the
    reference runs the upstream suite): large_unary (271828/314159-byte
    payloads), client_streaming aggregation, ping_pong full duplex,
    empty_stream."""

    def test_canonical_cases_grpcio_client(self):
        loop = asyncio.new_event_loop()
        disp = ServerDispatcher()

        async def large_unary(req: Payload) -> Payload:
            assert len(req.body) == 271828
            return Payload(body=b"\0" * 314159)

        async def client_stream(reqs) -> Payload:
            total = 0
            async for r in reqs:
                total += len(r.body)
            return Payload(body=str(total).encode())

        async def ping_pong(reqs):
            async def gen():
                async for r in reqs:
                    yield Payload(body=r.body[::-1])
            return gen()

        async def empty_stream(reqs):
            async def gen():
                async for _ in reqs:
                    pass
                return
                yield  # pragma: no cover — makes this an async generator
            return gen()

        disp.register_all(PING_SVC, {
            "LargeUnary": large_unary, "ClientStream": client_stream,
            "PingPong": ping_pong, "EmptyStream": empty_stream})

        async def serve():
            return await H2Server(disp).start()

        server = loop.run_until_complete(serve())
        port = server.bound_port
        import threading
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        try:
            ch = grpc.insecure_channel(f"127.0.0.1:{port}")
            # large_unary: canonical 271828 -> 314159 byte payloads
            lu = ch.unary_unary(
                "/interop.PingPong/LargeUnary",
                request_serializer=lambda m: m.encode(),
                response_deserializer=Payload.decode)
            rsp = lu(Payload(body=b"\x5a" * 271828), timeout=10)
            assert len(rsp.body) == 314159

            # client_streaming: sizes 27182, 8, 1828, 45904 aggregate
            cs = ch.stream_unary(
                "/interop.PingPong/ClientStream",
                request_serializer=lambda m: m.encode(),
                response_deserializer=Payload.decode)
            sizes = [27182, 8, 1828, 45904]
            rsp = cs(iter([Payload(body=b"a" * n) for n in sizes]),
                     timeout=10)
            assert rsp.body == str(sum(sizes)).encode()

            # ping_pong: full-duplex request/response alternation
            pp = ch.stream_stream(
                "/interop.PingPong/PingPong",
                request_serializer=lambda m: m.encode(),
                response_deserializer=Payload.decode)
            got = list(pp(iter([Payload(body=b"abc"),
                                Payload(body=b"wxyz")]), timeout=10))
            assert [g.body for g in got] == [b"cba", b"zyxw"]

            # empty_stream: zero messages both directions, clean OK
            es = ch.stream_stream(
                "/interop.PingPong/EmptyStream",
                request_serializer=lambda m: m.encode(),
                response_deserializer=Payload.decode)
            assert list(es(iter([]), timeout=10)) == []
            ch.close()
        finally:
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=5)
            loop.run_until_complete(server.close())
            loop.close()
