"""PathMatcher, per-prefix static client/svc config, and TLS both sides.

Reference parity: finagle/buoyant PathMatcher.scala unit behavior;
linkerd/core Client.scala/Svc.scala static per-prefix configs; the TLS
integration tests (linkerd/protocol/http/src/integration/.../TlsUtils.scala
shells out for certs; TlsTerminationTest / TlsStaticValidationTest).
"""

import asyncio
import ssl
import subprocess

import pytest

from linkerd_tpu.core.path import Path
from linkerd_tpu.core.pathmatcher import PathMatcher
from linkerd_tpu.linker import ClientSpec, SvcSpec, load_linker, per_prefix_lookup
from linkerd_tpu.protocol.http import Request, Response
from linkerd_tpu.protocol.http.client import HttpClient
from linkerd_tpu.protocol.http.server import HttpServer, serve
from linkerd_tpu.protocol.tls import TlsClientConfig, TlsServerConfig
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


class TestPathMatcher:
    def test_literal_prefix(self):
        m = PathMatcher("/svc/web")
        assert m.matches(Path.read("/svc/web"))
        assert m.matches(Path.read("/svc/web/extra"))
        assert not m.matches(Path.read("/svc/db"))
        assert not m.matches(Path.read("/svc"))

    def test_capture_and_wildcard(self):
        m = PathMatcher("/#/io.l5d.fs/{service}")
        assert m.extract(Path.read("/#/io.l5d.fs/web")) == {"service": "web"}
        assert m.extract(Path.read("/#/other/web")) is None
        w = PathMatcher("/svc/*/admin")
        assert w.matches(Path.read("/svc/anything/admin"))
        assert not w.matches(Path.read("/svc/anything/user"))

    def test_substitute(self):
        m = PathMatcher("/#/io.l5d.fs/{service}")
        assert (m.substitute(Path.read("/#/io.l5d.fs/web"),
                             "{service}.example.com")
                == "web.example.com")
        assert m.substitute(Path.read("/nope"), "{service}.x") is None
        # unresolved var -> None
        assert m.substitute(Path.read("/#/io.l5d.fs/web"), "{other}.x") is None


class TestPerPrefixLookup:
    def test_plain_mapping_applies_everywhere(self):
        lookup = per_prefix_lookup({"hostConnectionPool": 7}, ClientSpec, "t")
        spec, vars_ = lookup(Path.read("/anything"))
        assert spec.hostConnectionPool == 7
        assert vars_ == {}

    def test_static_merges_in_order(self):
        raw = {
            "kind": "io.l5d.static",
            "configs": [
                {"prefix": "/#/io.l5d.fs", "hostConnectionPool": 4},
                {"prefix": "/#/io.l5d.fs/{service}", "connectTimeoutMs": 99},
            ],
        }
        lookup = per_prefix_lookup(raw, ClientSpec, "t")
        spec, vars_ = lookup(Path.read("/#/io.l5d.fs/web"))
        assert spec.hostConnectionPool == 4       # first match
        assert spec.connectTimeoutMs == 99        # second overlays
        assert vars_ == {"service": "web"}
        spec2, _ = lookup(Path.read("/#/io.l5d.fs"))
        assert spec2.hostConnectionPool == 4
        assert spec2.connectTimeoutMs == 3000     # default, no second match
        spec3, _ = lookup(Path.read("/#/elsewhere"))
        assert spec3.hostConnectionPool == 64     # defaults only

    def test_per_path_service_policy(self):
        raw = {
            "kind": "io.l5d.static",
            "configs": [{"prefix": "/svc/slow", "totalTimeoutMs": 1234}],
        }
        lookup = per_prefix_lookup(raw, SvcSpec, "t")
        assert lookup(Path.read("/svc/slow"))[0].totalTimeoutMs == 1234
        assert lookup(Path.read("/svc/fast"))[0].totalTimeoutMs is None


class TestLoadTimeValidation:
    def test_bad_classifier_kind_fails_startup(self, tmp_path):
        cfg = """
routers:
- protocol: http
  dtab: "/svc => /$/inet/127.0.0.1/1 ;"
  service:
    responseClassifier: {kind: io.l5d.typo}
"""
        from linkerd_tpu.config import ConfigError
        with pytest.raises(ConfigError):
            load_linker(cfg)

    def test_static_entry_field_typo_fails_startup(self):
        cfg = """
routers:
- protocol: http
  dtab: "/svc => /$/inet/127.0.0.1/1 ;"
  client:
    kind: io.l5d.static
    configs:
    - prefix: /#/never-matched
      connectTimeoutMS: 5
"""
        from linkerd_tpu.config import ConfigError
        with pytest.raises(ConfigError):
            load_linker(cfg)

    def test_static_unknown_toplevel_key_fails(self):
        cfg = """
routers:
- protocol: http
  dtab: "/svc => /$/inet/127.0.0.1/1 ;"
  client:
    kind: io.l5d.static
    tls: {commonName: x}
    configs: []
"""
        from linkerd_tpu.config import ConfigError
        with pytest.raises(ConfigError):
            load_linker(cfg)

    def test_unresolved_common_name_var_raises(self):
        from linkerd_tpu.config import ConfigError
        tls = TlsClientConfig(commonName="{service}.example.com")
        with pytest.raises(ConfigError):
            tls.server_hostname({})


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """Self-signed cert for CN=web (SAN web, localhost) like TlsUtils."""
    d = tmp_path_factory.mktemp("certs")
    cert, key = d / "cert.pem", d / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "2",
         "-subj", "/CN=web",
         "-addext", "subjectAltName=DNS:web,DNS:localhost,IP:127.0.0.1"],
        check=True, capture_output=True)
    return str(cert), str(key)


def tls_downstream(name: str, certs):
    cert, key = certs

    async def handler(req: Request) -> Response:
        return Response(status=200, body=name.encode())

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    return HttpServer(FnService(handler), ssl_context=ctx)


class TestTls:
    def test_client_originates_tls_with_cn_substitution(self, certs, tmp_path):
        """Router speaks TLS to the downstream, verifying against the CA
        with a commonName substituted from the client prefix capture."""
        cert, _key = certs
        disco = tmp_path / "disco"
        disco.mkdir()

        cfg = f"""
routers:
- protocol: http
  label: tlsout
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
  client:
    kind: io.l5d.static
    configs:
    - prefix: "/#/io.l5d.fs/{{service}}"
      tls:
        commonName: "{{service}}"
        trustCerts: ["{cert}"]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""

        async def go():
            down = tls_downstream("secure-web", certs)
            await down.start()
            (disco / "web").write_text(f"127.0.0.1 {down.bound_port}\n")
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "web")
                r = await proxy(req)
                assert (r.status, r.body) == (200, b"secure-web")
            finally:
                await proxy.close()
                await linker.close()
                await down.close()

        run(go())

    def test_server_terminates_tls(self, certs, tmp_path):
        cert, key = certs
        disco = tmp_path / "disco"
        disco.mkdir()

        cfg = f"""
routers:
- protocol: http
  label: tlsin
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
    tls:
      certPath: "{cert}"
      keyPath: "{key}"
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""

        async def go():
            down = await serve(FnService(
                lambda req: _ok(b"plain-web")))
            (disco / "web").write_text(f"127.0.0.1 {down.bound_port}\n")
            linker = load_linker(cfg)
            await linker.start()
            cctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            cctx.load_verify_locations(cafile=cert)
            proxy = HttpClient(
                "127.0.0.1", linker.routers[0].server_ports[0],
                ssl_context=cctx, server_hostname="web")
            try:
                req = Request(uri="/")
                req.headers.set("Host", "web")
                r = await proxy(req)
                assert (r.status, r.body) == (200, b"plain-web")
            finally:
                await proxy.close()
                await linker.close()
                await down.close()

        run(go())

    def test_static_validation_failure(self, certs, tmp_path):
        """Wrong commonName -> handshake fails -> 502 from the router
        (ref: TlsStaticValidationTest)."""
        cert, _key = certs
        disco = tmp_path / "disco"
        disco.mkdir()

        cfg = f"""
routers:
- protocol: http
  label: tlsbad
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
  client:
    tls:
      commonName: "not-the-right-name"
      trustCerts: ["{cert}"]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""

        async def go():
            down = tls_downstream("x", certs)
            await down.start()
            (disco / "web").write_text(f"127.0.0.1 {down.bound_port}\n")
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "web")
                r = await proxy(req)
                assert r.status >= 500
            finally:
                await proxy.close()
                await linker.close()
                await down.close()

        run(go())


async def _ok(body: bytes) -> Response:
    return Response(status=200, body=body)


class TestH2OverTls:
    def test_h2_alpn_end_to_end(self, certs):
        """h2 over TLS with ALPN negotiation, client verifying the server
        cert (ref: finagle/h2/src/e2e/.../TlsEndToEndTest.scala)."""
        from linkerd_tpu.protocol.h2.client import H2Client
        from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
        from linkerd_tpu.protocol.h2.server import H2Server

        cert, key = certs

        async def handler(req: H2Request) -> H2Response:
            body, _ = await req.stream.read_all()
            return H2Response(status=200, body=b"tls:" + body)

        async def go():
            sctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            sctx.load_cert_chain(cert, key)
            server = await H2Server(FnService(handler),
                                    ssl_context=sctx).start()

            cctx = ssl.create_default_context(cafile=cert)
            client = H2Client("127.0.0.1", server.bound_port,
                              ssl_context=cctx, server_hostname="web")
            try:
                rsp = await client(H2Request(
                    method="POST", path="/s", authority="web",
                    body=b"hello"))
                body, _ = await rsp.stream.read_all()
                assert body == b"tls:hello"
                # the negotiated protocol must actually be h2 (ALPN)
                transport = client._conn._writer.transport
                sslobj = transport.get_extra_info("ssl_object")
                assert sslobj is not None
                assert sslobj.selected_alpn_protocol() == "h2"
            finally:
                await client.close()
                await server.close()

        run(go())
