"""Tests for the anomaly model, feature extraction, and sharded steps."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from linkerd_tpu.models import (
    FEATURE_DIM, FeatureVector, featurize,
    AnomalyModelConfig, init_params, apply_model, anomaly_scores, loss_fn,
)
from linkerd_tpu.models.features import featurize_batch
from linkerd_tpu.parallel import (
    make_mesh, make_train_step, make_score_step,
)
from linkerd_tpu.parallel.mesh import init_sharded, shard_params

CFG = AnomalyModelConfig()


class TestFeatures:
    def test_shape_and_bias(self):
        x = featurize(FeatureVector(latency_ms=12.0, status=503))
        assert x.shape == (FEATURE_DIM,)
        assert x.dtype == np.float32
        assert x[31] == 1.0

    def test_status_one_hot(self):
        x = featurize(FeatureVector(status=503))
        assert x[5] == 1.0  # 5xx bucket
        assert x[1] == 0.0
        x2 = featurize(FeatureVector(status=200))
        assert x2[2] == 1.0

    def test_path_hashing_stable_and_distinct(self):
        a1 = featurize(FeatureVector(dst_path="/svc/users"))
        a2 = featurize(FeatureVector(dst_path="/svc/users"))
        b = featurize(FeatureVector(dst_path="/svc/orders"))
        assert (a1 == a2).all()
        assert not (a1 == b).all()

    def test_batch(self):
        xs = featurize_batch([FeatureVector(), FeatureVector(status=500)])
        assert xs.shape == (2, FEATURE_DIM)

    def test_batch_bit_identical_to_per_row(self):
        """The vectorized batch encoder is an optimization of
        ``featurize``, not a second schema: it must agree bit-for-bit
        on every column, including edge values (negative sizes,
        out-of-range statuses, signed drift)."""
        rng = np.random.default_rng(7)
        fvs = [FeatureVector(
            latency_ms=float(rng.uniform(-5, 5000)),
            status=int(rng.integers(0, 700)),
            retries=int(rng.integers(0, 4)),
            request_bytes=int(rng.integers(-10, 10**6)),
            response_bytes=int(rng.integers(0, 10**6)),
            concurrency=int(rng.integers(0, 100)),
            ewma_ms=float(rng.uniform(0, 100)),
            queue_ms=float(rng.uniform(-1, 10)),
            exception=bool(rng.integers(0, 2)),
            retryable=bool(rng.integers(0, 2)),
            dst_path=f"/svc/s{int(rng.integers(0, 20))}",
            dst_rps=float(rng.uniform(0, 10**4)),
            lat_drift_ms=float(rng.uniform(-500, 500)),
        ) for _ in range(256)]
        batch = featurize_batch(fvs)
        ref = np.stack([featurize(fv) for fv in fvs])
        assert (batch == ref).all()


class TestModel:
    def test_forward_shapes(self):
        params = init_params(jax.random.key(0), CFG)
        x = jnp.ones((8, FEATURE_DIM))
        recon, z, logits = apply_model(params, x, CFG)
        assert recon.shape == (8, FEATURE_DIM)
        assert z.shape == (8, CFG.bottleneck)
        assert logits.shape == (8,)

    def test_scores_in_unit_interval(self):
        params = init_params(jax.random.key(0), CFG)
        x = jax.random.normal(jax.random.key(1), (16, FEATURE_DIM))
        s = anomaly_scores(params, x, CFG)
        assert s.shape == (16,)
        assert bool(jnp.all(s >= 0.0)) and bool(jnp.all(s <= 1.0))

    def test_loss_finite_and_mask_works(self):
        params = init_params(jax.random.key(0), CFG)
        x = jax.random.normal(jax.random.key(1), (8, FEATURE_DIM))
        labels = jnp.zeros(8)
        # fully unlabeled: loss is recon-only and finite
        l0 = loss_fn(params, x, labels, jnp.zeros(8), CFG)
        l1 = loss_fn(params, x, labels, jnp.ones(8), CFG)
        assert jnp.isfinite(l0) and jnp.isfinite(l1)
        assert float(l1) > float(l0)  # BCE adds loss

    def test_training_reduces_loss(self):
        """A few steps of the real sharded train step reduce loss on a
        fixed batch (8 virtual devices; tp=2 forced to keep the
        model-axis path covered now that make_mesh defaults pure-data
        at this width)."""
        mesh = make_mesh(tp=2)
        assert mesh.devices.size == 8
        assert dict(mesh.shape) == {"data": 4, "model": 2}
        opt = optax.adam(1e-3)
        params, opt_state = init_sharded(mesh, jax.random.key(0), opt, CFG)
        step = make_train_step(mesh, opt, CFG)
        x = jax.random.normal(jax.random.key(1), (64, FEATURE_DIM))
        labels = (jax.random.uniform(jax.random.key(2), (64,)) > 0.8).astype(
            jnp.float32)
        mask = jnp.ones(64)
        losses = []
        for _ in range(12):
            params, opt_state, loss = step(params, opt_state, x, labels, mask)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_sharded_score_matches_single_device(self):
        # both mesh shapes: the pure-data default and forced tp=2
        for tp in (None, 2):
            mesh = make_mesh(tp=tp)
            if tp is None:  # width heuristic: pure data at MLP scale
                assert dict(mesh.shape) == {"data": 8, "model": 1}
            params = init_params(jax.random.key(0), CFG)
            x = jax.random.normal(jax.random.key(1), (32, FEATURE_DIM))
            ref = anomaly_scores(params, x, CFG)
            sharded = shard_params(mesh, params)
            score = make_score_step(mesh, CFG)
            got = score(sharded, x)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       atol=2e-2, rtol=2e-2)

    def test_trained_ae_separates_anomalies(self):
        """Autoencoder trained on 'normal' traffic scores shifted
        anomalous traffic higher (the AUC mechanism, unsupervised)."""
        cfg = AnomalyModelConfig(recon_weight=1.0)  # recon-only
        mesh = make_mesh()
        opt = optax.adam(3e-3)
        params, opt_state = init_sharded(mesh, jax.random.key(0), opt, cfg)
        step = make_train_step(mesh, opt, cfg)
        key = jax.random.key(42)
        normal = 0.1 * jax.random.normal(key, (256, FEATURE_DIM)) + 0.5
        zeros = jnp.zeros(256)
        for _ in range(60):
            params, opt_state, _ = step(params, opt_state, normal, zeros, zeros)
        anomalous = normal + 2.0  # shifted distribution
        s_norm = anomaly_scores(params, normal[:64], cfg)
        s_anom = anomaly_scores(params, anomalous[:64], cfg)
        assert float(jnp.mean(s_anom)) > float(jnp.mean(s_norm))


class TestDeviceNormalization:
    """normalize_features folded into the jitted steps (ADVICE r5): the
    device path with raw features + mu/var must match host-side z-score
    then score, on both the sharded and fused/XLA single-chip paths."""

    def _host_norm(self, x, mu, var):
        return (np.asarray(x) - mu) / np.sqrt(var + 1e-2)

    def test_sharded_score_normalizes_on_device(self):
        mesh = make_mesh()
        params = init_params(jax.random.key(0), CFG)
        rng = np.random.default_rng(7)
        x = rng.standard_normal((32, FEATURE_DIM)).astype(np.float32) * 40 + 5
        mu = x.mean(axis=0)
        var = x.var(axis=0)
        ref = anomaly_scores(params, jnp.asarray(
            self._host_norm(x, mu, var), jnp.float32), CFG)
        score = make_score_step(mesh, CFG)
        got = score(shard_params(mesh, params), jnp.asarray(x),
                    jnp.asarray(mu), jnp.asarray(var))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-2, rtol=2e-2)

    def test_best_scorer_normalizes_on_device(self):
        from linkerd_tpu.ops.scoring import best_scorer
        params = init_params(jax.random.key(0), CFG)
        rng = np.random.default_rng(8)
        x = rng.standard_normal((64, FEATURE_DIM)).astype(np.float32) * 10
        mu = x.mean(axis=0)
        var = x.var(axis=0)
        ref = anomaly_scores(params, jnp.asarray(
            self._host_norm(x, mu, var), jnp.float32), CFG)
        scorer = best_scorer(CFG)
        got = scorer(params, jnp.asarray(x), jnp.asarray(mu),
                     jnp.asarray(var))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-2, rtol=2e-2)

    def test_train_step_normalizes_on_device(self):
        """Training with raw x + mu/var must move loss the same way as
        training on pre-normalized input (same objective)."""
        mesh = make_mesh()
        opt = optax.adam(1e-3)
        rng = np.random.default_rng(9)
        x = rng.standard_normal((64, FEATURE_DIM)).astype(np.float32) * 20
        mu = x.mean(axis=0)
        var = x.var(axis=0)
        labels = np.zeros(64, np.float32)
        step = make_train_step(mesh, opt, CFG)
        params, opt_state = init_sharded(mesh, jax.random.key(0), opt, CFG)
        _, _, loss_dev = step(params, opt_state, jnp.asarray(x),
                              jnp.asarray(labels), jnp.asarray(labels),
                              None, jnp.asarray(mu), jnp.asarray(var))
        params2, opt_state2 = init_sharded(mesh, jax.random.key(0), opt, CFG)
        _, _, loss_host = step(params2, opt_state2, jnp.asarray(
            self._host_norm(x, mu, var), jnp.float32),
            jnp.asarray(labels), jnp.asarray(labels))
        np.testing.assert_allclose(float(loss_dev), float(loss_host),
                                   rtol=2e-2)
