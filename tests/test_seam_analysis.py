"""l5dseam self-tests: every seam rule fires on the checked-in drifted
fixture tree, stays quiet on the matching clean tree, C-comment
suppressions work (and require justification), and the real tree's
seam is contract-clean (the tier-1 gate).

The fixture trees under ``tests/fixtures/seam/`` are the real seam in
miniature — an ``extern "C"`` header, a ctypes table, a config plane —
checked in rather than generated so the drift the analyzer must catch
is reviewable by eye. ``drift/`` is ``good/`` with every contract
violated once; the mini manifest below points the rules at them.
"""

import json
import os
import shutil
import subprocess
import sys

from tools.analysis.seam import (
    ConstPair, Knob, SeamManifest, Site, run_seam_analysis, seam_rule_ids,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "seam")
GOOD = os.path.join(FIXTURES, "good")
DRIFT = os.path.join(FIXTURES, "drift")


def mini_manifest(declare_frame_data=True, window_knob=False):
    """The fixture trees' declared contract. The drift tree leaves
    FRAME_DATA undeclared (near-miss bait) and documents a window knob
    it never plumbs."""
    pairs = [ConstPair(
        "FEATURE_DIM",
        (Site("py-const", "pybind.py", "FEATURE_DIM"),
         Site("c-const", "native/engine.h", "FEATURE_DIM")))]
    if declare_frame_data:
        pairs.append(ConstPair(
            "FRAME_DATA",
            (Site("py-const", "pybind.py", "FRAME_DATA"),
             Site("c-const", "native/engine.h", "FRAME_DATA"))))
    knobs = [Knob("engine.limit", "controller.py",
                  r"limit: max rows", ("set_limit",))]
    if window_knob:
        knobs.append(Knob("engine.window", "controller.py",
                          r"window: scoring window", ("set_window",)))
    return SeamManifest(
        abi_sources=("native/engine.h",),
        binding="pybind.py",
        const_pairs=tuple(pairs),
        near_miss_c=("native/engine.h",),
        near_miss_py_roots=("pybind.py",),
        emitters=(("native/engine.h", "fp_stats_json"),),
        scrape_files=("controller.py",),
        knob_scope=("controller.py",),
        knobs=tuple(knobs),
    )


def drift_findings(rule=None):
    out = run_seam_analysis(
        repo_root=DRIFT,
        manifest=mini_manifest(declare_frame_data=False,
                               window_knob=True))
    return [f for f in out if rule is None or f.rule == rule]


class TestGoodTree:
    def test_clean_tree_has_zero_findings(self):
        out = run_seam_analysis(repo_root=GOOD, manifest=mini_manifest())
        assert out == [], "\n" + "\n".join(f.show() for f in out)

    def test_rule_filter_runs_only_that_rule(self):
        out = run_seam_analysis(
            repo_root=DRIFT,
            manifest=mini_manifest(declare_frame_data=False,
                                   window_knob=True),
            rules=["stats-contract"])
        assert out and all(f.rule == "stats-contract" for f in out)

    def test_rule_ids_are_the_four_rules(self):
        assert seam_rule_ids() == ["abi-signature", "const-parity",
                                   "knob-plumbing", "stats-contract"]


class TestAbiSignature:
    def test_width_drift_is_caught(self):
        got = [f for f in drift_findings("abi-signature")
               if "type-width mismatch" in f.message]
        assert len(got) == 1, got
        assert "fp_set_limit" in got[0].message
        assert "i32" in got[0].message and "i64" in got[0].message
        assert got[0].path == "pybind.py"

    def test_arity_drift_is_caught(self):
        got = [f for f in drift_findings("abi-signature")
               if "arity mismatch" in f.message]
        assert len(got) == 1 and "fp_push" in got[0].message, got
        assert "2 argument(s)" in got[0].message
        assert "3" in got[0].message

    def test_unbound_export_is_caught(self):
        got = [f for f in drift_findings("abi-signature")
               if "no ctypes declaration" in f.message
               and not f.suppressed]
        assert len(got) == 1 and "fp_flush" in got[0].message, got
        assert got[0].path == "native/engine.h"

    def test_binding_to_removed_symbol_is_caught(self):
        got = [f for f in drift_findings("abi-signature")
               if "removed or renamed" in f.message]
        assert len(got) == 1 and "fp_gc" in got[0].message, got

    def test_justified_c_suppression_waives(self):
        got = [f for f in drift_findings("abi-signature")
               if "fp_reset" in f.message]
        assert len(got) == 1 and got[0].suppressed, got
        assert "out-of-tree caller" in got[0].justification

    def test_matching_widths_stay_quiet(self):
        out = run_seam_analysis(repo_root=GOOD, manifest=mini_manifest(),
                                rules=["abi-signature"])
        assert out == []


class TestConstParity:
    def test_mirrored_constant_drift_is_caught(self):
        got = [f for f in drift_findings("const-parity")
               if "disagrees across the seam" in f.message]
        assert len(got) == 1 and "FEATURE_DIM" in got[0].message, got
        assert "8" in got[0].message and "16" in got[0].message

    def test_undeclared_mirror_is_a_near_miss(self):
        got = [f for f in drift_findings("const-parity")
               if "undeclared mirror" in f.message]
        assert len(got) == 1 and "FRAME_DATA" in got[0].message, got
        # same value on both sides today — flagged anyway, because the
        # manifest is what makes tomorrow's drift visible
        assert "values currently agree" in got[0].message

    def test_manifest_rot_is_a_finding_not_a_skip(self):
        pairs = (ConstPair(
            "GONE",
            (Site("py-const", "pybind.py", "GONE"),
             Site("c-const", "native/engine.h", "GONE"))),)
        out = run_seam_analysis(
            repo_root=GOOD,
            manifest=SeamManifest(
                abi_sources=("native/engine.h",), binding="pybind.py",
                const_pairs=pairs),
            rules=["const-parity"])
        assert len(out) == 2, out
        assert all("extraction failed" in f.message for f in out)


class TestStatsContract:
    def test_renamed_stat_is_caught_in_both_directions(self):
        got = drift_findings("stats-contract")
        dead = [f for f in got if "scraped nowhere" in f.message]
        ghost = [f for f in got if "emitted by no engine" in f.message]
        assert len(dead) == 1 and "'drops'" in dead[0].message, got
        assert dead[0].path == "native/engine.h"
        assert len(ghost) == 1 and "'dropped'" in ghost[0].message, got
        assert ghost[0].path == "controller.py"

    def test_agreeing_contract_stays_quiet(self):
        out = run_seam_analysis(repo_root=GOOD, manifest=mini_manifest(),
                                rules=["stats-contract"])
        assert out == []


class TestKnobPlumbing:
    def test_unplumbed_setter_is_a_dead_knob(self):
        got = [f for f in drift_findings("knob-plumbing")
               if "dead knob" in f.message]
        assert len(got) == 1 and "fp_set_window" in got[0].message, got
        assert got[0].path == "pybind.py"

    def test_documented_surface_reaching_no_setter_is_inert(self):
        got = [f for f in drift_findings("knob-plumbing")
               if "silently inert" in f.message]
        assert len(got) == 1 and "engine.window" in got[0].message, got
        assert got[0].path == "controller.py"

    def test_plumbed_knob_stays_quiet(self):
        out = run_seam_analysis(repo_root=GOOD, manifest=mini_manifest(),
                                rules=["knob-plumbing"])
        assert out == []


class TestSuppressionMeta:
    def test_drift_tree_finding_census(self):
        # the full drifted sweep: 11 findings, exactly one waived
        out = drift_findings()
        assert len(out) == 11, "\n" + "\n".join(f.show() for f in out)
        assert sum(1 for f in out if f.suppressed) == 1

    def test_c_suppression_requires_justification(self, tmp_path):
        shutil.copytree(DRIFT, tmp_path / "t")
        hdr = tmp_path / "t" / "native" / "engine.h"
        hdr.write_text(hdr.read_text().replace(
            "// l5d: ignore[abi-signature] — kept for an out-of-tree "
            "caller; bound lazily there",
            "// l5d: ignore[abi-signature]"))
        out = run_seam_analysis(
            repo_root=str(tmp_path / "t"),
            manifest=mini_manifest(declare_frame_data=False,
                                   window_knob=True))
        bare = [f for f in out if f.rule == "suppression"
                and "without justification" in f.message]
        assert len(bare) == 1 and bare[0].path == "native/engine.h", out
        # and the waiver no longer waives: fp_reset is unsuppressed
        reset = [f for f in out if "fp_reset" in f.message]
        assert len(reset) == 1 and not reset[0].suppressed

    def test_c_suppression_for_unknown_rule_is_reported(self, tmp_path):
        shutil.copytree(DRIFT, tmp_path / "t")
        hdr = tmp_path / "t" / "native" / "engine.h"
        hdr.write_text(hdr.read_text().replace(
            "ignore[abi-signature] — kept",
            "ignore[abi-sig] — kept"))
        out = run_seam_analysis(
            repo_root=str(tmp_path / "t"),
            manifest=mini_manifest(declare_frame_data=False,
                                   window_knob=True))
        unknown = [f for f in out if f.rule == "suppression"
                   and "unknown seam rule" in f.message]
        assert len(unknown) == 1 and "abi-sig" in unknown[0].message


class TestCli:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.analysis", "seam", *args],
            cwd=REPO, capture_output=True, text=True)

    def test_seam_json_mode_is_machine_readable(self):
        p = self.run_cli("--format", "json")
        doc = json.loads(p.stdout)
        assert doc["mode"] == "seam"
        assert set(doc) >= {"wall_s", "unsuppressed", "suppressed_count"}

    def test_seam_rejects_paths(self):
        p = self.run_cli("linkerd_tpu")
        assert p.returncode == 2
        assert "takes no paths" in p.stderr

    def test_list_rules_names_all_four(self):
        p = self.run_cli("--list-rules")
        assert p.returncode == 0
        for rule in seam_rule_ids():
            assert rule in p.stdout


class TestRepoSeam:
    def test_repo_seam_has_zero_unsuppressed_findings(self):
        """The tier-1 gate: the live tree's C++/Python seam is
        contract-clean. A finding here is a real cross-plane bug or a
        missing manifest entry — fix the code or declare the contract,
        don't relax this test."""
        out = run_seam_analysis(repo_root=REPO)
        unsuppressed = [f for f in out if not f.suppressed]
        assert unsuppressed == [], "\n" + "\n".join(
            f.show() for f in unsuppressed)

    def test_every_repo_seam_suppression_is_justified(self):
        for f in run_seam_analysis(repo_root=REPO):
            if f.suppressed:
                assert f.justification, f.show()
