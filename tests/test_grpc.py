"""gRPC runtime tests: wire codec, framing, dispatchers end-to-end.

Mirrors the reference's grpc/runtime tests and grpc/interop local suite
(ref: grpc/interop/.../LocalInteropTest.scala) — in-process h2 server +
client on ephemeral ports.
"""

import asyncio

import pytest

from linkerd_tpu.grpc import (
    ClientDispatcher, Codec, Field, GrpcError, GrpcFramer, GrpcStatus,
    GrpcStream, ProtoMessage, Rpc, ServerDispatcher, ServiceDef,
    VarEventStream,
)
from linkerd_tpu.grpc.status import NOT_FOUND, OK, UNIMPLEMENTED
from linkerd_tpu.core.var import Var
from linkerd_tpu.protocol.h2.client import H2Client
from linkerd_tpu.protocol.h2.server import H2Server


class Inner(ProtoMessage):
    FIELDS = {"tag": Field(1, "string")}


class Echo(ProtoMessage):
    FIELDS = {
        "text": Field(1, "string"),
        "n": Field(2, "int32"),
        "flag": Field(3, "bool"),
        "data": Field(4, "bytes"),
        "ratio": Field(5, "double"),
        "ids": Field(6, "int64", repeated=True),
        "inner": Field(7, "message", message=Inner),
        "inners": Field(8, "message", message=Inner, repeated=True),
        "signed": Field(9, "sint64"),
    }


def test_proto_roundtrip():
    msg = Echo(text="héllo", n=-3, flag=True, data=b"\x00\x01", ratio=2.5,
               ids=[1, 2, 300000], inner=Inner(tag="t"),
               inners=[Inner(tag="a"), Inner(tag="b")], signed=-77)
    back = Echo.decode(msg.encode())
    assert back == msg
    assert back.n == -3 and back.signed == -77
    assert [i.tag for i in back.inners] == ["a", "b"]


def test_proto_defaults_omitted_and_unknown_skipped():
    assert Echo().encode() == b""
    # unknown field (number 99, varint) is skipped on decode
    from linkerd_tpu.grpc.proto import encode_varint
    raw = encode_varint((99 << 3) | 0) + encode_varint(7) + Echo(n=5).encode()
    assert Echo.decode(raw).n == 5


def test_proto_interop_with_google_protobuf():
    """Wire-format cross-check against the installed protobuf runtime."""
    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "echo_test.proto"
    fdp.package = "t"
    m = fdp.message_type.add()
    m.name = "Echo"
    for name, num, ftype in [("text", 1, "TYPE_STRING"), ("n", 2, "TYPE_INT32"),
                             ("ratio", 5, "TYPE_DOUBLE")]:
        f = m.field.add()
        f.name, f.number = name, num
        f.type = getattr(descriptor_pb2.FieldDescriptorProto, ftype)
        f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
    pool.Add(fdp)
    cls = message_factory.GetMessageClass(pool.FindMessageTypeByName("t.Echo"))
    ours = Echo(text="x", n=-9, ratio=1.25).encode()
    theirs = cls.FromString(ours)
    assert theirs.text == "x" and theirs.n == -9 and theirs.ratio == 1.25
    assert Echo.decode(cls(text="y", n=4, ratio=0.5).SerializeToString()).text == "y"


def test_framer_split_and_coalesced():
    codec = Codec(Echo)
    f1 = codec.encode_frame(Echo(text="one"))
    f2 = codec.encode_frame(Echo(text="two"))
    fr = GrpcFramer()
    # two messages in one feed
    out = fr.feed(f1 + f2)
    assert [codec.decode_payload(*m).text for m in out] == ["one", "two"]
    # one message split byte-by-byte
    fr2 = GrpcFramer()
    got = []
    for i in range(len(f1)):
        got.extend(fr2.feed(f1[i:i + 1]))
    assert len(got) == 1 and codec.decode_payload(*got[0]).text == "one"


SVC = ServiceDef("test.Echo", [
    Rpc("Say", Echo, Echo),
    Rpc("Count", Echo, Echo, server_streaming=True),
    Rpc("Sum", Echo, Echo, client_streaming=True),
    Rpc("Chat", Echo, Echo, client_streaming=True, server_streaming=True),
])


def _mk_dispatcher() -> ServerDispatcher:
    disp = ServerDispatcher()

    async def say(req: Echo) -> Echo:
        if req.text == "missing":
            raise GrpcError.of(NOT_FOUND, "no such thing")
        return Echo(text=f"hi {req.text}")

    async def count(req: Echo):
        async def gen():
            for i in range(req.n):
                yield Echo(n=i)
        return gen()

    async def total(reqs) -> Echo:
        s = 0
        async for m in reqs:
            s += m.n
        return Echo(n=s)

    async def chat(reqs):
        async def gen():
            async for m in reqs:
                yield Echo(text=m.text.upper())
        return gen()

    disp.register_all(SVC, {"Say": say, "Count": count,
                            "Sum": total, "Chat": chat})
    return disp


@pytest.fixture
def grpc_pair():
    """(ClientDispatcher, cleanup) over a live h2 server."""
    loop = asyncio.new_event_loop()
    server = loop.run_until_complete(H2Server(_mk_dispatcher()).start())
    client = H2Client("127.0.0.1", server.bound_port)
    yield loop, ClientDispatcher(client, authority="test")
    loop.run_until_complete(client.close())
    loop.run_until_complete(server.close())
    loop.close()


def test_unary_roundtrip(grpc_pair):
    loop, client = grpc_pair
    rep = loop.run_until_complete(client.unary(SVC, "Say", Echo(text="tpu")))
    assert rep.text == "hi tpu"


def test_unary_error_status(grpc_pair):
    loop, client = grpc_pair
    with pytest.raises(GrpcError) as ei:
        loop.run_until_complete(client.unary(SVC, "Say", Echo(text="missing")))
    assert ei.value.status.code == NOT_FOUND
    assert "no such thing" in ei.value.status.message


def test_unimplemented(grpc_pair):
    loop, client = grpc_pair
    bogus = ServiceDef("test.Echo", [Rpc("Nope", Echo, Echo)])
    with pytest.raises(GrpcError) as ei:
        loop.run_until_complete(client.unary(bogus, "Nope", Echo()))
    assert ei.value.status.code == UNIMPLEMENTED


def test_server_streaming(grpc_pair):
    loop, client = grpc_pair

    async def go():
        reps = await client.server_stream(SVC, "Count", Echo(n=5))
        msgs = await reps.collect()
        return msgs, reps.status

    msgs, status = loop.run_until_complete(go())
    assert [m.n for m in msgs] == [0, 1, 2, 3, 4]
    assert status.code == OK


def test_client_streaming(grpc_pair):
    loop, client = grpc_pair

    async def go():
        reqs = GrpcStream.of([Echo(n=i) for i in (1, 2, 3, 4)])
        reps = await client.call_stream(SVC, "Sum", reqs)
        return await reps.recv()

    assert loop.run_until_complete(go()).n == 10


def test_bidi_streaming(grpc_pair):
    loop, client = grpc_pair

    async def go():
        reqs = GrpcStream.of([Echo(text="a"), Echo(text="b")])
        reps = await client.call_stream(SVC, "Chat", reqs)
        return [m.text async for m in reps]

    assert loop.run_until_complete(go()) == ["A", "B"]


def test_var_event_stream_coalesces():
    async def go():
        v = Var(1)
        ev = VarEventStream(v, to_msg=lambda x: x * 10)
        first = await ev.__anext__()
        # burst of updates while consumer away -> only latest seen
        v.update(2)
        v.update(3)
        v.update(4)
        second = await ev.__anext__()
        ev.close()
        with pytest.raises(StopAsyncIteration):
            await ev.__anext__()
        return first, second

    loop = asyncio.new_event_loop()
    try:
        assert loop.run_until_complete(go()) == (10, 40)
    finally:
        loop.close()


def test_trailers_only_error_response(grpc_pair):
    """Conformant servers send immediate errors as HEADERS+END_STREAM with
    grpc-status (Trailers-Only); the client must surface that status."""
    loop, _client = grpc_pair
    from linkerd_tpu.grpc.dispatch import ClientDispatcher
    from linkerd_tpu.protocol.h2.messages import H2Response, Headers
    from linkerd_tpu.protocol.h2.stream import DataFrame, H2Stream
    from linkerd_tpu.router.service import FnService

    async def trailers_only(req):
        s = H2Stream()
        s.offer(DataFrame(b"", eos=True))
        return H2Response(status=200, headers=Headers(
            [("grpc-status", "7"), ("grpc-message", "denied")]), stream=s)

    async def go():
        client = ClientDispatcher(FnService(trailers_only))
        with pytest.raises(GrpcError) as ei:
            await client.unary(SVC, "Say", Echo(text="x"))
        assert ei.value.status.code == 7
        assert ei.value.status.message == "denied"

    loop.run_until_complete(go())


def test_non200_response_maps_to_unavailable(grpc_pair):
    loop, _client = grpc_pair
    from linkerd_tpu.grpc.dispatch import ClientDispatcher
    from linkerd_tpu.protocol.h2.messages import H2Response
    from linkerd_tpu.router.service import FnService

    async def proxy_503(req):
        return H2Response(status=503, body=b"<html>overloaded</html>")

    async def go():
        client = ClientDispatcher(FnService(proxy_503))
        with pytest.raises(GrpcError) as ei:
            await client.unary(SVC, "Say", Echo(text="x"))
        assert ei.value.status.code == 14  # UNAVAILABLE

    loop.run_until_complete(go())
