"""Transformers, announcers, usage report.

Ref: interpreter/per-host + subnet transformer tests, announcer wiring
(Main.announce), UsageDataTelemeter anonymization.
"""

import asyncio
import json

import pytest

from linkerd_tpu.core import Path, Var
from linkerd_tpu.core.addr import Address, Bound
from linkerd_tpu.core.nametree import Leaf
from linkerd_tpu.linker import load_linker, parse_linker_spec
from linkerd_tpu.namer.transformers import (
    LocalhostTransformer, PortTransformer, SpecificHostTransformer,
    SubnetGatewayTransformer,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def bound(*hostports):
    return Bound(frozenset(Address.mk(h, p) for h, p in hostports))


class TestAddressTransformers:
    def test_port_transformer(self):
        t = PortTransformer(4141)
        got = t.transform_addr(bound(("10.0.0.1", 8080), ("10.0.0.2", 9090)))
        assert {(a.host, a.port) for a in got.addresses} == {
            ("10.0.0.1", 4141), ("10.0.0.2", 4141)}

    def test_localhost_transformer(self):
        t = LocalhostTransformer(local_ips=frozenset({"10.0.0.1"}))
        got = t.transform_addr(bound(("10.0.0.1", 1), ("10.0.0.2", 2)))
        assert {(a.host, a.port) for a in got.addresses} == {("10.0.0.1", 1)}

    def test_specific_host(self):
        t = SpecificHostTransformer("10.0.0.2")
        got = t.transform_addr(bound(("10.0.0.1", 1), ("10.0.0.2", 2)))
        assert {(a.host, a.port) for a in got.addresses} == {("10.0.0.2", 2)}

    def test_subnet_gateway(self):
        gateways = Var(bound(("10.0.1.200", 4140), ("10.0.2.200", 4140)))
        t = SubnetGatewayTransformer(gateways, "255.255.255.0")
        got = t.transform_addr(
            bound(("10.0.1.7", 8080), ("10.0.2.9", 8080),
                  ("10.0.9.1", 8080)))
        # each endpoint replaced by its subnet's gateway; no-gateway
        # subnet endpoints are dropped
        assert {(a.host, a.port) for a in got.addresses} == {
            ("10.0.1.200", 4140), ("10.0.2.200", 4140)}

    def test_transformed_leaf_id_prefixed(self):
        t = PortTransformer(4141)
        from linkerd_tpu.core.addr import BoundName
        bn = BoundName(Path.read("/#/io.l5d.fs/web"), Var(bound()))
        got = t.transform_leaf(bn)
        assert got.id_.show == "/%/io.l5d.port/#/io.l5d.fs/web"


class TestTransformerWiring:
    def test_namer_transformers_from_config(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()
        (disco / "web").write_text("10.0.0.1 8080\n10.0.0.2 9090\n")
        cfg = f"""
routers:
- protocol: http
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
  transformers:
  - kind: io.l5d.port
    port: 4141
"""
        async def go():
            linker = load_linker(cfg)
            namer = linker.namers[0][1]
            act = namer.lookup(Path.read("/web"))
            tree = act.sample()
            assert isinstance(tree, Leaf)
            addrs = tree.value.addr.sample()
            assert {(a.host, a.port) for a in addrs.addresses} == {
                ("10.0.0.1", 4141), ("10.0.0.2", 4141)}
            await linker.close()
        run(go())


class TestAnnouncer:
    def test_fs_announce_and_withdraw(self, tmp_path):
        """A linkerd announces its server; another discovers it through
        the fs namer pointing at the same directory (the serversets
        pattern, file-backed)."""
        disco = tmp_path / "disco"

        cfg = f"""
routers:
- protocol: http
  label: out
  servers:
  - port: 0
    announce: ["/#/io.l5d.fs/web"]
announcers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
        async def go():
            linker = load_linker(cfg)
            await linker.start()
            port = linker.routers[0].server_ports[0]
            content = (disco / "web").read_text()
            assert content.strip() == f"127.0.0.1 {port}"
            await linker.close()
            assert not (disco / "web").exists()  # withdrawn
        run(go())


class TestUsageReport:
    def test_report_is_anonymized(self):
        from linkerd_tpu.telemetry.usage import build_report
        spec = parse_linker_spec("""
routers:
- protocol: http
  dtab: |
    /svc/secret-service => /#/io.l5d.fs ;
  identifier: {kind: io.l5d.methodAndHost}
  servers: [{port: 0}]
namers:
- kind: io.l5d.fs
  rootDir: /secret/path
""")
        report = build_report(spec, orgId="acme", instance_id="i",
                              start_time=0)
        text = json.dumps(report)
        assert "secret" not in text       # no dtabs/paths leak
        assert report["namers"] == ["io.l5d.fs"]
        assert report["routers"][0]["identifiers"] == ["io.l5d.methodAndHost"]


class TestK8sTransformerKinds:
    def test_localnode_subnet_and_hostnetwork(self):
        from linkerd_tpu.config import instantiate
        from linkerd_tpu.core.addr import Address

        t = instantiate("transformer", {
            "kind": "io.l5d.k8s.localnode", "podIp": "10.0.1.7"}).mk()
        addrs = frozenset({Address.mk("10.0.1.20", 80),
                           Address.mk("10.0.2.20", 80)})
        out = t.transform_addresses(addrs)
        assert {a.host for a in out} == {"10.0.1.20"}

        t2 = instantiate("transformer", {
            "kind": "io.l5d.k8s.localnode", "hostNetwork": True,
            "nodeName": "node-a"}).mk()
        addrs2 = frozenset({Address.mk("10.0.1.20", 80, nodeName="node-a"),
                            Address.mk("10.0.2.20", 80, nodeName="node-b")})
        out2 = t2.transform_addresses(addrs2)
        assert {a.host for a in out2} == {"10.0.1.20"}

    def test_daemonset_subnet_and_hostnetwork_gateways(self):
        from linkerd_tpu.core import Var
        from linkerd_tpu.core.addr import Address, Bound
        from linkerd_tpu.namer.transformers import (
            MetadataGatewayTransformer, SubnetGatewayTransformer,
        )

        gw = Var(Bound(frozenset({
            Address.mk("10.0.1.1", 4140, nodeName="node-a"),
            Address.mk("10.0.2.1", 4140, nodeName="node-b")})))
        t = SubnetGatewayTransformer(gw, "255.255.255.0")
        pods = frozenset({Address.mk("10.0.1.20", 80),
                          Address.mk("10.0.1.21", 80),
                          Address.mk("10.0.2.30", 80)})
        out = t.transform_addresses(pods)
        # pods collapse onto their subnet's gateway
        assert {(a.host, a.port) for a in out} == {
            ("10.0.1.1", 4140), ("10.0.2.1", 4140)}

        t2 = MetadataGatewayTransformer(gw, "nodeName")
        pods2 = frozenset({Address.mk("1.2.3.4", 80, nodeName="node-a"),
                           Address.mk("5.6.7.8", 80, nodeName="node-x")})
        out2 = t2.transform_addresses(pods2)
        assert {a.host for a in out2} == {"10.0.1.1"}


class TestConstAndRewriteKinds:
    def test_const_transformer_redirects_tree(self):
        from linkerd_tpu.config import instantiate
        from linkerd_tpu.core import Path
        from linkerd_tpu.core.nametree import Leaf, NEG

        t = instantiate("transformer", {
            "kind": "io.l5d.const", "path": "/$/inet/127.0.0.1/9990"}).mk()
        from linkerd_tpu.core import Var
        from linkerd_tpu.core.addr import Bound, BoundName
        tree = Leaf(BoundName(Path.read("/#/x/web"), Var(Bound(frozenset())),
                              Path.read("/")))
        out = t.transform_tree(tree)
        assert isinstance(out, Leaf)
        assert out.value == Path.read("/$/inet/127.0.0.1/9990")
        # Neg passes through untouched
        assert t.transform_tree(NEG) is NEG

    def test_rewrite_namer_kind(self):
        from linkerd_tpu.config import instantiate
        from linkerd_tpu.core import Path
        from linkerd_tpu.core.nametree import Leaf, Neg

        n = instantiate("namer", {
            "kind": "io.l5d.rewrite",
            "prefix": "/rw",
            "pattern": "/{env}/{svc}",
            "name": "/envs/{env}/{svc}"}).mk()
        act = n.lookup(Path.read("/prod/web"))
        tree = act.sample()
        assert isinstance(tree, Leaf)
        assert tree.value == Path.read("/envs/prod/web")
        assert isinstance(n.lookup(Path.read("/onlyone")).sample(), Neg)

    def test_rewrite_namer_mounted_in_interpreter(self):
        """The namer must work THROUGH its /#/ mount (config prefix is
        the mount point, pattern applies to the residual)."""
        from linkerd_tpu.config import instantiate
        from linkerd_tpu.core import Dtab, Path
        from linkerd_tpu.core.nametree import Leaf
        from linkerd_tpu.namer.core import ConfiguredDtabNamer

        cfg = instantiate("namer", {
            "kind": "io.l5d.rewrite", "prefix": "/rw",
            "pattern": "/{svc}", "name": "/$/inet/127.0.0.1/8080"})
        interp = ConfiguredDtabNamer(
            [(Path.read(cfg.prefix), cfg.mk())])
        act = interp.bind(Dtab.read("/svc => /#/rw"),
                          Path.read("/svc/web"))
        tree = act.sample().simplified
        assert isinstance(tree, Leaf)
        assert "/inet/127.0.0.1/8080" in tree.value.id_.show
