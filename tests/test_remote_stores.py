"""etcd / consul-KV dtab stores against scripted fake backends.

Ref test models: etcd integration fixtures (EtcdDtabStoreIntegrationTest)
and ConsulDtabStore tests — here with in-process fake APIs implementing
just the CAS + list semantics the stores rely on.
"""

import asyncio
import base64
import json
from urllib.parse import parse_qsl, unquote, urlsplit

import pytest

from linkerd_tpu.core import Dtab
from linkerd_tpu.namerd.store import (
    DtabNamespaceAlreadyExists, DtabVersionMismatch,
)
from linkerd_tpu.namerd.stores import ConsulDtabStore, EtcdDtabStore
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


class FakeEtcd:
    """Just enough of the v2 keys API: PUT w/ prevExist/prevIndex CAS,
    DELETE, recursive GET."""

    def __init__(self):
        self.nodes = {}  # key -> (value, modifiedIndex)
        self.index = 100

    def service(self):
        async def handler(req: Request) -> Response:
            parts = urlsplit(req.uri)
            assert parts.path.startswith("/v2/keys")
            key = unquote(parts.path[len("/v2/keys"):]).rstrip("/")
            q = dict(parse_qsl(parts.query))
            if req.method == "GET":
                if q.get("recursive") == "true":
                    nodes = [
                        {"key": k, "value": v, "modifiedIndex": idx}
                        for k, (v, idx) in self.nodes.items()
                        if k.startswith(key + "/")
                    ]
                    return Response(status=200, body=json.dumps(
                        {"node": {"key": key, "dir": True,
                                  "nodes": nodes}}).encode())
                if key in self.nodes:
                    v, idx = self.nodes[key]
                    return Response(status=200, body=json.dumps(
                        {"node": {"key": key, "value": v,
                                  "modifiedIndex": idx}}).encode())
                return Response(status=404, body=b"{}")
            if req.method == "PUT":
                form = dict(parse_qsl(req.body.decode()))
                if form.get("prevExist") == "false" and key in self.nodes:
                    return Response(status=412, body=b"{}")
                if "prevIndex" in form:
                    if key not in self.nodes:
                        return Response(status=404, body=b"{}")
                    if str(self.nodes[key][1]) != form["prevIndex"]:
                        return Response(status=412, body=b"{}")
                self.index += 1
                self.nodes[key] = (form["value"], self.index)
                return Response(status=200, body=b"{}")
            if req.method == "DELETE":
                if key not in self.nodes:
                    return Response(status=404, body=b"{}")
                del self.nodes[key]
                return Response(status=200, body=b"{}")
            return Response(status=405)
        return FnService(handler)


class FakeConsulKv:
    def __init__(self):
        self.kv = {}  # key -> (value bytes, ModifyIndex)
        self.index = 50

    def service(self):
        async def handler(req: Request) -> Response:
            parts = urlsplit(req.uri)
            assert parts.path.startswith("/v1/kv/")
            key = unquote(parts.path[len("/v1/kv/"):])
            q = dict(parse_qsl(parts.query))
            if req.method == "GET":
                if q.get("recurse") == "true":
                    prefix = key
                    entries = [
                        {"Key": k,
                         "Value": base64.b64encode(v).decode(),
                         "ModifyIndex": idx}
                        for k, (v, idx) in self.kv.items()
                        if k.startswith(prefix)
                    ]
                    if not entries:
                        return Response(status=404, body=b"[]")
                    return Response(status=200,
                                    body=json.dumps(entries).encode())
                return Response(status=404)
            if req.method == "PUT":
                if "cas" in q:
                    cas = int(q["cas"])
                    cur = self.kv.get(key)
                    if cas == 0 and cur is not None:
                        return Response(status=200, body=b"false")
                    if cas != 0 and (cur is None or cur[1] != cas):
                        return Response(status=200, body=b"false")
                self.index += 1
                self.kv[key] = (req.body, self.index)
                return Response(status=200, body=b"true")
            if req.method == "DELETE":
                self.kv.pop(key, None)
                return Response(status=200, body=b"true")
            return Response(status=405)
        return FnService(handler)


async def _store_contract(store, fake_refresh=None):
    """The DtabStore contract (mirrors TestInMemoryStore behavior)."""
    await store.create("default", Dtab.read("/svc => /#/io.l5d.fs;"))
    with pytest.raises(DtabNamespaceAlreadyExists):
        await store.create("default", Dtab.empty())
    vd = await store.observe("default").to_future()
    assert "/#/io.l5d.fs" in vd.dtab.show

    with pytest.raises(DtabVersionMismatch):
        await store.update("default", Dtab.read("/x=>/y;"), b"99999")
    await store.update("default", Dtab.read("/svc => /#/other;"), vd.version)
    vd2 = await store.observe("default").to_future()
    assert "/#/other" in vd2.dtab.show and vd2.version != vd.version

    await store.put("extra", Dtab.read("/a => /b;"))
    for _ in range(50):
        if store.list().sample() == frozenset({"default", "extra"}):
            break
        await asyncio.sleep(0.05)
    assert store.list().sample() == frozenset({"default", "extra"})

    await store.delete("extra")
    assert "extra" not in store.list().sample()
    store.close()


class TestEtcdStore:
    def test_contract(self):
        async def go():
            fake = FakeEtcd()
            server = await HttpServer(fake.service()).start()
            store = EtcdDtabStore("127.0.0.1", server.bound_port,
                                  poll_interval=0.1)
            await _store_contract(store)
            await server.close()
        run(go())


class TestConsulKvStore:
    def test_contract(self):
        async def go():
            fake = FakeConsulKv()
            server = await HttpServer(fake.service()).start()
            store = ConsulDtabStore("127.0.0.1", server.bound_port,
                                    poll_interval=0.1)
            await _store_contract(store)
            await server.close()
        run(go())

    def test_external_write_visible_via_poll(self):
        async def go():
            fake = FakeConsulKv()
            server = await HttpServer(fake.service()).start()
            store = ConsulDtabStore("127.0.0.1", server.bound_port,
                                    poll_interval=0.05)
            act = store.observe("ops")
            # another namerd (or operator) writes directly to consul
            fake.index += 1
            fake.kv["namerd/dtabs/ops"] = (b"/svc => /#/io.l5d.fs;",
                                           fake.index)
            for _ in range(100):
                vd = act.current.value if hasattr(act.current, "value") \
                    else None
                if vd is not None:
                    break
                await asyncio.sleep(0.05)
            vd = await act.to_future()
            assert vd is not None and "/#/io.l5d.fs" in vd.dtab.show
            store.close()
            await server.close()
        run(go())
