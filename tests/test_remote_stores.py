"""etcd / consul-KV dtab stores against scripted fake backends.

Ref test models: etcd integration fixtures (EtcdDtabStoreIntegrationTest)
and ConsulDtabStore tests — here with in-process fake APIs implementing
just the CAS + list semantics the stores rely on.
"""

import asyncio
import base64
import json
from urllib.parse import parse_qsl, unquote, urlsplit

import pytest

from linkerd_tpu.core import Dtab
from linkerd_tpu.namerd.store import (
    DtabNamespaceAlreadyExists, DtabVersionMismatch,
)
from linkerd_tpu.namerd.stores import ConsulDtabStore, EtcdDtabStore
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


class FakeEtcd:
    """Just enough of the v2 keys API: PUT w/ prevExist/prevIndex CAS,
    DELETE, recursive GET, and ``wait=true&waitIndex=N`` watches with an
    event history + X-Etcd-Index headers (what the watch loop uses)."""

    def __init__(self):
        self.nodes = {}  # key -> (value, modifiedIndex)
        self.index = 100
        self.events = []  # (index, action, key, value|None)
        self._changed = asyncio.Event()

    def _record(self, action, key, value):
        self.index += 1
        self.events.append((self.index, action, key, value))
        self._changed.set()
        self._changed = asyncio.Event()

    def _hdrs(self):
        from linkerd_tpu.protocol.http.message import Headers
        return Headers([("X-Etcd-Index", str(self.index))])

    def service(self):
        async def handler(req: Request) -> Response:
            parts = urlsplit(req.uri)
            assert parts.path.startswith("/v2/keys")
            key = unquote(parts.path[len("/v2/keys"):]).rstrip("/")
            q = dict(parse_qsl(parts.query))
            if req.method == "GET":
                if q.get("wait") == "true":
                    wait_idx = int(q.get("waitIndex", 0))
                    while True:
                        for idx, action, k, v in self.events:
                            if idx >= wait_idx and k.startswith(key + "/"):
                                node = {"key": k, "modifiedIndex": idx}
                                if v is not None:
                                    node["value"] = v
                                return Response(
                                    status=200, headers=self._hdrs(),
                                    body=json.dumps({
                                        "action": action,
                                        "node": node}).encode())
                        await self._changed.wait()
                if q.get("recursive") == "true":
                    nodes = [
                        {"key": k, "value": v, "modifiedIndex": idx}
                        for k, (v, idx) in self.nodes.items()
                        if k.startswith(key + "/")
                    ]
                    return Response(status=200, headers=self._hdrs(),
                                    body=json.dumps(
                        {"node": {"key": key, "dir": True,
                                  "nodes": nodes}}).encode())
                if key in self.nodes:
                    v, idx = self.nodes[key]
                    return Response(status=200, headers=self._hdrs(),
                                    body=json.dumps(
                        {"node": {"key": key, "value": v,
                                  "modifiedIndex": idx}}).encode())
                return Response(status=404, headers=self._hdrs(), body=b"{}")
            if req.method == "PUT":
                form = dict(parse_qsl(req.body.decode()))
                if form.get("prevExist") == "false" and key in self.nodes:
                    return Response(status=412, body=b"{}")
                if "prevIndex" in form:
                    if key not in self.nodes:
                        return Response(status=404, body=b"{}")
                    if str(self.nodes[key][1]) != form["prevIndex"]:
                        return Response(status=412, body=b"{}")
                self._record("set", key, form["value"])
                self.nodes[key] = (form["value"], self.index)
                # real etcd echoes the resulting node
                return Response(status=200, headers=self._hdrs(),
                                body=json.dumps({
                                    "action": "set",
                                    "node": {"key": key,
                                             "value": form["value"],
                                             "modifiedIndex": self.index},
                                }).encode())
            if req.method == "DELETE":
                if key not in self.nodes:
                    return Response(status=404, body=b"{}")
                del self.nodes[key]
                self._record("delete", key, None)
                return Response(status=200, headers=self._hdrs(),
                                body=json.dumps({
                                    "action": "delete",
                                    "node": {"key": key,
                                             "modifiedIndex": self.index},
                                }).encode())
            return Response(status=405)
        return FnService(handler)


class FakeConsulKv:
    """Consul KV with CAS + blocking-index queries (``index=N&wait=..``
    parks until self.index moves past N) + X-Consul-Index headers."""

    def __init__(self):
        self.kv = {}  # key -> (value bytes, ModifyIndex)
        self.index = 50
        self._changed = asyncio.Event()

    def _bump(self):
        self.index += 1
        self._changed.set()
        self._changed = asyncio.Event()

    def _hdrs(self):
        from linkerd_tpu.protocol.http.message import Headers
        return Headers([("X-Consul-Index", str(self.index))])

    def service(self):
        async def handler(req: Request) -> Response:
            parts = urlsplit(req.uri)
            assert parts.path.startswith("/v1/kv/")
            key = unquote(parts.path[len("/v1/kv/"):])
            q = dict(parse_qsl(parts.query))
            if req.method == "GET":
                if q.get("recurse") == "true":
                    if "index" in q:
                        want = int(q["index"])
                        while self.index <= want:
                            await self._changed.wait()
                    prefix = key
                    entries = [
                        {"Key": k,
                         "Value": base64.b64encode(v).decode(),
                         "ModifyIndex": idx}
                        for k, (v, idx) in self.kv.items()
                        if k.startswith(prefix)
                    ]
                    if not entries:
                        return Response(status=404, headers=self._hdrs(),
                                        body=b"[]")
                    return Response(status=200, headers=self._hdrs(),
                                    body=json.dumps(entries).encode())
                return Response(status=404, headers=self._hdrs())
            if req.method == "PUT":
                if "cas" in q:
                    cas = int(q["cas"])
                    cur = self.kv.get(key)
                    if cas == 0 and cur is not None:
                        return Response(status=200, body=b"false")
                    if cas != 0 and (cur is None or cur[1] != cas):
                        return Response(status=200, body=b"false")
                self._bump()
                self.kv[key] = (req.body, self.index)
                return Response(status=200, body=b"true")
            if req.method == "DELETE":
                self.kv.pop(key, None)
                self._bump()
                return Response(status=200, body=b"true")
            return Response(status=405)
        return FnService(handler)


async def _store_contract(store, fake_refresh=None):
    """The DtabStore contract (mirrors TestInMemoryStore behavior)."""
    await store.create("default", Dtab.read("/svc => /#/io.l5d.fs;"))
    with pytest.raises(DtabNamespaceAlreadyExists):
        await store.create("default", Dtab.empty())
    vd = await store.observe("default").to_future()
    assert "/#/io.l5d.fs" in vd.dtab.show

    with pytest.raises(DtabVersionMismatch):
        await store.update("default", Dtab.read("/x=>/y;"), b"99999")
    await store.update("default", Dtab.read("/svc => /#/other;"), vd.version)
    vd2 = await store.observe("default").to_future()
    assert "/#/other" in vd2.dtab.show and vd2.version != vd.version

    await store.put("extra", Dtab.read("/a => /b;"))
    for _ in range(50):
        if store.list().sample() == frozenset({"default", "extra"}):
            break
        await asyncio.sleep(0.05)
    assert store.list().sample() == frozenset({"default", "extra"})

    await store.delete("extra")
    assert "extra" not in store.list().sample()
    store.close()


class TestEtcdStore:
    def test_contract(self):
        async def go():
            fake = FakeEtcd()
            server = await HttpServer(fake.service()).start()
            store = EtcdDtabStore("127.0.0.1", server.bound_port,
                                  poll_interval=0.1)
            await _store_contract(store)
            await server.close()
        run(go())


class TestConsulKvStore:
    def test_contract(self):
        async def go():
            fake = FakeConsulKv()
            server = await HttpServer(fake.service()).start()
            store = ConsulDtabStore("127.0.0.1", server.bound_port,
                                    poll_interval=0.1)
            await _store_contract(store)
            await server.close()
        run(go())

    def test_external_write_visible_via_blocking_watch(self):
        """An out-of-band write must land through the blocking-index
        watch — fast (<100ms), no polling sleeps involved."""
        import time

        async def go():
            fake = FakeConsulKv()
            server = await HttpServer(fake.service()).start()
            store = ConsulDtabStore("127.0.0.1", server.bound_port)
            act = store.observe("ops")
            # wait until the store holds a parked blocking query
            for _ in range(100):
                if store._consul_index is not None:
                    break
                await asyncio.sleep(0.01)
            # another namerd (or operator) writes directly to consul
            t0 = time.perf_counter()
            fake._bump()
            fake.kv["namerd/dtabs/ops"] = (b"/svc => /#/io.l5d.fs;",
                                           fake.index)
            while True:
                st = act.current
                vd = getattr(st, "value", None)
                if vd is not None:
                    break
                await asyncio.sleep(0.005)
            elapsed = time.perf_counter() - t0
            assert "/#/io.l5d.fs" in vd.dtab.show
            assert elapsed < 0.5, f"watch took {elapsed:.3f}s"
            store.close()
            await server.close()
        run(go())


class TestEtcdWatch:
    def test_external_write_visible_via_watch(self):
        import time

        async def go():
            fake = FakeEtcd()
            server = await HttpServer(fake.service()).start()
            store = EtcdDtabStore("127.0.0.1", server.bound_port)
            act = store.observe("ops")
            for _ in range(100):
                if store._primed:  # initial list delivered by the watch
                    break
                await asyncio.sleep(0.01)
            t0 = time.perf_counter()
            fake._record("set", "/namerd/dtabs/ops", "/svc => /#/io.l5d.fs;")
            fake.nodes["/namerd/dtabs/ops"] = (
                "/svc => /#/io.l5d.fs;", fake.index)
            while True:
                st = act.current
                vd = getattr(st, "value", None)
                if vd is not None:
                    break
                await asyncio.sleep(0.005)
            elapsed = time.perf_counter() - t0
            assert "/#/io.l5d.fs" in vd.dtab.show
            assert elapsed < 0.5, f"watch took {elapsed:.3f}s"

            # delete propagates through the watch too
            del fake.nodes["/namerd/dtabs/ops"]
            fake._record("delete", "/namerd/dtabs/ops", None)
            for _ in range(100):
                st = act.current
                if getattr(st, "value", object()) is None:
                    break
                await asyncio.sleep(0.01)
            assert getattr(act.current, "value", object()) is None
            store.close()
            await server.close()
        run(go())

    def test_observe_pending_until_first_fetch(self):
        """Startup must not transiently report namespaces as missing
        (Pending, not Ok(None), before the first backend answer)."""
        from linkerd_tpu.core.activity import Pending

        async def go():
            fake = FakeEtcd()
            fake.nodes["/namerd/dtabs/boot"] = ("/a => /b;", 101)
            server = await HttpServer(fake.service()).start()
            store = EtcdDtabStore("127.0.0.1", server.bound_port)
            act = store.observe("boot")
            assert isinstance(act.current, Pending)
            vd = await asyncio.wait_for(act.to_future(), 5)
            assert vd is not None and "/a" in vd.dtab.show
            store.close()
            await server.close()
        run(go())
