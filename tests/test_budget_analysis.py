"""l5dbudget self-tests: every budget rule fires on the checked-in
drifted miniature engine, stays quiet on the matching clean twin,
manifest rot is itself a finding, C-comment suppressions work (and
require justification), the CLI surface matches the other analyzers,
and the live tree itself is clean (the tier-1 gate).

The fixture trees under ``tests/fixtures/budget/`` are an event loop
in miniature — recv, relay, send, one stat lock — checked in rather
than generated so the drift the analyzer must catch is reviewable by
eye. ``drift/`` is ``good/`` with every rule violated exactly once at
a ``// DRIFT:`` marker plus ONE justified suppression; the tests pin
each finding to the marked line. Both fixtures compile
(``g++ -fsyntax-only``) so the walker is exercised on real C++, not
pseudo-code.

The live-tree pins at the bottom are the regression half of the
pilot sweep: the per-wakeup clock cache, the h1 write coalescing, the
zero-copy header probes, the in-place chunk parser, the cached SNI,
and the h2 drain scratch were all forced in by l5dbudget findings —
the sweep gate alone would only catch their loss after the fact.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from tools.analysis.budget import (
    BUDGET_RULES, budget_rule_ids, budget_static_profiles,
    run_budget_analysis,
)
from tools.analysis.budget.manifest import (
    DEFAULT_MANIFEST, BudgetManifest, MeasuredCheck, PathBudget, Syscall,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "budget")
GOOD = os.path.join(FIXTURES, "good")
DRIFT = os.path.join(FIXTURES, "drift")


def mini_manifest(**over) -> BudgetManifest:
    """The declared envelope of the miniature fixture engine; tests
    override single fields to plant manifest rot."""
    kw = dict(
        name="mini-serve",
        files=("native/engine.cpp",),
        roots=("loop_main",),
        wrappers=(("now_us", "clock_gettime"),),
        syscalls=(Syscall("epoll_wait", 1, 1.0, "loop"),
                  Syscall("recv", 1, 1.0, "loop"),
                  Syscall("send", 1, 1.0, "batched"),
                  Syscall("clock_gettime", 2, 1.0, "direct")),
        max_lock_sites=1,
        alloc_ok=("parse_head",),
        copy_ok=("relay",),
    )
    kw.update(over)
    return BudgetManifest(paths=(PathBudget(**kw),))


def marker_line(root, rel, needle):
    """1-based line containing ``needle`` — findings pin to source
    text, not hard-coded numbers."""
    path = os.path.join(root, rel)
    with open(path, encoding="utf-8") as fh:
        for i, text in enumerate(fh, 1):
            if needle in text:
                return i
    raise AssertionError(f"marker {needle!r} not found in {path}")


def code_after_marker(root, rel, needle):
    """Line of the first non-comment line after the marker — DRIFT
    markers are comments; the finding lands on the statement below."""
    start = marker_line(root, rel, needle)
    path = os.path.join(root, rel)
    with open(path, encoding="utf-8") as fh:
        lines = fh.readlines()
    for i in range(start, len(lines)):
        if not lines[i].strip().startswith("//"):
            return i + 1
    raise AssertionError(f"no code after marker {needle!r}")


def drift_findings(rule=None, manifest=None):
    out = run_budget_analysis(repo_root=DRIFT,
                              manifest=manifest or mini_manifest())
    return [f for f in out if rule is None or f.rule == rule]


class TestGoodTree:
    def test_clean_tree_has_zero_findings(self):
        out = run_budget_analysis(repo_root=GOOD,
                                  manifest=mini_manifest())
        assert out == [], "\n" + "\n".join(f.show() for f in out)

    def test_fixtures_compile(self):
        for tree in (GOOD, DRIFT):
            src = os.path.join(tree, "native", "engine.cpp")
            subprocess.run(["g++", "-fsyntax-only", "-std=c++17", src],
                           check=True)

    def test_rule_filter_runs_only_that_rule(self):
        out = run_budget_analysis(repo_root=DRIFT,
                                  manifest=mini_manifest(),
                                  rules=["hot-alloc"])
        assert out and all(f.rule == "hot-alloc" for f in out)

    def test_rule_ids_are_the_four_rules(self):
        assert budget_rule_ids() == ["copy-budget", "hot-alloc",
                                     "hot-lock", "syscall-budget"]

    def test_empty_scan_set_is_an_error_not_a_clean_bill(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            run_budget_analysis(repo_root=str(tmp_path))


class TestPerRule:
    def test_undeclared_syscall_site_is_caught_at_marker(self):
        got = [f for f in drift_findings("syscall-budget")
               if not f.suppressed]
        want = code_after_marker(DRIFT, "native/engine.cpp",
                                 "DRIFT: syscall-budget")
        assert [f.line for f in got] == [want]
        assert "fcntl" in got[0].message

    def test_hot_allocation_is_caught_at_marker(self):
        got = drift_findings("hot-alloc")
        want = code_after_marker(DRIFT, "native/engine.cpp",
                                 "DRIFT: hot-alloc")
        assert [f.line for f in got] == [want]
        assert "std::string" in got[0].message

    def test_excess_lock_site_is_caught_at_marker(self):
        got = drift_findings("hot-lock")
        want = code_after_marker(DRIFT, "native/engine.cpp",
                                 "DRIFT: hot-lock")
        assert [f.line for f in got] == [want]
        assert "2 acquisition sites > 1 declared" in got[0].message

    def test_unaccounted_copy_is_caught_at_marker(self):
        got = drift_findings("copy-budget")
        want = code_after_marker(DRIFT, "native/engine.cpp",
                                 "DRIFT: copy-budget")
        assert [f.line for f in got] == [want]
        assert "memmove" in got[0].message

    def test_syscall_sites_over_declared_max_fire(self):
        # drop the declared send allowance: the good tree's one send
        # site becomes an unaccounted finding
        mf = mini_manifest(syscalls=(
            Syscall("epoll_wait", 1, 1.0, "loop"),
            Syscall("recv", 1, 1.0, "loop"),
            Syscall("clock_gettime", 2, 1.0, "direct")))
        got = [f for f in run_budget_analysis(repo_root=GOOD,
                                              manifest=mf)
               if f.rule == "syscall-budget"]
        assert got and all("send" in f.message for f in got)


class TestManifestRot:
    def test_missing_root_is_a_finding(self):
        mf = mini_manifest(roots=("loop_main", "gone_fn"))
        got = [f for f in run_budget_analysis(repo_root=GOOD,
                                              manifest=mf)
               if "manifest rot" in f.message]
        assert got and any("gone_fn" in f.message for f in got)

    def test_unreached_declared_syscall_is_a_finding(self):
        mf = mini_manifest(syscalls=(
            Syscall("epoll_wait", 1, 1.0, "loop"),
            Syscall("recv", 1, 1.0, "loop"),
            Syscall("send", 1, 1.0, "batched"),
            Syscall("clock_gettime", 2, 1.0, "direct"),
            Syscall("accept4", 1, 1.0, "loop")))
        got = [f for f in run_budget_analysis(repo_root=GOOD,
                                              manifest=mf)
               if "manifest rot" in f.message]
        assert got and any("accept4" in f.message for f in got)

    def test_rot_findings_anchor_to_the_paths_tu(self):
        mf = mini_manifest(roots=("loop_main", "gone_fn"))
        got = [f for f in run_budget_analysis(repo_root=GOOD,
                                              manifest=mf)
               if "manifest rot" in f.message]
        assert all(f.path == "native/engine.cpp" and f.line == 1
                   for f in got)

    def test_cold_path_skips_alloc_and_copy_enforcement(self):
        # hot=False (control-plane cadence): the drift tree's planted
        # alloc/copy do NOT fire; its syscall/lock drift still does
        mf = mini_manifest(hot=False)
        out = run_budget_analysis(repo_root=DRIFT, manifest=mf)
        rules = {f.rule for f in out if not f.suppressed}
        assert "hot-alloc" not in rules
        assert "copy-budget" not in rules
        assert "syscall-budget" in rules
        assert "hot-lock" in rules


class TestSuppressionMeta:
    def test_drift_tree_finding_census(self):
        out = drift_findings()
        unsup = [f for f in out if not f.suppressed]
        sup = [f for f in out if f.suppressed]
        assert sorted(f.rule for f in unsup) == [
            "copy-budget", "hot-alloc", "hot-lock", "syscall-budget"]
        assert [f.rule for f in sup] == ["syscall-budget"]
        assert sup[0].justification

    def test_suppression_requires_justification(self, tmp_path):
        shutil.copytree(DRIFT, tmp_path / "t")
        eng = tmp_path / "t" / "native" / "engine.cpp"
        text = eng.read_text()
        assert "— fixture:" in text
        eng.write_text(text.replace(
            "// l5d: ignore[syscall-budget] — fixture: a justified "
            "waiver the census must count as suppressed, not silent",
            "// l5d: ignore[syscall-budget]"))
        out = run_budget_analysis(repo_root=str(tmp_path / "t"),
                                  manifest=mini_manifest())
        assert any(f.rule == "suppression"
                   and "without justification" in f.message
                   for f in out)
        # the bare waiver no longer suppresses: shutdown fires too
        assert sum(1 for f in out if f.rule == "syscall-budget"
                   and not f.suppressed) == 2

    def test_suppression_for_unknown_rule_is_reported(self, tmp_path):
        shutil.copytree(DRIFT, tmp_path / "t")
        eng = tmp_path / "t" / "native" / "engine.cpp"
        eng.write_text(eng.read_text().replace(
            "ignore[syscall-budget] — fixture:",
            "ignore[made-up-rule] — fixture:"))
        out = run_budget_analysis(repo_root=str(tmp_path / "t"),
                                  manifest=mini_manifest())
        assert any(f.rule == "suppression"
                   and "made-up-rule" in f.message for f in out)

    def test_stale_budget_waiver_is_reported(self, tmp_path):
        shutil.copytree(GOOD, tmp_path / "t")
        eng = tmp_path / "t" / "native" / "engine.cpp"
        eng.write_text(eng.read_text().replace(
            "void relay(Conn* c, const char* p, size_t n) {",
            "// l5d: ignore[hot-alloc] — nothing here allocates any "
            "more\nvoid relay(Conn* c, const char* p, size_t n) {"))
        out = run_budget_analysis(repo_root=str(tmp_path / "t"),
                                  manifest=mini_manifest())
        stale = [f for f in out if f.rule == "stale-suppression"]
        assert stale and "hot-alloc" in stale[0].message

    def test_other_analyzers_waivers_are_not_judged_stale_here(
            self, tmp_path):
        shutil.copytree(GOOD, tmp_path / "t")
        eng = tmp_path / "t" / "native" / "engine.cpp"
        eng.write_text(eng.read_text().replace(
            "void relay(Conn* c, const char* p, size_t n) {",
            "// l5d: ignore[bounded-table] — l5dnat's concern, judged "
            "by its own mode\nvoid relay(Conn* c, const char* p, "
            "size_t n) {"))
        out = run_budget_analysis(repo_root=str(tmp_path / "t"),
                                  manifest=mini_manifest())
        assert not [f for f in out if f.rule == "stale-suppression"]


class TestStaticProfiles:
    def test_profiles_cover_every_declared_path(self):
        prof = budget_static_profiles()
        assert sorted(prof) == sorted(
            p.name for p in DEFAULT_MANIFEST.paths)

    def test_fixture_profile_counts_sites(self):
        prof = budget_static_profiles(repo_root=GOOD,
                                      manifest=mini_manifest())
        p = prof["mini-serve"]
        assert p["syscall_sites"] == {"clock_gettime": 2,
                                      "epoll_wait": 1, "recv": 1,
                                      "send": 1}
        assert p["lock_sites"] == 1
        assert p["alloc_sites"] >= 1
        assert p["copy_sites"] == 1

    def test_wrapper_call_sites_count_as_the_syscall(self):
        # two clock sites: now_us's body + on_readable's now_us() call
        prof = budget_static_profiles(repo_root=GOOD,
                                      manifest=mini_manifest())
        assert prof["mini-serve"]["syscall_sites"]["clock_gettime"] == 2


class TestCli:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.analysis", *args],
            capture_output=True, text=True, cwd=REPO)

    def test_budget_json_mode_is_machine_readable(self):
        p = self.run_cli("budget", "--format", "json")
        assert p.returncode == 0, p.stderr
        doc = json.loads(p.stdout)
        assert doc["mode"] == "budget"
        assert doc["unsuppressed"] == []
        assert doc["suppressed_count"] >= 1

    def test_budget_rejects_paths(self):
        p = self.run_cli("budget", "native/fastpath.cpp")
        assert p.returncode == 2
        assert "no paths" in (p.stderr + p.stdout)

    def test_list_rules_names_all_four(self):
        p = self.run_cli("budget", "--list-rules")
        assert p.returncode == 0
        for rule in BUDGET_RULES:
            assert rule in p.stdout


class TestLiveTreePins:
    """The pilot-sweep fixes, pinned as source text: each of these was
    a true positive l5dbudget forced out of the engines; losing one
    silently regresses a measured per-request cost."""

    def read(self, rel):
        with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
            return fh.read()

    def test_both_loops_stamp_the_clock_once_per_wakeup(self):
        for rel in ("native/fastpath.cpp", "native/h2_fastpath.cpp"):
            src = self.read(rel)
            assert "e->now_cache_us = now_us();" in src, rel
            assert "uint64_t loop_now(Engine* e)" in src, rel

    def test_h1_header_probes_are_zero_copy(self):
        src = self.read("native/fastpath.cpp")
        assert 'ihas(*te, "chunked")' in src
        assert 'ihas(*conn_hdr, "close")' in src

    def test_h1_flushes_are_coalesced_per_wakeup(self):
        src = self.read("native/fastpath.cpp")
        assert "void queue_flush(Engine* e, Conn* c)" in src
        assert "void drain_dirty(Engine* e)" in src
        assert "void purge_dirty(Engine* e, Conn* c)" in src

    def test_chunk_size_parse_is_in_place(self):
        src = self.read("native/fastpath.cpp")
        assert "UINT64_MAX >> 4" in src  # the no-substr hex parser

    def test_sni_is_cached_once_per_handshake(self):
        for rel in ("native/fastpath.cpp", "native/h2_fastpath.cpp"):
            assert ("c->tls->sni = l5dtls::server_sni(c->tls->sess)"
                    in self.read(rel)), rel

    def test_h2_drain_swaps_through_persistent_scratch(self):
        src = self.read("native/h2_fastpath.cpp")
        assert "std::swap(e->dirty, e->dirty_scratch)" in src

    def test_h1_request_clock_sites_stay_cached(self):
        # the pre-fix tree had 16 clock_gettime sites per wakeup; the
        # cached-stamp fix pinned it at three (two wrapper bodies +
        # the loop stamp)
        prof = budget_static_profiles()
        assert prof["h1-request"]["syscall_sites"]["clock_gettime"] <= 3
        assert prof["h2-serve"]["syscall_sites"]["clock_gettime"] <= 3


class TestRepoBudget:
    """Tier-1 gate: the live tree carries zero unsuppressed budget
    findings, every waiver is justified, and the manifest covers every
    declared engine entrypoint."""

    def test_repo_tree_has_zero_unsuppressed_findings(self):
        out = run_budget_analysis()
        bad = [f for f in out if not f.suppressed]
        assert bad == [], "\n" + "\n".join(f.show() for f in bad)

    def test_every_repo_budget_suppression_is_justified(self):
        out = run_budget_analysis()
        assert all(f.justification for f in out if f.suppressed)

    def test_manifest_covers_every_declared_entrypoint(self):
        names = sorted(p.name for p in DEFAULT_MANIFEST.paths)
        assert names == sorted([
            "h1-accept", "h1-request", "h1-feature-drain",
            "h1-weight-publish", "h1-tls-handshake",
            "h2-accept", "h2-serve", "h2-feature-drain",
            "h2-weight-publish", "h2-tls-handshake"])

    def test_measured_checks_reference_real_paths(self):
        engines = sorted(m.engine for m in DEFAULT_MANIFEST.measured)
        assert engines == ["h1", "h2"]
        for mc in DEFAULT_MANIFEST.measured:
            assert isinstance(mc, MeasuredCheck)
            assert mc.tolerance > 1.0
            for pname in mc.paths:
                assert DEFAULT_MANIFEST.path(pname) is not None, pname

    def test_tls_handshake_paths_declare_zero_syscalls(self):
        # the memory-BIO design invariant, as data: the TLS boundary
        # itself never talks to the kernel
        for name in ("h1-tls-handshake", "h2-tls-handshake"):
            assert DEFAULT_MANIFEST.path(name).syscalls == ()
