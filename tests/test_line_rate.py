"""Line-rate scoring tests: donated ring dispatch safety, hot-swap
during in-flight donated batches, native-ring wraparound under
backpressure, the adaptive micro-batcher, and sidecar tier demotion.

The donation contract under test (COMPONENTS.md §2.11): a donated input
buffer must NEVER be re-read after dispatch (JAX deletes it; re-reads
raise), hot-swap during an in-flight donated batch completes or fails
cleanly, and ring wraparound drops-and-counts instead of corrupting
unconsumed rows.
"""

import asyncio
import time

import numpy as np
import pytest

from linkerd_tpu.models.features import FEATURE_DIM, FeatureVector, featurize
from linkerd_tpu.telemetry.anomaly import (
    InProcessScorer, JaxAnomalyConfig, JaxAnomalyTelemeter,
)
from linkerd_tpu.telemetry.linerate import (
    NATIVE_ROW_WIDTH, NativeFeatureRing, NativeFeaturizer, RingDispatcher,
    TieredScorer,
)
from linkerd_tpu.telemetry.metrics import MetricsTree


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


class TestRingDispatcher:
    def test_dispatch_returns_scores_and_reuses_staging(self):
        calls = []

        def step(staging):
            calls.append(staging)
            return staging.sum(axis=1)

        async def go():
            d = RingDispatcher(4, lambda n: 8)
            try:
                out1 = await d.dispatch(np.ones((3, 4), np.float32), step)
                out2 = await d.dispatch(
                    np.full((3, 4), 2.0, np.float32), step)
                assert out1.shape == (3,) and (out1 == 4.0).all()
                assert (out2 == 8.0).all()
                # double-buffered: two dispatches of one bucket use the
                # SAME two persistent staging buffers, not fresh arrays
                assert len({id(c) for c in calls}) <= 2
            finally:
                d.close()

        run(go())

    def test_backpressure_bounds_slots_per_bucket(self):
        inflight = []
        release = asyncio.Event()

        async def go():
            d = RingDispatcher(2, lambda n: 4, depth=2)

            class SlowResult:
                """np.asarray on the drainer blocks until released."""

                def __init__(self, staging):
                    self.staging = staging

                def __array__(self, dtype=None, copy=None):
                    # runs on the drainer thread
                    while not release.is_set():
                        time.sleep(0.001)
                    return np.zeros(4, np.float32)

            def step(staging):
                inflight.append(1)
                return SlowResult(staging)

            try:
                t1 = asyncio.ensure_future(
                    d.dispatch(np.ones((2, 2), np.float32), step))
                t2 = asyncio.ensure_future(
                    d.dispatch(np.ones((2, 2), np.float32), step))
                t3 = asyncio.ensure_future(
                    d.dispatch(np.ones((2, 2), np.float32), step))
                await asyncio.sleep(0.05)
                # only two slots exist: the third dispatch must wait
                assert len(inflight) == 2
                release.set()
                await asyncio.gather(t1, t2, t3)
                assert len(inflight) == 3
            finally:
                release.set()
                d.close()

        run(go())

    def test_step_exception_releases_slot(self):
        async def go():
            d = RingDispatcher(2, lambda n: 4)

            def boom(staging):
                raise RuntimeError("no")

            try:
                for _ in range(5):  # more dispatches than slots: a
                    # leaked slot would deadlock the later attempts
                    with pytest.raises(RuntimeError):
                        await d.dispatch(np.ones((2, 2), np.float32),
                                         boom)
            finally:
                d.close()

        run(go())

    def test_close_rejects_new_dispatch(self):
        async def go():
            d = RingDispatcher(2, lambda n: 4)
            d.close()
            with pytest.raises(RuntimeError):
                await d.dispatch(np.ones((1, 2), np.float32),
                                 lambda s: s)

        run(go())


class TestDonationSafety:
    def test_donated_device_buffer_never_rereadable(self):
        """A buffer dispatched through the ring with a donating step is
        deleted — any re-read raises instead of silently returning
        stale data. Uses a same-shape step so every backend (CPU
        included) actually consumes the donation."""
        import jax

        async def go():
            d = RingDispatcher(4, lambda n: 4)
            donating = jax.jit(lambda v: v * 2.0, donate_argnums=(0,))
            dev = jax.devices()[0]
            captured = []

            def step(staging):
                xd = jax.device_put(staging, dev)
                captured.append(xd)
                return donating(xd)

            try:
                out = await d.dispatch(
                    np.ones((4, 4), np.float32), step)
                assert (out == 2.0).all()
                (xd,) = captured
                assert xd.is_deleted()
                with pytest.raises(RuntimeError):
                    np.asarray(xd)
            finally:
                d.close()

        run(go())

    def test_scorer_dispatch_path_drops_device_buffer(self):
        """On the real scorer the device copy is handed to the donating
        step and never re-read. Backends that can fold the [B, D] input
        into the [B] output consume the donation (deleted buffer,
        re-read raises); backends that decline it must still score
        correctly — the structural contract is that the path works
        without ever touching the buffer again either way."""
        import jax

        async def go():
            scorer = InProcessScorer()
            captured = []
            orig_step = scorer._scorer

            def spying(params, xd, mu, var):
                captured.append(xd)
                return orig_step(params, xd, mu, var)

            scorer._scorer = spying
            try:
                x = np.random.default_rng(0).standard_normal(
                    (16, scorer.cfg.in_dim)).astype(np.float32)
                out = await scorer.score(x)
                assert out.shape == (16,)
                assert np.isfinite(out).all()
                (xd,) = captured
                if xd.is_deleted():  # donation consumed (e.g. TPU)
                    with pytest.raises(RuntimeError):
                        np.asarray(xd)
                # either way a second batch reuses the same staging
                # slot cleanly
                out2 = await scorer.score(x)
                assert np.allclose(out, out2)
            finally:
                scorer._scorer = orig_step
                scorer.close()

        run(go())

    def test_scores_match_non_donating_reference(self):
        """Donation must not change values: ring-dispatch scores equal
        a fresh non-donating evaluation of the same model."""
        from linkerd_tpu.models.anomaly import anomaly_scores

        async def go():
            import jax
            scorer = InProcessScorer()
            x = np.random.default_rng(1).standard_normal(
                (32, scorer.cfg.in_dim)).astype(np.float32)
            got = await scorer.score(x)
            ref = np.asarray(anomaly_scores(
                scorer.params, np.asarray(x), scorer.cfg))
            assert np.allclose(got, ref, atol=2e-2)
            scorer.close()

        run(go())

    def test_hot_swap_during_inflight_donated_batch(self):
        """restore() while a donated batch is in flight: the in-flight
        batch completes against the captured (old) params; the next
        batch scores against the restored model; nothing raises."""

        async def go():
            scorer = InProcessScorer(seed=0, learning_rate=5e-3)
            rng = np.random.default_rng(2)
            x = rng.standard_normal(
                (64, scorer.cfg.in_dim)).astype(np.float32)
            labels = np.zeros(64, np.float32)
            mask = np.ones(64, np.float32)
            snap = scorer.snapshot()
            for _ in range(4):  # move the live model away from snap
                await scorer.fit(x, labels, mask)
            trained = await scorer.score(x)

            # dispatch a batch and IMMEDIATELY hot-swap mid-flight
            fut = asyncio.ensure_future(scorer.score(x))
            await asyncio.to_thread(scorer.restore, snap)
            inflight = await fut
            assert np.isfinite(inflight).all()

            after = await scorer.score(x)
            assert np.isfinite(after).all()
            # the post-swap batch scores with the RESTORED params
            fresh = InProcessScorer(seed=0, learning_rate=5e-3)
            fresh.restore(snap)
            expect = await fresh.score(x)
            assert np.allclose(after, expect, atol=1e-5)
            assert not np.allclose(after, trained, atol=1e-6)
            scorer.close()
            fresh.close()

        run(go())


class TestNativeFeatureRing:
    def test_produce_consume_roundtrip(self):
        ring = NativeFeatureRing(8)
        views = ring.produce_views(3)
        assert sum(len(v) for v in views) == 3
        views[0][:] = np.arange(
            3 * NATIVE_ROW_WIDTH, dtype=np.float32).reshape(
                3, NATIVE_ROW_WIDTH)
        ring.commit(3)
        got = ring.consume(8)
        assert got.shape == (3, NATIVE_ROW_WIDTH)
        assert (got.ravel() == np.arange(3 * NATIVE_ROW_WIDTH)).all()
        assert len(ring) == 0

    def test_wraparound_preserves_row_integrity(self):
        ring = NativeFeatureRing(4)
        # fill, consume 2, refill past the physical end
        v = ring.produce_views()
        v[0][:] = 1.0
        ring.commit(4)
        ring.consume(2)
        views = ring.produce_views()
        total = sum(len(w) for w in views)
        assert total == 2  # free slots only
        for w in views:
            w[:] = 7.0
        ring.commit(2)
        # rows come out whole and in order: two old, then two new
        a = ring.consume(16)
        b = ring.consume(16)
        rows = np.concatenate([a.copy(), b.copy()])
        assert (rows[:2] == 1.0).all()
        assert (rows[2:] == 7.0).all()

    def test_backpressure_drops_and_counts_never_corrupts(self):
        """A full ring exposes NO writable views — overflow rows are
        dropped at the producer (drop-and-count), and the unconsumed
        rows read back bit-identical."""
        ring = NativeFeatureRing(4)
        v = ring.produce_views()
        for i, w in enumerate(v):
            w[:] = float(i + 1)
        ring.commit(4)
        before = ring.buf.copy()
        assert ring.produce_views() == []  # no room: nothing writable
        ring.drop(3)  # producer counts the overflow
        assert ring.dropped == 3
        assert (ring.buf == before).all()
        assert len(ring.consume(16)) == 4

    def test_commit_beyond_free_raises(self):
        ring = NativeFeatureRing(2)
        ring.produce_views()
        ring.commit(2)
        with pytest.raises(ValueError):
            ring.commit(1)


class TestNativeFeaturizer:
    def test_vectorized_encoding_matches_featurize(self):
        """The zero-copy block encoder must agree with the per-row
        reference encoding on every column it populates."""
        f = NativeFeaturizer(resolver=lambda rid: f"/svc/route-{rid}")
        block = np.array([
            # route_id, lat_ms, status, req_b, rsp_b, ts_s
            [3, 12.5, 200, 100, 2048, 1.0],
            [3, 80.0, 500, 10, 0, 1.1],
            [7, 5.0, 404, 0, 512, 1.2],
        ], np.float32)
        x, inv, dsts = f.encode_block(block)
        assert x.shape == (3, FEATURE_DIM)
        assert sorted(dsts) == ["/svc/route-3", "/svc/route-7"]
        for i, row in enumerate(block):
            ref = featurize(FeatureVector(
                latency_ms=float(row[1]), status=int(row[2]),
                request_bytes=int(row[3]), response_bytes=int(row[4]),
                concurrency=1, dst_path=dsts[inv[i]]))
            # drift col (32) uses block-granular temporal state; all
            # other populated columns must match the reference exactly
            ref[32] = x[i, 32]
            assert np.allclose(x[i], ref, atol=1e-6), f"row {i}"

    def test_temporal_drift_reacts_to_latency_shift(self):
        f = NativeFeaturizer(resolver=lambda rid: "/svc/a")
        base = np.array([[1, 10.0, 200, 0, 0, 1.0]] * 8, np.float32)
        f.encode_block(base)
        spike = np.array([[1, 200.0, 200, 0, 0, 2.0]], np.float32)
        x, _, _ = f.encode_block(spike)
        assert x[0, 32] > 2.0  # log1p(~190) ≈ 5.2


class TestLineRateBatcher:
    def test_rows_scored_within_linger_without_manual_drain(self):
        """The batcher is deadline-triggered: appended rows score
        within ~maxLingerMs with NO manual drain call, and the scored
        fraction reads 1.0 — 100% scored is measured, not asserted."""

        class Stub:
            async def score(self, x):
                return np.zeros(len(x), np.float32)

            async def fit(self, x, labels, mask):
                return 0.0

            def close(self):
                pass

        async def go():
            mt = MetricsTree()
            cfg = JaxAnomalyConfig(maxBatch=64, trainEveryBatches=0,
                                   maxLingerMs=2.0)
            tele = JaxAnomalyTelemeter(cfg, mt, scorer=Stub())
            rec = tele.recorder()
            drain = asyncio.ensure_future(tele.run())
            try:
                from linkerd_tpu.protocol.http import Request, Response
                from linkerd_tpu.router.service import FnService

                async def ok(req):
                    return Response(200)

                svc = rec.and_then(FnService(ok))
                for _ in range(10):
                    await svc(Request())
                t0 = time.monotonic()
                while mt.flatten().get("anomaly/scored_total", 0) < 10:
                    assert time.monotonic() - t0 < 2.0, \
                        "rows not scored within deadline"
                    await asyncio.sleep(0.005)
                flat = mt.flatten()
                assert flat["anomaly/requests_total"] == 10
                assert flat["anomaly/scored_total"] == 10
                assert flat["anomaly/scored_fraction"] == 1.0
                state = tele.model_state()
                assert state["scored_fraction"] == 1.0
                assert state["line_rate"] is True
            finally:
                drain.cancel()
                await asyncio.gather(drain, return_exceptions=True)
                tele.close()

        run(go())

    def test_native_rows_flow_through_batcher(self):
        """Engine-style rows fed through the native ring are scored,
        attributed to their dst on the board, and counted toward the
        scored fraction."""

        class Stub:
            async def score(self, x):
                # score = normalized first column so dsts differ
                return (x[:, 0] / 10.0).astype(np.float32)

            async def fit(self, x, labels, mask):
                return 0.0

            def close(self):
                pass

        async def go():
            mt = MetricsTree()
            cfg = JaxAnomalyConfig(maxBatch=64, trainEveryBatches=0)
            tele = JaxAnomalyTelemeter(cfg, mt, scorer=Stub())
            tele.set_native_route_resolver(lambda rid: f"/fp/route-{rid}")
            views = tele.native_ring.produce_views(4)
            views[0][:] = np.array([
                [1, 50.0, 200, 0, 0, 1.0, 0, 0, 0, 0, 0, 0],
                [1, 60.0, 200, 0, 0, 1.1, 0, 0, 0, 0, 0, 0],
                [2, 900.0, 500, 0, 0, 1.2, 0, 0, 0, 0, 0, 0],
                [2, 950.0, 500, 0, 0, 1.3, 0, 0, 0, 0, 0, 0],
            ], np.float32)
            tele.native_ring.commit(4)
            tele.native_committed(4)
            n = await tele.drain_once()
            assert n == 4
            flat = mt.flatten()
            assert flat["anomaly/requests_total"] == 4
            assert flat["anomaly/scored_total"] == 4
            scores = tele.board.scores.sample()
            assert set(scores) == {"/fp/route-1", "/fp/route-2"}
            assert scores["/fp/route-2"] > scores["/fp/route-1"]
            tele.close()

        run(go())

    def test_mixed_python_and_native_batch(self):
        class Stub:
            async def score(self, x):
                return np.full(len(x), 0.5, np.float32)

            async def fit(self, x, labels, mask):
                return 0.0

            def close(self):
                pass

        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(maxBatch=64, trainEveryBatches=0),
                MetricsTree(), scorer=Stub())
            tele.ring.append((FeatureVector(dst_path="/svc/py"), None))
            tele.set_native_route_resolver(lambda rid: "/fp/nat")
            v = tele.native_ring.produce_views(2)
            v[0][:] = np.array(
                [[9, 1.0, 200, 0, 0, 1.0, 0, 0, 0, 0, 0, 0],
                 [9, 2.0, 200, 0, 0, 1.1, 0, 0, 0, 0, 0, 0]], np.float32)
            tele.native_ring.commit(2)
            n = await tele.drain_once()
            assert n == 3
            scores = tele.board.scores.sample()
            assert set(scores) == {"/svc/py", "/fp/nat"}
            tele.close()

        run(go())


class TestTieredScorer:
    class _Primary:
        def __init__(self):
            self.fail = False
            self.calls = 0

        async def score(self, x):
            self.calls += 1
            if self.fail:
                raise RuntimeError("device sick")
            return np.zeros(len(x), np.float32)

        async def fit(self, x, labels, mask):
            if self.fail:
                raise RuntimeError("device sick")
            return 0.1

        def snapshot(self):
            return "snap"

        def restore(self, snap):
            self.restored = snap

        def close(self):
            self.closed = True

    class _Fallback:
        def __init__(self):
            self.calls = 0

        async def score(self, x):
            self.calls += 1
            return np.ones(len(x), np.float32)

        async def fit(self, x, labels, mask):
            return 0.2

        def close(self):
            self.closed = True

    def test_primary_serves_then_fallback_on_failure(self):
        from linkerd_tpu.telemetry.resilience import CircuitBreaker

        async def go():
            p, f = self._Primary(), self._Fallback()
            import itertools
            tiered = TieredScorer(p, f, breaker=CircuitBreaker(
                failures=1, backoffs=itertools.repeat(0.05)))
            x = np.zeros((4, 2), np.float32)
            assert (await tiered.score(x) == 0.0).all()  # primary
            assert tiered.primary_calls == 1
            p.fail = True
            assert (await tiered.score(x) == 1.0).all()  # fell back
            assert tiered.fallback_calls == 1
            # breaker open: the next call goes straight to fallback
            assert (await tiered.score(x) == 1.0).all()
            assert p.calls == 2  # no third primary attempt
            # primary heals; the probe (after backoff) re-admits it
            p.fail = False
            await asyncio.sleep(0.06)
            assert (await tiered.score(x) == 0.0).all()
            st = tiered.tier_state()
            assert st["primary_breaker"] == "closed"
            tiered.close()
            assert p.closed and f.closed

        run(go())

    def test_lifecycle_hooks_bind_to_primary(self):
        p, f = self._Primary(), self._Fallback()
        tiered = TieredScorer(p, f)
        assert tiered.snapshot() == "snap"
        tiered.restore("other")
        assert p.restored == "other"

    def test_telemeter_builds_tiered_scorer_by_default(self):
        """sidecarAddress + the default fallback tier => TieredScorer
        with an in-process primary; sidecarTier: primary keeps the
        legacy resilient-sidecar wiring."""
        from linkerd_tpu.telemetry.resilience import ResilientScorer

        cfg = JaxAnomalyConfig(sidecarAddress="127.0.0.1:1",
                               trainEveryBatches=0)
        tele = JaxAnomalyTelemeter(cfg, MetricsTree())
        s = tele._ensure_scorer()
        assert isinstance(s, TieredScorer)
        assert isinstance(s.primary, InProcessScorer)
        assert tele.model_state()["tiers"]["primary"] == "InProcessScorer"
        tele.close()

        cfg2 = JaxAnomalyConfig(sidecarAddress="127.0.0.1:1",
                                sidecarTier="primary",
                                trainEveryBatches=0)
        tele2 = JaxAnomalyTelemeter(cfg2, MetricsTree())
        assert isinstance(tele2._ensure_scorer(), ResilientScorer)
        tele2.close()

    def test_bad_tier_value_rejected(self):
        with pytest.raises(ValueError):
            JaxAnomalyTelemeter(
                JaxAnomalyConfig(sidecarTier="nope"), MetricsTree())


class TestShardBatch:
    def test_shard_batch_matches_device_put(self):
        import jax
        from linkerd_tpu.parallel.mesh import (
            batch_sharding, make_mesh, shard_batch,
        )

        mesh = make_mesh(jax.devices()[:1])
        x = np.random.default_rng(3).standard_normal(
            (8, 4)).astype(np.float32)
        got = shard_batch(mesh, x)
        ref = jax.device_put(x, batch_sharding(mesh))
        assert got.shape == ref.shape
        assert got.sharding == ref.sharding
        assert (np.asarray(got) == np.asarray(ref)).all()


class TestFastpathNativeFeed:
    """FastPathController drains engine feature rows C -> the
    telemeter's NativeFeatureRing (no per-row Python objects) and
    counts overflow as drops."""

    class _StubEngine:
        """drain_features_into semantics of the native engines: fill up
        to len(out) rows from a pending pool, return the count."""

        def __init__(self, rows):
            self.pending = [np.asarray(r, np.float32) for r in rows]

        def drain_features_into(self, out):
            n = min(len(out), len(self.pending))
            for i in range(n):
                out[i] = self.pending.pop(0)
            return n

        def drain_features(self):
            return np.zeros((0, NATIVE_ROW_WIDTH), np.float32)

    class _StubScorer:
        async def score(self, x):
            return np.zeros(len(x), np.float32)

        async def fit(self, x, labels, mask):
            return 0.0

        def close(self):
            pass

    def _mk_controller(self, engine, tele):
        from linkerd_tpu.core import Dtab, Path
        from linkerd_tpu.router.fastpath import FastPathController
        return FastPathController(
            engine, interpreter=None, base_dtab=Dtab.read(""),
            prefix=Path.read("/svc"), label="fp",
            metrics=MetricsTree(), telemeters=[tele])

    def test_rows_drain_into_native_ring(self):
        async def go():
            mt = MetricsTree()
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0), mt,
                scorer=self._StubScorer())
            eng = self._StubEngine(
                [[5, 12.0, 200, 10, 20, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0],
                 [5, 14.0, 500, 10, 20, 1.1, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]])
            ctl = self._mk_controller(eng, tele)
            ctl._id_to_host[5] = "web"
            ctl._forward_features()
            assert len(tele.native_ring) == 2
            assert mt.flatten()["anomaly/requests_total"] == 2
            n = await tele.drain_once()
            assert n == 2
            # resolver installed: rows attributed under the fastpath
            # prefix + engine host
            assert "/svc/web" in tele.board.scores.sample()
            tele.close()

        run(go())

    def test_overflow_drops_and_counts(self):
        async def go():
            mt = MetricsTree()
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0, ringCapacity=4),
                mt, scorer=self._StubScorer())
            rows = [[1, float(i), 200, 0, 0, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0,
                     0.0]
                    for i in range(10)]
            ctl = self._mk_controller(self._StubEngine(rows), tele)
            ctl._forward_features()
            assert len(tele.native_ring) == 4  # capacity
            assert tele.native_ring.dropped == 6  # counted, not lost track of
            # shed rows still count toward requests_total: under
            # backpressure the scored fraction must read < 1.0
            assert mt.flatten()["anomaly/requests_total"] == 10
            await tele.drain_once()
            assert mt.flatten()["anomaly/scored_total"] == 4
            assert mt.flatten()["anomaly/scored_fraction"] == \
                pytest.approx(0.4)
            got = tele.native_ring.consume(16).copy()
            assert len(got) == 0  # drained
            tele.close()

        run(go())

    def test_fan_out_to_multiple_telemeters(self):
        """Two jaxAnomaly telemeters both receive the drained block
        (the first zero-copy, the second by copy) — neither starves."""

        async def go():
            mts = [MetricsTree(), MetricsTree()]
            teles = [JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0), m,
                scorer=self._StubScorer()) for m in mts]
            rows = [[3, float(i), 200, 0, 0, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0,
                     0.0]
                    for i in range(6)]
            eng = self._StubEngine(rows)
            from linkerd_tpu.core import Dtab, Path
            from linkerd_tpu.router.fastpath import FastPathController
            ctl = FastPathController(
                eng, interpreter=None, base_dtab=Dtab.read(""),
                prefix=Path.read("/svc"), label="fp",
                metrics=MetricsTree(), telemeters=teles)
            ctl._id_to_host[3] = "web"
            ctl._forward_features()
            for tele, mt in zip(teles, mts):
                assert len(tele.native_ring) == 6
                assert mt.flatten()["anomaly/requests_total"] == 6
                assert await tele.drain_once() == 6
                assert "/svc/web" in tele.board.scores.sample()
                tele.close()

        run(go())

    def test_real_engine_drain_into_plumbing(self):
        """ctypes pointer plumbing against the real native lib: an
        idle engine drains zero rows into a ring view and rejects
        non-contiguous/wrong-dtype buffers."""
        native = pytest.importorskip("linkerd_tpu.native")
        if not native.available():
            pytest.skip("native lib unavailable")
        eng = native.FastPathEngine()
        try:
            ring = NativeFeatureRing(16)
            views = ring.produce_views(8)
            assert eng.drain_features_into(views[0]) == 0
            with pytest.raises(ValueError):
                eng.drain_features_into(
                    np.zeros((4, NATIVE_ROW_WIDTH), np.float64))
            with pytest.raises(ValueError):
                eng.drain_features_into(
                    np.zeros((4, 2 * NATIVE_ROW_WIDTH), np.float32)[:, ::2])
        finally:
            eng.close()


class TestSampledTiming:
    def test_span_sink_timing_is_sampled_not_per_batch(self):
        """With a span sink installed, only 1-in-N batches pay the
        instrumented two-barrier path; the rest stay on the ring. The
        FIRST batch is always sampled so span tags exist immediately."""

        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0), MetricsTree())
            tele.set_tracer(lambda span: None)  # any sink-shaped object
            scorer = tele._ensure_scorer()
            assert scorer.timing_enabled
            assert scorer.timing_sample_every == \
                JaxAnomalyTelemeter.TIMING_SAMPLE_EVERY
            x = np.zeros((8, scorer.cfg.in_dim), np.float32)
            for _ in range(8):
                await scorer.score(x)
            # exactly one timed call in the first 8 (the first)
            assert scorer.timing_totals["calls"] == 1
            assert scorer.last_timing is not None
            tele.close()

        run(go())


class TestTieredFit:
    def test_fit_never_routes_to_fallback(self):
        """Training binds to the primary (the lifecycle-managed model):
        with the primary breaker open, fit raises ScorerUnavailable
        instead of silently training the sidecar's remote model."""
        from linkerd_tpu.telemetry.resilience import (
            CircuitBreaker, ScorerUnavailable,
        )

        class Primary:
            def __init__(self):
                self.fail = False
                self.fits = 0

            async def score(self, x):
                if self.fail:
                    raise RuntimeError("sick")
                return np.zeros(len(x), np.float32)

            async def fit(self, x, labels, mask):
                if self.fail:
                    raise RuntimeError("sick")
                self.fits += 1
                return 0.1

            def close(self):
                pass

        class Fallback:
            def __init__(self):
                self.fits = 0

            async def score(self, x):
                return np.ones(len(x), np.float32)

            async def fit(self, x, labels, mask):
                self.fits += 1
                return 0.2

            def close(self):
                pass

        async def go():
            import itertools
            p, f = Primary(), Fallback()
            tiered = TieredScorer(p, f, breaker=CircuitBreaker(
                failures=1, backoffs=itertools.repeat(30.0)))
            x = np.zeros((2, 2), np.float32)
            labels = mask = np.zeros(2, np.float32)
            assert await tiered.fit(x, labels, mask) == 0.1
            p.fail = True
            with pytest.raises(RuntimeError):
                await tiered.fit(x, labels, mask)  # breaker opens
            # open breaker: scoring falls back, training does NOT
            assert (await tiered.score(x) == 1.0).all()
            with pytest.raises(ScorerUnavailable):
                await tiered.fit(x, labels, mask)
            assert f.fits == 0  # the remote model was never trained
            tiered.close()

        run(go())
