"""namerd control plane: store, mesh iface, HTTP control API, mesh client.

Mirrors the reference's namerd tests: InMemoryDtabStore CAS semantics,
mesh iface streaming (namerd/iface/mesh), control-http CRUD/bind/addr
(namerd/iface/control-http/.../HttpControlServiceTest style), and the
io.l5d.mesh interpreter client with reconnect (interpreter/mesh).
"""

import asyncio
import json

import pytest

from linkerd_tpu.core import Dtab, Path, Var
from linkerd_tpu.core.activity import Ok
from linkerd_tpu.core.addr import Bound
from linkerd_tpu.grpc import ClientDispatcher, GrpcError
from linkerd_tpu.grpc.status import NOT_FOUND
from linkerd_tpu.interpreter.mesh import MeshClientInterpreter
from linkerd_tpu.mesh import (
    DELEGATOR_SVC, INTERPRETER_SVC, RESOLVER_SVC, converters, messages as m,
)
from linkerd_tpu.namer.fs import FsNamer
from linkerd_tpu.namerd import (
    DtabNamespaceAlreadyExists, DtabVersionMismatch, InMemoryDtabStore,
    Namerd,
)
from linkerd_tpu.namerd.http_api import HttpControlService
from linkerd_tpu.namerd.mesh_iface import MeshIface
from linkerd_tpu.namerd.store import FsDtabStore
from linkerd_tpu.protocol.h2.client import H2Client
from linkerd_tpu.protocol.h2.server import H2Server
from linkerd_tpu.protocol.http.message import Headers, Request
from linkerd_tpu.protocol.http.server import HttpServer


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


# ---- store -----------------------------------------------------------------

class TestInMemoryStore:
    def test_crud_and_cas(self):
        async def go():
            store = InMemoryDtabStore()
            await store.create("default", Dtab.read("/svc => /#/io.l5d.fs;"))
            with pytest.raises(DtabNamespaceAlreadyExists):
                await store.create("default", Dtab.empty())
            vd = await store.observe("default").to_future()
            assert "/svc" in vd.dtab.show

            # CAS with stale version fails
            with pytest.raises(DtabVersionMismatch):
                await store.update("default", Dtab.empty(), b"bogus")
            await store.update("default",
                               Dtab.read("/svc => /#/other;"), vd.version)
            vd2 = await store.observe("default").to_future()
            assert vd2.version != vd.version
            assert "/#/other" in vd2.dtab.show

            # observe is live
            states = []
            obs = store.observe("default")
            obs.states.observe(lambda st: states.append(st))
            await store.put("default", Dtab.read("/svc => /#/third;"))
            assert isinstance(states[-1], Ok)
            assert "/#/third" in states[-1].value.dtab.show

            assert store.list().sample() == frozenset({"default"})
            await store.delete("default")
            assert store.list().sample() == frozenset()
        run(go())

    def test_fs_store_persists(self, tmp_path):
        async def go():
            store = FsDtabStore(str(tmp_path))
            await store.create("prod", Dtab.read("/svc => /#/io.l5d.fs;"))
            store2 = FsDtabStore(str(tmp_path))
            vd = await store2.observe("prod").to_future()
            assert "/svc" in vd.dtab.show
        run(go())


# ---- proto converters ------------------------------------------------------

def test_dtab_proto_roundtrip():
    dtab = Dtab.read("/svc/* => /#/io.l5d.fs | /$/fail; /x => /y & /z;")
    back = converters.dtab_from_proto(
        m.MDtab.decode(converters.dtab_to_proto(dtab).encode()))
    assert back.show == dtab.show


# ---- end-to-end: namerd serving mesh + control-http ------------------------

def _mk_namerd(disco_dir) -> Namerd:
    store = InMemoryDtabStore(
        {"default": Dtab.read("/svc => /#/io.l5d.fs;")})
    namer = FsNamer(str(disco_dir), poll_interval=0.05)
    return Namerd(store, [(Path.read("/io.l5d.fs"), namer)])


@pytest.fixture
def disco(tmp_path):
    d = tmp_path / "disco"
    d.mkdir()
    (d / "web").write_text("127.0.0.1 8080\n127.0.0.1 8081\n")
    return d


class TestMeshIface:
    def test_get_and_stream_bound_tree(self, disco):
        async def go():
            namerd = _mk_namerd(disco)
            server = await H2Server(MeshIface(namerd).dispatcher).start()
            client = ClientDispatcher(
                H2Client("127.0.0.1", server.bound_port))

            req = m.MBindReq(
                root=converters.path_to_proto(Path.read("/default")),
                name=converters.path_to_proto(Path.read("/svc/web")))
            rsp = await client.unary(INTERPRETER_SVC, "GetBoundTree", req)
            assert rsp.tree.leaf is not None
            assert converters.path_from_proto(
                rsp.tree.leaf.id).show == "/#/io.l5d.fs/web"

            # dtab switch re-streams the bound tree
            stream = await client.server_stream(
                INTERPRETER_SVC, "StreamBoundTree", req)
            first = await stream.recv()
            assert first.tree.leaf is not None
            vd = await namerd.store.observe("default").to_future()
            await namerd.store.update(
                "default", Dtab.read("/svc => /$/fail;"), vd.version)
            second = await asyncio.wait_for(stream.recv(), 5)
            assert second.tree.fail is not None

            await server.close()
            await namerd.close()
        run(go())

    def test_resolver_streams_addr_churn(self, disco):
        async def go():
            namerd = _mk_namerd(disco)
            server = await H2Server(MeshIface(namerd).dispatcher).start()
            client = ClientDispatcher(
                H2Client("127.0.0.1", server.bound_port))

            req = m.MReplicasReq(id=converters.path_to_proto(
                Path.read("/#/io.l5d.fs/web")))
            rep = await client.unary(RESOLVER_SVC, "GetReplicas", req)
            assert rep.bound is not None
            ports = sorted(ep.port for ep in rep.bound.endpoints)
            assert ports == [8080, 8081]

            stream = await client.server_stream(
                RESOLVER_SVC, "StreamReplicas", req)
            first = await asyncio.wait_for(stream.recv(), 5)
            assert first.bound is not None
            (disco / "web").write_text("127.0.0.1 9090\n")
            second = await asyncio.wait_for(stream.recv(), 5)
            assert [ep.port for ep in second.bound.endpoints] == [9090]

            await server.close()
            await namerd.close()
        run(go())

    def test_delegator_dtab(self, disco):
        async def go():
            namerd = _mk_namerd(disco)
            server = await H2Server(MeshIface(namerd).dispatcher).start()
            client = ClientDispatcher(
                H2Client("127.0.0.1", server.bound_port))
            rsp = await client.unary(
                DELEGATOR_SVC, "GetDtab",
                m.MDtabReq(root=converters.path_to_proto(
                    Path.read("/default"))))
            dtab = converters.dtab_from_proto(rsp.dtab.dtab)
            assert "/#/io.l5d.fs" in dtab.show
            with pytest.raises(GrpcError) as ei:
                await client.unary(
                    DELEGATOR_SVC, "GetDtab",
                    m.MDtabReq(root=converters.path_to_proto(
                        Path.read("/nope"))))
            assert ei.value.status.code == NOT_FOUND
            await server.close()
            await namerd.close()
        run(go())


class TestMeshInterpreterClient:
    def test_bind_via_remote_namerd_with_live_addrs(self, disco):
        async def go():
            namerd = _mk_namerd(disco)
            server = await H2Server(MeshIface(namerd).dispatcher).start()
            interp = MeshClientInterpreter(
                "127.0.0.1", server.bound_port, root="default",
                backoff_base=0.05, backoff_max=0.2)

            act = interp.bind(Dtab.empty(), Path.read("/svc/web"))
            tree = await asyncio.wait_for(act.to_future(), 5)
            from linkerd_tpu.core.nametree import Leaf
            assert isinstance(tree, Leaf)
            bn = tree.value
            assert bn.id_.show == "/#/io.l5d.fs/web"

            # addr var fed by StreamReplicas
            for _ in range(100):
                if isinstance(bn.addr.sample(), Bound):
                    break
                await asyncio.sleep(0.05)
            addr = bn.addr.sample()
            assert isinstance(addr, Bound)
            assert sorted(a.port for a in addr.addresses) == [8080, 8081]

            # file edit -> namerd fs namer -> resolver stream -> client var
            (disco / "web").write_text("127.0.0.1 7070\n")
            for _ in range(100):
                a = bn.addr.sample()
                if isinstance(a, Bound) and \
                        sorted(x.port for x in a.addresses) == [7070]:
                    break
                await asyncio.sleep(0.05)
            assert sorted(x.port for x in bn.addr.sample().addresses) == [7070]

            await interp.aclose()
            await server.close()
            await namerd.close()
        run(go())


# ---- HTTP control API ------------------------------------------------------

async def _http_get(port: int, uri: str, headers=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    hdrs = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    writer.write(f"GET {uri} HTTP/1.1\r\nHost: t\r\n{hdrs}"
                 f"Connection: close\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    hdr_map = {}
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b": ")
        hdr_map[k.decode().lower()] = v.decode()
    if hdr_map.get("transfer-encoding") == "chunked":
        # de-chunk
        out = b""
        rest = body
        while rest:
            ln, _, rest = rest.partition(b"\r\n")
            n = int(ln, 16)
            if n == 0:
                break
            out += rest[:n]
            rest = rest[n + 2:]
        body = out
    return status, hdr_map, body


async def _http_req(port: int, method: str, uri: str, body: bytes = b"",
                    headers=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    hdrs = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    writer.write(
        f"{method} {uri} HTTP/1.1\r\nHost: t\r\n{hdrs}"
        f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
        + body)
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, rbody = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ", 2)[1]), rbody


class TestHttpControlApi:
    def test_dtab_crud_and_bind(self, disco):
        async def go():
            namerd = _mk_namerd(disco)
            server = await HttpServer(HttpControlService(namerd)).start()
            port = server.bound_port

            status, hdrs, body = await _http_get(port, "/api/1/dtabs")
            assert status == 200 and json.loads(body) == ["default"]

            status, hdrs, body = await _http_get(port, "/api/1/dtabs/default")
            assert status == 200
            assert json.loads(body) == [
                {"prefix": "/svc", "dst": "/#/io.l5d.fs"}]
            etag = hdrs["etag"]

            # CAS PUT with ETag
            st, _ = await _http_req(
                port, "PUT", "/api/1/dtabs/default",
                b"/svc => /#/updated;",
                headers={"If-Match": etag, "Content-Type": "application/dtab"})
            assert st == 204
            st, _ = await _http_req(
                port, "PUT", "/api/1/dtabs/default", b"/svc => /#/x;",
                headers={"If-Match": etag})
            assert st == 412  # stale version

            # create + delete
            st, _ = await _http_req(port, "POST", "/api/1/dtabs/stage",
                                    b"/svc => /$/fail;")
            assert st == 204
            st, _ = await _http_req(port, "POST", "/api/1/dtabs/stage", b"")
            assert st == 409
            st, _ = await _http_req(port, "DELETE", "/api/1/dtabs/stage")
            assert st == 204
            st, _ = await _http_req(port, "DELETE", "/api/1/dtabs/stage")
            assert st == 404

            # bind + addr
            status, _, body = await _http_get(
                port, "/api/1/dtabs/default")
            assert json.loads(body)[0]["dst"] == "/#/updated"
            st, _ = await _http_req(
                port, "PUT", "/api/1/dtabs/default",
                b"/svc => /#/io.l5d.fs;")
            status, _, body = await _http_get(
                port, "/api/1/bind/default?path=/svc/web")
            tree = json.loads(body)
            assert tree["type"] == "leaf" and tree["id"] == "/#/io.l5d.fs/web"

            status, _, body = await _http_get(
                port, "/api/1/addr/default?path=/svc/web")
            addr = json.loads(body)
            assert addr["type"] == "bound"
            assert sorted(a["port"] for a in addr["addrs"]) == [8080, 8081]

            await server.close()
            await namerd.close()
        run(go())

    def test_watch_streams_dtab_updates(self, disco):
        async def go():
            namerd = _mk_namerd(disco)
            server = await HttpServer(HttpControlService(namerd)).start()
            port = server.bound_port

            async def watch():
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port)
                writer.write(b"GET /api/1/dtabs/default?watch=true "
                             b"HTTP/1.1\r\nHost: t\r\n\r\n")
                await writer.drain()
                lines = []
                # skip headers
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b""):
                        break
                # read 2 chunks (initial + updated)
                while len(lines) < 2:
                    ln = await reader.readline()  # chunk size
                    if not ln.strip():
                        continue
                    n = int(ln, 16)
                    data = await reader.readexactly(n)
                    await reader.readline()
                    lines.append(json.loads(data))
                writer.close()
                return lines

            task = asyncio.ensure_future(watch())
            await asyncio.sleep(0.2)
            await namerd.store.put(
                "default", Dtab.read("/svc => /#/flipped;"))
            lines = await asyncio.wait_for(task, 10)
            assert lines[0][0]["dst"] == "/#/io.l5d.fs"
            assert lines[1][0]["dst"] == "/#/flipped"

            await server.close()
            await namerd.close()
        run(go())


# ---- full loop: linkerd router -> mesh interpreter -> namerd ---------------

class TestLinkerdViaNamerd:
    def test_router_binds_through_namerd_and_dtab_flip_reroutes(self, disco):
        """The reference validator scenario (validator/.../Validator.scala):
        traffic through linkerd, dtab flipped in namerd, re-routes live."""
        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.http import Request, Response
        from linkerd_tpu.protocol.http.client import HttpClient
        from linkerd_tpu.protocol.http.server import serve
        from linkerd_tpu.router.service import FnService

        def downstream(name):
            async def handler(req):
                return Response(status=200, body=name.encode())
            return FnService(handler)

        async def go():
            d_a = await serve(downstream("A"))
            d_b = await serve(downstream("B"))
            (disco / "web").write_text(f"127.0.0.1 {d_a.bound_port}\n")
            (disco / "web2").write_text(f"127.0.0.1 {d_b.bound_port}\n")

            namerd = _mk_namerd(disco)
            mesh_srv = await H2Server(MeshIface(namerd).dispatcher).start()

            cfg = f"""
routers:
- protocol: http
  label: out
  interpreter:
    kind: io.l5d.mesh
    dst: /$/inet/127.0.0.1/{mesh_srv.bound_port}
    root: /default
  servers:
  - port: 0
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "web")
                r = await proxy(req)
                assert (r.status, r.body) == (200, b"A")

                # flip the dtab in namerd -> routes to web2 (B), live
                await namerd.store.put(
                    "default", Dtab.read("/svc/web => /#/io.l5d.fs/web2;"))
                for _ in range(100):
                    r = await proxy(req)
                    if r.body == b"B":
                        break
                    await asyncio.sleep(0.05)
                assert r.body == b"B"
            finally:
                await proxy.close()
                await linker.close()
                await mesh_srv.close()
                await namerd.close()
        run(go())


class TestDelegateApiErrors:
    def test_missing_path_is_400(self, disco):
        async def go():
            namerd = _mk_namerd(disco)
            server = await HttpServer(HttpControlService(namerd)).start()
            st, body = await _http_req(
                server.bound_port, "GET", "/api/1/delegate/default")
            assert st == 400
            await server.close()
            await namerd.close()
        run(go())


class TestNamerdHttpInterpreter:
    def test_bind_via_http_watch_with_dtab_flip(self, disco):
        """io.l5d.namerd.http: binds + addrs stream over the control
        API's chunked watches (StreamingNamerClient.scala behavior)."""
        from linkerd_tpu.interpreter.namerd_http import NamerdHttpInterpreter
        from linkerd_tpu.core.nametree import Leaf

        async def go():
            namerd = _mk_namerd(disco)
            server = await HttpServer(HttpControlService(namerd)).start()
            interp = NamerdHttpInterpreter(
                "127.0.0.1", server.bound_port, namespace="default",
                backoff_base=0.05, backoff_max=0.2)

            act = interp.bind(Dtab.empty(), Path.read("/svc/web"))
            tree = await asyncio.wait_for(act.to_future(), 5)
            assert isinstance(tree, Leaf)
            bn = tree.value
            assert bn.id_.show == "/#/io.l5d.fs/web"
            for _ in range(100):
                if isinstance(bn.addr.sample(), Bound):
                    break
                await asyncio.sleep(0.05)
            assert sorted(a.port for a in bn.addr.sample().addresses) == \
                [8080, 8081]

            # dtab flip in namerd propagates through the HTTP watch
            await namerd.store.put(
                "default", Dtab.read("/svc => /$/fail;"))
            from linkerd_tpu.core.nametree import Fail
            for _ in range(100):
                st = act.current
                from linkerd_tpu.core.activity import Ok
                if isinstance(st, Ok) and isinstance(st.value, Fail):
                    break
                await asyncio.sleep(0.05)
            assert isinstance(act.sample(), Fail)

            await interp.aclose()
            await server.close()
            await namerd.close()
        run(go())
