"""Istio integration: proto codegen, mixer client, pilot caches, namer,
identifier, and interpreter — all against scripted fake Pilot/mixer
services (the reference's test style: MixerClientTest etc. replay
captured API payloads into in-process services).
"""

import asyncio
import json

import pytest

from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.core.activity import Ok
from linkerd_tpu.core.addr import Address, Bound
from linkerd_tpu.core.nametree import Leaf, Neg, Union as TreeUnion
from linkerd_tpu.istio import mixer_pb as pb
from linkerd_tpu.istio.identifier import (
    IstioIdentifierLogic, RequestMeta, http_rewrite,
)
from linkerd_tpu.istio.interpreter import mk_istio_interpreter, routes_dtab
from linkerd_tpu.istio.mixer import MixerClient, mk_report_request
from linkerd_tpu.istio.namer import IstioNamer
from linkerd_tpu.istio.pilot import (
    ApiserverClient, ClusterCache, DiscoveryClient, RouteCache, RouteRule,
    StringMatch,
)
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.router.binding import DstPath
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


class FakePilot:
    """SDS + RDS + apiserver in one fake HTTP service."""

    def __init__(self):
        # cluster|port|k=v... -> [(ip, port)]
        self.registrations = {}
        self.virtual_hosts = []  # [{"name": "dest|port", "domains": [..]}]
        self.route_rules = []    # [{"type","name","spec"}]

    def service(self):
        async def handler(req: Request) -> Response:
            path = req.uri.split("?", 1)[0]
            if path.startswith("/v1/registration/"):
                key = path[len("/v1/registration/"):]
                hosts = [{"ip_address": ip, "port": port}
                         for ip, port in self.registrations.get(key, [])]
                return Response(status=200,
                                body=json.dumps({"hosts": hosts}).encode())
            if path == "/v1/routes":
                return Response(status=200, body=json.dumps(
                    [{"virtual_hosts": self.virtual_hosts}]).encode())
            if path == "/v1alpha1/config/route-rule":
                return Response(status=200,
                                body=json.dumps(self.route_rules).encode())
            return Response(status=404)

        return FnService(handler)


RULES = [
    {"type": "route-rule", "name": "to-v1", "spec": {
        "destination": "reviews.default.svc.cluster.local",
        "precedence": 2,
        "match": {"httpHeaders": {
            "uri": {"prefix": "/api/"},
        }},
        "rewrite": {"uri": "/v1/"},
        "route": [
            {"tags": {"version": "v1"}, "weight": 90},
            {"tags": {"version": "v2"}, "weight": 10},
        ],
    }},
    {"type": "route-rule", "name": "redirect-old", "spec": {
        "destination": "reviews.default.svc.cluster.local",
        "precedence": 5,
        "match": {"httpHeaders": {"uri": {"exact": "/old"}}},
        "redirect": {"uri": "/new", "authority": "reviews"},
    }},
]


class TestProtoGen:
    def test_mixer_report_roundtrip(self):
        req = mk_report_request(200, "/api", "reviews.default", "caller",
                                "reviews", "v1", 0.25)
        out = pb.ReportRequest.decode(req.encode())
        attrs = out.attribute_update
        words = attrs.dictionary
        # dictionary indices are self-describing
        path_idx = [i for i, w in words.items() if w == "request.path"][0]
        assert attrs.string_attributes[path_idx] == "/api"
        code_idx = [i for i, w in words.items() if w == "response.code"][0]
        assert attrs.int64_attributes[code_idx] == 200
        dur_idx = [i for i, w in words.items()
                   if w == "response.duration"][0]
        d = attrs.duration_attributes_HACK[dur_idx]
        assert d.seconds == 0 and 2.4e8 < d.nanos < 2.6e8

    def test_interop_with_google_protobuf_duration(self):
        """Wire-compat spot check against the real protobuf runtime."""
        gp = pytest.importorskip("google.protobuf.duration_pb2")
        ours = pb.Duration(seconds=3, nanos=500)
        theirs = gp.Duration()
        theirs.ParseFromString(ours.encode())
        assert (theirs.seconds, theirs.nanos) == (3, 500)
        theirs2 = gp.Duration(seconds=7, nanos=9)
        back = pb.Duration.decode(theirs2.SerializeToString())
        assert (back.seconds, back.nanos) == (7, 9)


class TestMixerClient:
    def test_report_over_grpc(self):
        """MixerClient.report against a mixer served by the in-repo gRPC
        runtime (bidi-streaming Report)."""
        from linkerd_tpu.grpc import ServerDispatcher
        from linkerd_tpu.protocol.h2.client import H2Client
        from linkerd_tpu.protocol.h2.server import H2Server

        seen = []
        disp = ServerDispatcher()

        async def report(reqs):
            async def gen():
                async for r in reqs:
                    seen.append(r)
                    yield pb.ReportResponse(request_index=r.request_index)
            return gen()

        disp.register(pb.MIXER_SVC, "Report", report)

        async def go():
            server = await H2Server(disp).start()
            h2 = H2Client("127.0.0.1", server.bound_port)
            client = MixerClient(h2)
            try:
                rsp = await client.report(
                    500, "/reviews", "reviews.default.svc.cluster.local",
                    "productpage", "reviews", "v1", 0.04)
                assert isinstance(rsp, pb.ReportResponse)
                assert len(seen) == 1
                attrs = seen[0].attribute_update
                assert "reviews.default.svc.cluster.local" in \
                    attrs.dictionary.values()
            finally:
                await h2.close()
                await server.close()

        run(go())


class TestPilotCaches:
    def test_cluster_cache_and_route_cache(self):
        async def go():
            pilot = FakePilot()
            pilot.virtual_hosts = [
                {"name": "reviews.default.svc.cluster.local|http",
                 "domains": ["reviews", "reviews.default"]},
                {"name": "bogus-name", "domains": ["x"]},
            ]
            pilot.route_rules = RULES
            server = await HttpServer(pilot.service()).start()
            discovery = DiscoveryClient("127.0.0.1", server.bound_port,
                                        interval=0.1)
            apiserver = ApiserverClient("127.0.0.1", server.bound_port,
                                        interval=0.1)
            clusters = ClusterCache(discovery)
            routes = RouteCache(apiserver)
            try:
                c = await asyncio.wait_for(clusters.get("reviews"), 5)
                assert c is not None
                assert c.dest == "reviews.default.svc.cluster.local"
                assert c.port == "http"
                assert await clusters.get("nope") is None

                rules = await asyncio.wait_for(routes.get_rules(), 5)
                assert set(rules) == {"to-v1", "redirect-old"}
                assert rules["to-v1"].precedence == 2
                assert rules["to-v1"].match_headers["uri"].prefix == "/api/"
                assert rules["to-v1"].route[0].tags == {"version": "v1"}
            finally:
                clusters.close()
                routes.close()
                discovery.close()
                apiserver.close()
                await server.close()

        run(go())


class TestIstioNamer:
    def test_sds_lookup(self):
        async def go():
            pilot = FakePilot()
            pilot.registrations[
                "reviews.default.svc.cluster.local|http|version=v1"] = [
                ("10.0.0.1", 8080), ("10.0.0.2", 8080)]
            server = await HttpServer(pilot.service()).start()
            discovery = DiscoveryClient("127.0.0.1", server.bound_port,
                                        interval=0.1)
            namer = IstioNamer(discovery)
            try:
                act = namer.lookup(Path.read(
                    "/reviews.default.svc.cluster.local/version:v1/http"))
                for _ in range(100):
                    if isinstance(act.current, Ok) and isinstance(
                            act.current.value, Leaf):
                        break
                    await asyncio.sleep(0.05)
                tree = act.sample()
                assert isinstance(tree, Leaf)
                addr = tree.value.addr.sample()
                assert isinstance(addr, Bound)
                assert Address("10.0.0.1", 8080) in addr.addresses

                # unknown cluster -> Neg (empty SDS answer)
                act2 = namer.lookup(Path.read("/ghost/::/http"))
                for _ in range(100):
                    if isinstance(act2.current, Ok):
                        break
                    await asyncio.sleep(0.05)
                assert isinstance(act2.sample(), Neg)
            finally:
                namer.close()
                discovery.close()
                await server.close()

        run(go())


class TestIstioIdentifier:
    def mk_logic(self, pilot_port):
        discovery = DiscoveryClient("127.0.0.1", pilot_port, interval=0.1)
        apiserver = ApiserverClient("127.0.0.1", pilot_port, interval=0.1)
        return IstioIdentifierLogic(
            ClusterCache(discovery), RouteCache(apiserver),
            Path.read("/svc"), Dtab.empty())

    def test_identify_route_rewrite_redirect_external(self):
        async def go():
            pilot = FakePilot()
            pilot.virtual_hosts = [
                {"name": "reviews.default.svc.cluster.local|http",
                 "domains": ["reviews"]}]
            pilot.route_rules = RULES
            server = await HttpServer(pilot.service()).start()
            logic = self.mk_logic(server.bound_port)
            rewrites = []

            def apply_rewrite(uri, authority):
                rewrites.append((uri, authority))

            def mk_redirect(uri, authority):
                return ("REDIRECT", uri, authority)

            def meta(uri, headers=None):
                return RequestMeta(
                    uri=uri, scheme="http", method="GET",
                    authority="reviews",
                    get_header=(headers or {}).get)

            try:
                # matching rule: rewrite applied, route path
                dst = await logic.identify(
                    meta("/api/list"), Dtab.empty(), apply_rewrite,
                    mk_redirect)
                assert isinstance(dst, DstPath)
                assert dst.path.show == "/svc/route/to-v1/http"
                assert rewrites == [("/v1/list", "reviews")]

                # redirect rule wins by precedence on /old
                got = await logic.identify(
                    meta("/old"), Dtab.empty(), apply_rewrite, mk_redirect)
                assert got == ("REDIRECT", "/new", "reviews")

                # no rule matches -> dest path
                dst2 = await logic.identify(
                    meta("/plain"), Dtab.empty(), apply_rewrite,
                    mk_redirect)
                assert dst2.path.show == (
                    "/svc/dest/reviews.default.svc.cluster.local/::/http")

                # unknown vhost -> external
                m = RequestMeta(uri="/", scheme="http", method="GET",
                                authority="example.com:8443",
                                get_header=lambda _n: None)
                dst3 = await logic.identify(
                    m, Dtab.empty(), apply_rewrite, mk_redirect)
                assert dst3.path.show == "/svc/ext/example.com/8443"
            finally:
                logic.clusters.close()
                logic.routes.close()
                logic.clusters.discovery.close()
                logic.routes.api.close()
                await server.close()

        run(go())


class TestIstioInterpreter:
    def test_routes_dtab_synthesis(self):
        rules = {
            "to-v1": RouteRule.parse(RULES[0]["spec"]),
        }
        dtab = routes_dtab(rules)
        # default dtab + the route dentry
        shown = dtab.show
        assert "/svc/dest" in shown
        assert "/svc/route/to-v1" in shown
        # weighted union over version labels
        entry = [d for d in dtab
                 if d.prefix.show == "/svc/route/to-v1"][0]
        assert isinstance(entry.dst, TreeUnion)
        weights = sorted(w.weight for w in entry.dst.weighted)
        assert weights == [10.0, 90.0]
        leaf_shows = sorted(
            w.tree.value.show for w in entry.dst.weighted)
        assert leaf_shows == [
            "/#/io.l5d.k8s.istio/reviews.default.svc.cluster.local/version:v1",
            "/#/io.l5d.k8s.istio/reviews.default.svc.cluster.local/version:v2",
        ]

    def test_interpreter_binds_route_through_istio_namer(self):
        async def go():
            pilot = FakePilot()
            pilot.route_rules = [RULES[0]]
            pilot.registrations[
                "reviews.default.svc.cluster.local|http|version=v1"] = [
                ("10.0.1.1", 9080)]
            pilot.registrations[
                "reviews.default.svc.cluster.local|http|version=v2"] = [
                ("10.0.2.1", 9080)]
            server = await HttpServer(pilot.service()).start()
            discovery = DiscoveryClient("127.0.0.1", server.bound_port,
                                        interval=0.1)
            apiserver = ApiserverClient("127.0.0.1", server.bound_port,
                                        interval=0.1)
            namer = IstioNamer(discovery)
            cache = RouteCache(apiserver)
            interp = mk_istio_interpreter(
                cache, [(Path.read("/io.l5d.k8s.istio"), namer)])
            try:
                act = interp.bind(
                    Dtab.empty(), Path.read("/svc/route/to-v1/http"))
                for _ in range(100):
                    st = act.current
                    if isinstance(st, Ok) and not isinstance(
                            st.value.simplified, Neg):
                        break
                    await asyncio.sleep(0.05)
                tree = act.sample().simplified
                assert isinstance(tree, TreeUnion)
                leaves = [w.tree for w in tree.weighted]
                assert all(isinstance(l, Leaf) for l in leaves)
                addrs = set()
                for l in leaves:
                    a = l.value.addr.sample()
                    if isinstance(a, Bound):
                        addrs.update(a.addresses)
                assert Address("10.0.1.1", 9080) in addrs
                assert Address("10.0.2.1", 9080) in addrs
            finally:
                cache.close()
                namer.close()
                discovery.close()
                apiserver.close()
                await server.close()

        run(go())


class TestIstioIngressIdentifier:
    """The fused kind io.l5d.k8s.istio-ingress: istio traffic routed
    through a k8s Ingress resource (ref IstioIngressIdentifier.scala:1-128
    + the h2 twin)."""

    def _pilot(self):
        pilot = FakePilot()
        pilot.virtual_hosts = [
            {"name": "reviews.default.svc.cluster.local|http",
             "domains": ["reviews",
                         "reviews.default.svc.cluster.local:9080"]}]
        pilot.route_rules = RULES
        return pilot

    def _ingress_items(self):
        from test_k8s_ingress import ingress_obj
        return [ingress_obj(
            name="shop", ns="default", host="shop.example.com",
            path="/api/.*", svc="reviews", port="9080",
            annotations={"kubernetes.io/ingress.class": "istio"})]

    def test_linker_routes_istio_request_by_ingress_rule(self, tmp_path):
        """e2e: ingress (host,path) match -> numeric port resolved to the
        istio port name via RDS -> route rule rewrite -> fs-bound
        backend; non-matching host is unidentified (400)."""
        from test_k8s_ingress import FakeIngressApi

        async def go():
            from linkerd_tpu.linker import load_linker
            from linkerd_tpu.protocol.http.client import HttpClient
            from linkerd_tpu.protocol.http.server import serve

            pilot = self._pilot()
            pilot_srv = await HttpServer(pilot.service()).start()
            fake = FakeIngressApi(items=self._ingress_items())
            k8s_srv = await HttpServer(fake.service()).start()

            async def backend_handler(req: Request) -> Response:
                return Response(status=200,
                                body=f"echo:{req.uri}".encode())
            backend = await serve(FnService(backend_handler))

            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "reviews-v1").write_text(
                f"127.0.0.1 {backend.bound_port}\n")

            cfg = f"""
routers:
- protocol: http
  label: istio-ing
  identifier:
    kind: io.l5d.k8s.istio-ingress
    host: 127.0.0.1
    port: {k8s_srv.bound_port}
    apiserverHost: 127.0.0.1
    apiserverPort: {pilot_srv.bound_port}
    discoveryPort: {pilot_srv.bound_port}
    pollIntervalMs: 100
  dtab: |
    /svc/route/to-v1/http => /#/io.l5d.fs/reviews-v1 ;
  servers:
  - port: 0
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            try:
                # the to-v1 rule matches uri prefix /api/ and rewrites it
                # to /v1/ before routing to /svc/route/to-v1/http
                req = Request(uri="/api/users")
                req.headers.set("Host", "shop.example.com")
                rsp = await proxy(req)
                assert (rsp.status, rsp.body) == (200, b"echo:/v1/users")

                # no ingress rule for this host -> unidentified -> 400
                bad = Request(uri="/api/users")
                bad.headers.set("Host", "other.example.com")
                rsp2 = await proxy(bad)
                assert rsp2.status == 400
            finally:
                await proxy.close()
                await linker.close()
                await backend.close()
                await k8s_srv.close()
                await pilot_srv.close()

        run(go())

    def test_h2_twin_redirect_and_dest_fallthrough(self, tmp_path):
        """The h2 kind registers + identifies: route rewrite, redirect
        rules answering 302 directly, and the empty-label dest
        fall-through when no rule matches."""
        from test_k8s_ingress import FakeIngressApi, ingress_obj
        from linkerd_tpu.config import lookup
        from linkerd_tpu.core import Dtab, Path
        from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
        from linkerd_tpu.router.binding import DstPath

        async def go():
            pilot = self._pilot()
            pilot_srv = await HttpServer(pilot.service()).start()
            # catch-all ingress path so every uri reaches the route rules
            fake = FakeIngressApi(items=[ingress_obj(
                name="shop", ns="default", host="shop.example.com",
                path="/.*", svc="reviews", port="9080",
                annotations={"kubernetes.io/ingress.class": "istio"})])
            k8s_srv = await HttpServer(fake.service()).start()
            try:
                cls = lookup("h2identifier", "io.l5d.k8s.istio-ingress")
                cfg = cls(host="127.0.0.1", port=k8s_srv.bound_port,
                          apiserverHost="127.0.0.1",
                          apiserverPort=pilot_srv.bound_port,
                          discoveryPort=pilot_srv.bound_port,
                          pollIntervalMs=100)
                identify = cfg.mk(Path.read("/svc"), Dtab.empty())

                # to-v1 rule: uri prefix /api/ -> rewrite + route path
                req = H2Request(method="GET", path="/api/x",
                                authority="shop.example.com")
                got = await identify(req)
                assert isinstance(got, DstPath)
                assert got.path.show == "/svc/route/to-v1/http"
                assert req.path == "/v1/x"  # rewrite applied in place

                # redirect-old rule (exact /old, precedence 5) -> 302
                rsp = await identify(H2Request(
                    method="GET", path="/old",
                    authority="shop.example.com"))
                assert isinstance(rsp, H2Response)
                assert rsp.status == 302
                assert rsp.headers.get("location") == "http://reviews/new"

                # uri matching no rule -> empty-label dest fall-through
                got2 = await identify(H2Request(
                    method="GET", path="/plain",
                    authority="shop.example.com"))
                assert isinstance(got2, DstPath)
                assert got2.path.show == (
                    "/svc/dest/reviews.default.svc.cluster.local/::/http")
            finally:
                await k8s_srv.close()
                await pilot_srv.close()

        run(go())


class TestIstioLoggerPlugin:
    def test_logger_kind_reports_to_mixer(self, tmp_path):
        """`loggers: [{kind: io.l5d.k8s.istio}]` on an http router sends
        one mixer Report per proxied response (ref IstioLogger.scala —
        the logger-plugin wiring of mixer reporting)."""
        from linkerd_tpu.grpc import ServerDispatcher
        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.h2.server import H2Server
        from linkerd_tpu.protocol.http.client import HttpClient
        from linkerd_tpu.protocol.http.server import serve

        seen = []
        disp = ServerDispatcher()

        async def report(reqs):
            async def gen():
                async for r in reqs:
                    seen.append(r)
                    yield pb.ReportResponse(request_index=r.request_index)
            return gen()

        disp.register(pb.MIXER_SVC, "Report", report)

        async def go():
            mixer = await H2Server(disp).start()

            async def ok(req: Request) -> Response:
                return Response(status=200, body=b"hi")
            backend = await serve(FnService(ok))

            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text(f"127.0.0.1 {backend.bound_port}\n")
            cfg = f"""
routers:
- protocol: http
  label: mix
  loggers:
  - kind: io.l5d.k8s.istio
    mixerHost: 127.0.0.1
    mixerPort: {mixer.bound_port}
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/api")
                req.headers.set("Host", "web")
                rsp = await proxy(req)
                assert rsp.status == 200
                for _ in range(100):
                    if seen:
                        break
                    await asyncio.sleep(0.05)
                assert seen, "no mixer report arrived"
            finally:
                await proxy.close()
                await linker.close()
                await backend.close()
                await mixer.close()

        run(go())

    def test_logger_kind_on_h2_router(self, tmp_path):
        """The same logger kind rides h2 routers (ref: the h2
        IstioLoggerInitializer twin)."""
        from linkerd_tpu.grpc import ServerDispatcher
        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.h2.client import H2Client
        from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
        from linkerd_tpu.protocol.h2.server import H2Server
        from linkerd_tpu.router.service import FnService

        seen = []
        disp = ServerDispatcher()

        async def report(reqs):
            async def gen():
                async for r in reqs:
                    seen.append(r)
                    yield pb.ReportResponse(request_index=r.request_index)
            return gen()

        disp.register(pb.MIXER_SVC, "Report", report)

        async def go():
            mixer = await H2Server(disp).start()

            async def ok(req: H2Request) -> H2Response:
                return H2Response(status=200, body=b"hi")
            backend = await H2Server(FnService(ok)).start()

            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text(f"127.0.0.1 {backend.bound_port}\n")
            cfg = f"""
routers:
- protocol: h2
  label: mixh2
  loggers:
  - kind: io.l5d.k8s.istio
    mixerHost: 127.0.0.1
    mixerPort: {mixer.bound_port}
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = H2Client("127.0.0.1",
                             linker.routers[0].server_ports[0])
            try:
                rsp = await proxy(H2Request(method="GET", path="/api",
                                            authority="web"))
                assert rsp.status == 200
                await rsp.stream.read_all()
                for _ in range(100):
                    if seen:
                        break
                    await asyncio.sleep(0.05)
                assert seen, "no mixer report from the h2 router"
                # counters surface in the LINKER metrics tree
                flat = linker.metrics.flatten()
                assert flat.get("istio/reports", 0) >= 1
            finally:
                await proxy.close()
                await linker.close()
                await backend.close()
                await mixer.close()

        run(go())
