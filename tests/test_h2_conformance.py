"""h2 conformance/safety on the native fastpath engine (advisor findings).

Each test pins one of the RFC 7540 guards in native/h2_fastpath.cpp with
a raw-socket client that violates the protocol on purpose:

- receive-side flow control is enforced: a stream overrunning our
  advertised window is RST with FLOW_CONTROL_ERROR, a connection
  overrunning the conn-level window gets GOAWAY(FLOW_CONTROL_ERROR)
  (§6.9);
- SETTINGS_INITIAL_WINDOW_SIZE above 2^31-1 is a connection error of
  type FLOW_CONTROL_ERROR (§6.5.2);
- an ``:authority`` with characters outside the host grammar is
  rejected with a synthesized 400 before it can reach routing, parked
  maps, or the stats JSON (wire input is untrusted);
- a client stream id that goes backwards (or reuses a closed id) is
  RST with STREAM_CLOSED instead of poisoning the connection (§5.1.1).
"""

import socket
import struct
import threading

import pytest

from linkerd_tpu import native
from linkerd_tpu.protocol.h2.hpack import Decoder
from linkerd_tpu.protocol.h2.messages import H2Response
from linkerd_tpu.protocol.h2.server import H2Server
from linkerd_tpu.router.service import FnService

pytestmark = pytest.mark.skipif(
    not native.ensure_built(), reason="native toolchain unavailable")

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
HEADERS, RST_STREAM, SETTINGS, GOAWAY, WINDOW_UPDATE = 0x1, 0x3, 0x4, 0x7, 0x8
DATA = 0x0
END_STREAM, END_HEADERS = 0x1, 0x4
FLOW_CONTROL_ERROR, STREAM_CLOSED = 0x3, 0x5


def frame(ftype: int, flags: int, sid: int, payload: bytes = b"") -> bytes:
    return (len(payload).to_bytes(3, "big") + bytes([ftype, flags])
            + sid.to_bytes(4, "big") + payload)


def hpack_literal(headers) -> bytes:
    """Literal Header Field without Indexing — New Name (RFC 7541 §6.2.2),
    no Huffman: decodable by any conformant decoder, touches no dynamic
    table state."""
    out = b""
    for n, v in headers:
        nb, vb = n.encode(), v.encode()
        assert len(nb) < 127 and len(vb) < 127
        out += b"\x00" + bytes([len(nb)]) + nb + bytes([len(vb)]) + vb
    return out


def req_headers(authority: str, sid: int, end_stream: bool) -> bytes:
    block = hpack_literal([(":method", "POST"), (":scheme", "http"),
                           (":authority", authority), (":path", "/")])
    flags = END_HEADERS | (END_STREAM if end_stream else 0)
    return frame(HEADERS, flags, sid, block)


class FrameReader:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def next(self):
        """(type, flags, sid, payload) or None on EOF."""
        while len(self.buf) < 9:
            d = self.sock.recv(65536)
            if not d:
                return None
            self.buf += d
        n = int.from_bytes(self.buf[:3], "big")
        ftype, flags = self.buf[3], self.buf[4]
        sid = int.from_bytes(self.buf[5:9], "big") & 0x7FFFFFFF
        while len(self.buf) < 9 + n:
            d = self.sock.recv(65536)
            if not d:
                return None
            self.buf += d
        payload = self.buf[9:9 + n]
        self.buf = self.buf[9 + n:]
        return ftype, flags, sid, payload

    def wait_for(self, ftype: int, sid=None):
        """Skip frames until one matches; None if the peer closed first."""
        while True:
            fr = self.next()
            if fr is None:
                return None
            if fr[0] == ftype and (sid is None or fr[2] == sid):
                return fr


def h2_connect(port: int) -> "tuple[socket.socket, FrameReader]":
    s = socket.create_connection(("127.0.0.1", port))
    s.settimeout(30)
    s.sendall(PREFACE + frame(SETTINGS, 0, 0))
    return s, FrameReader(s)


@pytest.fixture
def sink_backend():
    """Accepts TCP but never speaks h2 back: the engine's upstream leg
    gets no SETTINGS and no window grants, so client-side buffering (and
    the grant gates) are fully deterministic."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(16)
    held = []

    def serve():
        while True:
            try:
                c, _ = lsock.accept()
            except OSError:
                return
            held.append(c)  # keep open, read nothing

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    yield lsock.getsockname()[1]
    lsock.close()
    for c in held:
        c.close()


@pytest.fixture
def engine():
    eng = native.H2FastPathEngine()
    yield eng
    eng.close()


class TestFlowControlEnforcement:
    def test_stream_overrun_rst_flow_control_error(self, engine,
                                                   sink_backend):
        """10MB of DATA on one stream: far past the 4MB advertised
        stream window plus every grant the engine can legally have made
        (grants stop once the per-stream pend cap is hit) -> RST with
        FLOW_CONTROL_ERROR on that stream, connection survives."""
        port = engine.listen("127.0.0.1", 0)
        engine.start()
        engine.set_route("sink", [("127.0.0.1", sink_backend)])
        s, rd = h2_connect(port)
        try:
            s.sendall(req_headers("sink", 1, end_stream=False))
            chunk = frame(DATA, 0, 1, b"\x00" * 16384)
            for _ in range(10 * 1024 * 1024 // 16384):
                s.sendall(chunk)
            fr = rd.wait_for(RST_STREAM, sid=1)
            assert fr is not None, "engine closed the conn instead of RST"
            assert struct.unpack("!I", fr[3])[0] == FLOW_CONTROL_ERROR
            # the connection is still alive: a PING comes back
            s.sendall(frame(0x6, 0, 0, b"12345678"))
            pong = rd.wait_for(0x6)
            assert pong is not None and pong[3] == b"12345678"
        finally:
            s.close()

    def test_conn_overrun_goaway_flow_control_error(self, engine,
                                                    sink_backend):
        """Eight streams each under their own stream window but 31MB in
        total: past the 16MB conn window plus the conn grants the
        engine's buffered-cap gate allows -> GOAWAY(FLOW_CONTROL_ERROR)
        and the connection closes."""
        port = engine.listen("127.0.0.1", 0)
        engine.start()
        engine.set_route("sink", [("127.0.0.1", sink_backend)])
        s, rd = h2_connect(port)
        goaway = []

        def read_all():
            while True:
                fr = rd.next()
                if fr is None:
                    return
                if fr[0] == GOAWAY:
                    goaway.append(fr)

        t = threading.Thread(target=read_all, daemon=True)
        t.start()
        try:
            sids = [1 + 2 * i for i in range(8)]
            for sid in sids:
                s.sendall(req_headers("sink", sid, end_stream=False))
            payload = b"\x00" * 16384
            try:
                # ~3.9MB per stream (< its 4MB window), 31MB total
                for _ in range(250):
                    for sid in sids:
                        s.sendall(frame(DATA, 0, sid, payload))
            except OSError:
                pass  # engine already closed on us — that's the point
            t.join(timeout=30)
            assert goaway, "no GOAWAY before close"
            last_sid, err = struct.unpack("!II", goaway[-1][3][:8])
            assert err == FLOW_CONTROL_ERROR
        finally:
            s.close()


class TestSettingsValidation:
    def test_initial_window_size_over_2_31_is_conn_error(self, engine):
        """SETTINGS_INITIAL_WINDOW_SIZE = 2^31 MUST be a connection
        error of type FLOW_CONTROL_ERROR (RFC 7540 §6.5.2)."""
        port = engine.listen("127.0.0.1", 0)
        engine.start()
        s = socket.create_connection(("127.0.0.1", port))
        s.settimeout(30)
        try:
            bad = struct.pack("!HI", 0x4, 1 << 31)  # INITIAL_WINDOW_SIZE
            s.sendall(PREFACE + frame(SETTINGS, 0, 0, bad))
            rd = FrameReader(s)
            fr = rd.wait_for(GOAWAY)
            assert fr is not None
            _, err = struct.unpack("!II", fr[3][:8])
            assert err == FLOW_CONTROL_ERROR
            assert rd.next() is None  # engine closed the connection
        finally:
            s.close()


class TestAuthorityValidation:
    def test_bad_authority_rejected_400(self, engine, sink_backend):
        """An :authority outside the host grammar is answered with a
        synthesized 400 — it must never reach routing (no route-miss is
        recorded for it)."""
        port = engine.listen("127.0.0.1", 0)
        engine.start()
        engine.set_route("sink", [("127.0.0.1", sink_backend)])
        s, rd = h2_connect(port)
        try:
            # CR/LF + quote smuggling attempt in the authority
            s.sendall(req_headers('evil"\r\nx: y', 1, end_stream=True))
            fr = rd.wait_for(HEADERS, sid=1)
            assert fr is not None
            hdrs = dict(Decoder().decode(fr[3]))
            assert hdrs[":status"] == "400"
            assert hdrs.get("l5d-err") == "bad authority"
            assert engine.drain_misses() == []
        finally:
            s.close()


class TestStreamIdReuse:
    def test_backwards_and_reused_stream_ids_rst(self, engine):
        """After stream 5 completes, HEADERS on 3 (backwards) and on 5
        (reuse of a closed id) are each RST with STREAM_CLOSED; stream 7
        still works, proving the connection was spared."""
        import asyncio

        async def go():
            async def echo(req):
                body, _ = await req.stream.read_all(max_bytes=1 << 20)
                return H2Response(status=200, body=body)

            backend = await H2Server(FnService(echo)).start()
            port = engine.listen("127.0.0.1", 0)
            engine.start()
            engine.set_route("echo", [("127.0.0.1", backend.bound_port)])

            def drive():
                s, rd = h2_connect(port)
                try:
                    s.sendall(req_headers("echo", 5, end_stream=True))
                    assert rd.wait_for(HEADERS, sid=5) is not None
                    for bad_sid in (3, 5):
                        s.sendall(req_headers("echo", bad_sid,
                                              end_stream=True))
                        fr = rd.wait_for(RST_STREAM, sid=bad_sid)
                        assert fr is not None, f"no RST for sid {bad_sid}"
                        code = struct.unpack("!I", fr[3])[0]
                        assert code == STREAM_CLOSED
                    s.sendall(req_headers("echo", 7, end_stream=True))
                    assert rd.wait_for(HEADERS, sid=7) is not None
                finally:
                    s.close()

            try:
                await asyncio.wait_for(asyncio.to_thread(drive), 30)
            finally:
                await backend.close()

        asyncio.run(go())
