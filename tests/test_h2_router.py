"""h2 router end-to-end: YAML linker, h2 downstreams, gRPC through proxy.

Mirrors the reference's router/h2 e2e suite
(router/h2/src/e2e/.../H2EndToEndTest, RetriesEndToEndTest) and the gRPC
classifier behavior (linkerd/protocol/h2 grpc/GrpcClassifier.scala).
"""

import asyncio
import os

import pytest

from linkerd_tpu.grpc import (
    ClientDispatcher, Field, GrpcError, ProtoMessage, Rpc, ServerDispatcher,
    ServiceDef,
)
from linkerd_tpu.linker import load_linker
from linkerd_tpu.protocol.h2.client import H2Client
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
from linkerd_tpu.protocol.h2.server import H2Server
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def h2_downstream(name: str):
    async def handler(req: H2Request) -> H2Response:
        body, _ = await req.stream.read_all()
        return H2Response(status=200, body=f"{name}:{body.decode()}".encode())
    return FnService(handler)


def mk_cfg(disco, extra_svc: str = "") -> str:
    return f"""
routers:
- protocol: h2
  label: h2out
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
{extra_svc}
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""


@pytest.fixture
def disco(tmp_path):
    d = tmp_path / "disco"
    d.mkdir()
    return d


class TestH2Router:
    def test_routes_by_authority(self, disco):
        async def go():
            d_a = await H2Server(h2_downstream("svc-a")).start()
            (disco / "web").write_text(f"127.0.0.1 {d_a.bound_port}\n")
            linker = load_linker(mk_cfg(disco))
            await linker.start()
            proxy = H2Client("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                req = H2Request(method="POST", path="/x", authority="web",
                                body=b"hello")
                rsp = await proxy(req)
                body, _ = await rsp.stream.read_all()
                assert (rsp.status, body) == (200, b"svc-a:hello")

                # unknown authority -> 400 + l5d-err
                bad = await proxy(H2Request(path="/", authority="nope"))
                assert bad.status == 400
                assert bad.headers.get("l5d-err") is not None

                flat = linker.metrics.flatten()
                assert flat["rt/h2out/server/requests"] == 2
                assert flat["rt/h2out/server/status/200"] == 1
                assert flat["rt/h2out/server/status/400"] == 1
                assert flat["rt/h2out/service/svc.web/requests"] == 1
            finally:
                await proxy.close()
                await linker.close()
                await d_a.close()
        run(go())

    def test_retries_5xx_when_read_classifier(self, disco):
        calls = {"n": 0}

        async def flaky(req: H2Request) -> H2Response:
            calls["n"] += 1
            if calls["n"] < 3:
                return H2Response(status=503, body=b"unavailable")
            return H2Response(status=200, body=b"finally")

        async def go():
            d = await H2Server(FnService(flaky)).start()
            (disco / "web").write_text(f"127.0.0.1 {d.bound_port}\n")
            svc_cfg = """  service:
    responseClassifier:
      kind: io.l5d.h2.retryableRead5XX
"""
            linker = load_linker(mk_cfg(disco, svc_cfg))
            await linker.start()
            proxy = H2Client("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                rsp = await proxy(H2Request(method="GET", path="/",
                                            authority="web"))
                body, _ = await rsp.stream.read_all()
                assert (rsp.status, body) == (200, b"finally")
                assert calls["n"] == 3
                flat = linker.metrics.flatten()
                assert flat["rt/h2out/service/svc.web/retries/total"] == 2
            finally:
                await proxy.close()
                await linker.close()
                await d.close()
        run(go())


class Ping(ProtoMessage):
    FIELDS = {"text": Field(1, "string"), "fail_times": Field(2, "int32")}


GRPC_SVC = ServiceDef("test.Pinger", [
    Rpc("Ping", Ping, Ping),
    Rpc("Watch", Ping, Ping, server_streaming=True),
])


class TestGrpcThroughProxy:
    def test_grpc_unary_and_stream_via_h2_router(self, disco):
        state = {"fails": 0}
        disp = ServerDispatcher()

        async def ping(req: Ping) -> Ping:
            if state["fails"] < req.fail_times:
                state["fails"] += 1
                raise GrpcError.of(14, "try again")  # UNAVAILABLE
            return Ping(text=f"pong {req.text}")

        async def watch(req: Ping):
            async def gen():
                for i in range(3):
                    yield Ping(text=f"ev{i}")
            return gen()

        disp.register_all(GRPC_SVC, {"Ping": ping, "Watch": watch})

        async def go():
            d = await H2Server(disp).start()
            (disco / "grpcsvc").write_text(f"127.0.0.1 {d.bound_port}\n")
            svc_cfg = """  service:
    responseClassifier:
      kind: io.l5d.h2.grpc.default
"""
            linker = load_linker(mk_cfg(disco, svc_cfg))
            await linker.start()
            proxy_client = ClientDispatcher(
                H2Client("127.0.0.1", linker.routers[0].server_ports[0]),
                authority="grpcsvc")
            try:
                # plain unary through the router
                rep = await proxy_client.unary(GRPC_SVC, "Ping",
                                               Ping(text="x"))
                assert rep.text == "pong x"

                # UNAVAILABLE failures are retried by the router
                # (grpc-status trailer classification + buffered replay)
                rep = await proxy_client.unary(
                    GRPC_SVC, "Ping", Ping(text="y", fail_times=2))
                assert rep.text == "pong y"

                # server-streaming passes through
                reps = await proxy_client.server_stream(
                    GRPC_SVC, "Watch", Ping())
                texts = [m.text async for m in reps]
                assert texts == ["ev0", "ev1", "ev2"]

                flat = linker.metrics.flatten()
                assert flat[
                    "rt/h2out/service/svc.grpcsvc/retries/total"] == 2
            finally:
                await proxy_client._svc.close()
                await linker.close()
                await d.close()
        run(go())


class TestLargeStreamThroughProxy:
    def test_8mb_body_exceeds_conn_window_through_router(self, disco):
        """A body larger than BOTH flow-control windows (1MB stream / 4MB
        conn) must flow through the full router path — proves the
        deferred WINDOW_UPDATE credits recycle across both hops (ref:
        router/h2 LargeStreamEndToEndTest + FlowControlEndToEndTest)."""
        big = bytes(1024) * (8 * 1024)  # 8MB

        async def echo_len(req: H2Request) -> H2Response:
            body, _ = await req.stream.read_all(max_bytes=1 << 27)
            return H2Response(status=200, body=body[::-1][:64]
                              + str(len(body)).encode())

        async def go():
            backend = await H2Server(FnService(echo_len)).start()
            (disco / "big").write_text(f"127.0.0.1 {backend.bound_port}\n")
            linker = load_linker(mk_cfg(disco))
            await linker.start()
            client = H2Client("127.0.0.1",
                              linker.routers[0].server_ports[0])
            try:
                rsp = await client(H2Request(
                    method="POST", path="/up", authority="big", body=big))
                body, _ = await rsp.stream.read_all(max_bytes=1 << 27)
                assert body.endswith(str(len(big)).encode())
            finally:
                await client.close()
                await linker.close()
                await backend.close()

        run(go())


class TestH2SettingsConfig:
    def test_settings_advertised_and_refusal(self, disco):
        """Router-level h2 SETTINGS reach the wire: a tiny
        maxConcurrentStreamsPerConnection causes REFUSED_STREAM resets
        when exceeded (ref: H2Config.scala settings params)."""
        async def slow(req: H2Request) -> H2Response:
            await asyncio.sleep(0.3)
            return H2Response(status=200, body=b"ok")

        async def go():
            backend = await H2Server(FnService(slow)).start()
            (disco / "slow").write_text(f"127.0.0.1 {backend.bound_port}\n")
            cfg = f"""
routers:
- protocol: h2
  label: h2cfg
  maxConcurrentStreamsPerConnection: 1
  initialStreamWindowBytes: 131072
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            from linkerd_tpu.linker import load_linker
            linker = load_linker(cfg)
            await linker.start()
            client = H2Client("127.0.0.1",
                              linker.routers[0].server_ports[0])

            async def one():
                rsp = await client(H2Request(
                    method="GET", path="/x", authority="slow"))
                body, _ = await rsp.stream.read_all()
                return rsp.status

            try:
                # two concurrent streams against a limit of 1: one served,
                # the other refused (StreamReset) — never a dead conn
                results = await asyncio.gather(one(), one(),
                                               return_exceptions=True)
                ok = [r for r in results if r == 200]
                refused = [r for r in results if isinstance(r, Exception)]
                assert len(ok) >= 1
                assert len(ok) + len(refused) == 2
                # after the burst, the connection still works
                assert await one() == 200
            finally:
                await client.close()
                await linker.close()
                await backend.close()

        run(go())
