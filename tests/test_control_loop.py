"""Reactive control loop: anomaly scores drive balancing, admission,
and namerd traffic shifting (linkerd_tpu/control/).

Chaos scenario matrix (ISSUE 8 acceptance):
- sick-replica drain-before-ejection: a replica with degrading scores
  receives measurably less traffic while still OPEN (no accrual kick);
- sick-cluster shift + recovery revert: a two-router fleet + namerd —
  the reactor publishes an l5dcheck-verified dtab override through the
  namerd HTTP API, every router re-binds away, and the override is
  reverted when scores recover;
- retry-storm under shifted traffic: a burst through the shifted route
  succeeds without flapping the override;
- mixed-protocol fleet: the http and h2 routers share one control loop
  and both shift;
- flap-resistance: oscillating scores produce ZERO override flaps
  (split thresholds + quorum + dwell);
- a bad override (cycle / unbound / collateral shadowing) is REJECTED
  by l5dcheck verification, never published.

Plus: score-weighted pick distribution property test, adaptive
admission, DeterministicScheduler interleavings for reactor
actuate-vs-revert, and the parity-tail satellites (ClassifierFilter
l5d-success-class trust across a two-linkerd chain; RewriteHostHeader
consuming bound authority metadata).
"""

import asyncio
import random
import time

import numpy as np
import pytest

from linkerd_tpu.control.admission import AdaptiveAdmission
from linkerd_tpu.control.balancer import ScoreWeightedBalancer, mk_weigher
from linkerd_tpu.control.reactor import LocalStoreClient, MeshReactor
from linkerd_tpu.control.state import HEALTHY, SICK, HysteresisGovernor
from linkerd_tpu.core import Dtab, Path, Var
from linkerd_tpu.core.addr import Address, Bound
from linkerd_tpu.linker import load_linker
from linkerd_tpu.namer.fs import FsNamer
from linkerd_tpu.namerd import InMemoryDtabStore, Namerd
from linkerd_tpu.namerd.http_api import HttpControlService
from linkerd_tpu.protocol.http import Request, Response
from linkerd_tpu.protocol.http.client import HttpClient
from linkerd_tpu.protocol.http.server import HttpServer, serve
from linkerd_tpu.router.admission import AdmissionControlFilter
from linkerd_tpu.router.balancer import P2CBalancer
from linkerd_tpu.router.service import FnService
from linkerd_tpu.telemetry.anomaly import ScoreBoard
from linkerd_tpu.telemetry.metrics import MetricsTree


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60))


async def eventually(pred, timeout: float = 10.0, what: str = "",
                     tick=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if tick is not None:
            await tick()
        if pred():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


class _LevelScorer:
    """Stub scorer: every row scores ``level`` (settable mid-test) —
    lets the chaos tests drive the FULL pipeline (recorder -> batcher ->
    scorer -> board -> reactor) without jax in the loop."""

    def __init__(self, level: float = 0.0):
        self.level = level
        self.batches = 0

    async def score(self, x):
        self.batches += 1
        return np.full(len(x), self.level, np.float32)

    async def fit(self, x, labels, mask):
        return 0.0

    def close(self):
        pass


class _FakeBoard:
    """Minimal board for reactor unit tests: one settable per-cluster
    level."""

    def __init__(self):
        self.levels = {}
        self.degraded = False

    def effective_scores(self):
        return dict(self.levels)

    def anomaly_level(self):
        return max(self.levels.values(), default=0.0)


# ---- hysteresis ------------------------------------------------------------


class TestHysteresisGovernor:
    def test_oscillation_produces_zero_transitions(self):
        g = HysteresisGovernor(enter=0.7, exit=0.3, quorum=3, dwell_s=0.0)
        t = 0.0
        for i in range(200):
            # hop across BOTH thresholds every observation: no streak
            # ever reaches quorum
            level = 0.9 if i % 2 == 0 else 0.1
            assert g.observe("k", level, now=t) == HEALTHY
            t += 0.01
        assert g.snapshot()["k"]["transitions"] == 0

    def test_sustained_trip_and_clear_once_each(self):
        g = HysteresisGovernor(enter=0.7, exit=0.3, quorum=2, dwell_s=1.0)
        t = 10.0
        assert g.observe("k", 0.9, now=t) == HEALTHY      # streak 1
        assert g.observe("k", 0.9, now=t + 2.0) == SICK   # quorum + dwell
        # mid-band levels change nothing in either state
        assert g.observe("k", 0.5, now=t + 2.1) == SICK
        # below exit but dwell not elapsed: stays SICK
        assert g.observe("k", 0.1, now=t + 2.2) == SICK
        assert g.observe("k", 0.1, now=t + 2.3) == SICK
        # dwell elapsed + quorum met: clears exactly once
        assert g.observe("k", 0.1, now=t + 3.3) == HEALTHY
        assert g.observe("k", 0.1, now=t + 3.4) == HEALTHY
        assert g.snapshot()["k"]["transitions"] == 2

    def test_spike_resets_streak(self):
        g = HysteresisGovernor(enter=0.7, exit=0.3, quorum=3, dwell_s=0.0)
        t = 0.0
        for level in (0.9, 0.9, 0.2, 0.9, 0.9):  # spike interrupted
            state = g.observe("k", level, now=t)
            t += 1.0
        assert state == HEALTHY

    def test_rejects_inverted_thresholds(self):
        with pytest.raises(ValueError):
            HysteresisGovernor(enter=0.3, exit=0.7)
        with pytest.raises(ValueError):
            HysteresisGovernor(quorum=0)


# ---- score-weighted balancing ----------------------------------------------


class TestScoreWeightedPick:
    def _bal(self, weigher, n=3):
        addrs = [Address.mk("127.0.0.1", 8000 + i) for i in range(n)]
        bal = P2CBalancer(Var(Bound(frozenset(addrs))),
                          lambda a: FnService(None),
                          rng=random.Random(7))
        return ScoreWeightedBalancer(bal, weigher), addrs

    def test_weigher_ramp(self):
        board = ScoreBoard(alpha=1.0, ttl_s=None)
        board.update_batch(["/svc/web"] * 3,
                           np.array([0.0, 0.5, 1.0], np.float32),
                           endpoints=["a:1", "b:1", "c:1"])
        w = mk_weigher(board, threshold=0.3, floor=0.05)
        assert w("a:1") == 1.0           # healthy
        assert 0.2 < w("b:1") < 0.9      # ramping
        assert w("c:1") == pytest.approx(0.05)  # floor, never zero
        assert w("unknown:1") == 1.0     # never-scored: neutral

    def test_degraded_board_weighs_neutral(self):
        board = ScoreBoard(alpha=1.0, ttl_s=None)
        board.update_batch(["/svc/web"], np.array([0.95], np.float32),
                           endpoints=["a:1"])
        w = mk_weigher(board)
        assert w("a:1") < 0.2
        board.degraded = True  # scorer path died: weights go neutral
        assert w("a:1") == 1.0

    def test_pick_distribution_shifts_but_keeps_trickle(self):
        """Property: with one sick replica of three, its pick share
        drops well below fair (1/3) but stays nonzero (the probe
        trickle), while the healthy pair splits the remainder evenly —
        at ZERO load, where every load formula ties."""
        sick = "127.0.0.1:8000"
        factors = {sick: 0.05}
        swb, addrs = self._bal(lambda hp: factors.get(hp, 1.0))
        counts = {a.hostport: 0 for a in addrs}
        swb._inner.refresh_weights(force=True)
        for _ in range(3000):
            counts[swb._inner._score_pick().address.hostport] += 1
        total = sum(counts.values())
        share = counts[sick] / total
        assert 0.0 < share < 0.12, f"sick share {share:.3f}"
        healthy = sorted(c for hp, c in counts.items() if hp != sick)
        assert healthy[0] / healthy[1] > 0.7  # pair stays balanced

    def test_weight_factor_scales_load_formula(self):
        swb, addrs = self._bal(lambda hp: 0.1
                               if hp == "127.0.0.1:8000" else 1.0)
        swb._inner.refresh_weights(force=True)
        ep = next(e for e in swb._inner._endpoints.values()
                  if e.address.port == 8000)
        assert ep.weight == pytest.approx(0.1)
        assert swb.weights()["127.0.0.1:8000"] == pytest.approx(0.1)

    def test_endpoint_scores_ride_staleness(self):
        board = ScoreBoard(alpha=1.0, ttl_s=0.1)
        board.update_batch(["/svc/web"], np.array([0.9], np.float32),
                           endpoints=["a:1"])
        assert board.endpoint_score_of("a:1") == pytest.approx(0.9)
        board._ep_updated["a:1"] -= 0.5  # fully stale: neutral
        assert board.endpoint_score_of("a:1") == 0.0

    def test_dead_endpoint_entries_pruned(self):
        """Replica churn (hostports change every deploy) must not grow
        the endpoint maps forever: fully-stale entries are pruned on
        the next update."""
        board = ScoreBoard(alpha=1.0, ttl_s=0.1)
        board.update_batch(["/svc/web"], np.array([0.9], np.float32),
                           endpoints=["dead:1"])
        board._ep_updated["dead:1"] -= 1.0  # > 2 * ttl old
        board.update_batch(["/svc/web"], np.array([0.5], np.float32),
                           endpoints=["live:1"])
        assert "dead:1" not in board._ep_scores
        assert "live:1" in board._ep_scores

    def test_retry_blames_first_picked_endpoint(self):
        """A retried request's degraded features must be attributed to
        the FIRST picked (failing) replica, not the healthy one that
        served the retry — first pick wins in req.ctx['endpoint']."""
        async def go():
            addrs = [Address.mk("127.0.0.1", 9001),
                     Address.mk("127.0.0.1", 9002)]

            class _Echo:
                def __init__(self, addr):
                    self.addr = addr

                async def __call__(self, req):
                    return Response(200)

            bal = P2CBalancer(Var(Bound(frozenset(addrs))),
                              lambda a: FnService(_Echo(a)),
                              rng=random.Random(3))
            req = Request(uri="/")
            await bal(req)
            first = req.ctx["endpoint"]
            # a retry re-dispatches the same request object: the blame
            # stamp must not be overwritten by the second pick
            for _ in range(10):
                await bal(req)
            assert req.ctx["endpoint"] == first
            await bal.close()

        run(go())


# ---- adaptive admission ----------------------------------------------------


class TestAdaptiveAdmission:
    def test_set_limit_narrows_and_rewidens(self):
        async def go():
            gate = asyncio.Event()

            async def waiting(req):
                await gate.wait()
                return Response(200)

            f = AdmissionControlFilter(4, max_pending=8)
            svc = f.and_then(FnService(waiting))
            f.set_limit(1)
            assert f.effective_concurrency == 1
            t1 = asyncio.ensure_future(svc(Request()))
            await asyncio.sleep(0.02)
            t2 = asyncio.ensure_future(svc(Request()))  # queues at limit 1
            await asyncio.sleep(0.02)
            assert f._inflight == 1 and f._pending == 1
            f.set_limit(4)  # widening admits the queued waiter now
            await asyncio.sleep(0.02)
            assert f._inflight == 2 and f._pending == 0
            gate.set()
            for t in (t1, t2):
                assert (await t).status == 200
            # clamped to [1, max_concurrency]
            f.set_limit(0)
            assert f.effective_concurrency == 1
            f.set_limit(99)
            assert f.effective_concurrency == 4

        run(go())

    def test_factor_tracks_signal_with_floor(self):
        board = _FakeBoard()
        adm = AdaptiveAdmission(board, threshold=0.5, floor=0.25,
                                alpha=1.0)
        f = AdmissionControlFilter(100, max_pending=0)
        adm.register(f)
        board.levels["/svc/web"] = 0.4   # below threshold: full open
        adm.step()
        assert f.effective_concurrency == 100
        board.levels["/svc/web"] = 1.0   # fully sick: floor, not zero
        adm.step()
        assert f.effective_concurrency == 25
        board.levels["/svc/web"] = 0.0   # recovery re-widens
        adm.step()
        assert f.effective_concurrency == 100

    def test_drift_shift_feeds_signal(self):
        class _Drift:
            def score_shift(self):
                return 6.0  # sigmas >> DRIFT_FULL_SIGMAS

        adm = AdaptiveAdmission(_FakeBoard(), drift=_Drift())
        assert adm.signal() == 1.0


# ---- override verification (l5dcheck override-unsafe) ----------------------


class TestOverrideUnsafe:
    PREFIXES = [Path.read("/io.l5d.fs")]
    BASE = Dtab.read("/svc => /#/io.l5d.fs ;")

    def _check(self, override, base=None, prefixes=PREFIXES):
        from tools.analysis.semantic.dtab_check import check_override
        return check_override(base if base is not None else self.BASE,
                              Dtab.read(override), prefixes)

    def test_good_override_is_clean(self):
        assert self._check("/svc/web => /svc/web-b ;") == []

    def test_self_shift_cycle_flagged(self):
        out = self._check("/svc/web => /svc/web ;")
        assert any("cycle" in f.message for f in out)

    def test_unbound_target_flagged(self):
        out = self._check("/svc/web => /#/io.l5d.nope/x ;")
        assert any("unroutable" in f.message for f in out)

    def test_wildcard_and_collateral_shadowing_flagged(self):
        assert any("wildcard" in f.message
                   for f in self._check("/svc/* => /svc/web-b ;"))
        base = Dtab.read(
            "/svc => /#/io.l5d.fs ; /svc/special => /#/io.l5d.fs/sp ;")
        out = self._check("/svc => /svc/web-b ;", base=base)
        assert any("shadows" in f.message for f in out)

    def test_unknown_namers_keep_cycle_check_only(self):
        # remote-namerd linker: /#/ targets assumed bindable...
        assert self._check("/svc/web => /#/anything/x ;",
                           prefixes=None) == []
        # ...but cycles still cannot hide
        out = self._check("/svc/web => /svc/web ;", prefixes=None)
        assert any("cycle" in f.message for f in out)


# ---- mesh reactor (unit) ---------------------------------------------------


def _reactor(store, board, failover=None, quorum=1, dwell=0.0,
             metrics=None, verify=True, prefixes=None):
    node = (metrics or MetricsTree()).scope("control", "reactor")
    return MeshReactor(
        board, LocalStoreClient(store), "default",
        failover or {"/svc/web": "/svc/web-b"},
        governor=HysteresisGovernor(enter=0.6, exit=0.2, quorum=quorum,
                                    dwell_s=dwell),
        metrics_node=node,
        namer_prefixes=(prefixes if prefixes is not None
                        else [Path.read("/io.l5d.fs")]),
        verify=verify)


BASE_DTAB = "/svc => /#/io.l5d.fs ;"


class TestMeshReactor:
    def test_trip_publish_revert(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _FakeBoard()
            metrics = MetricsTree()
            r = _reactor(store, board, metrics=metrics)
            board.levels["/svc/web"] = 0.9
            await r.step(now=1.0)
            vd = await store.observe("default").to_future()
            assert "/svc/web => /svc/web-b" in vd.dtab.show
            assert "/svc/web" in r.active
            # sick again: idempotent, no second publish
            await r.step(now=2.0)
            flat = metrics.flatten()
            assert flat["control/reactor/overrides_published"] == 1
            # recovery: the exact dentry is removed, base preserved
            board.levels["/svc/web"] = 0.0
            await r.step(now=3.0)
            vd = await store.observe("default").to_future()
            assert vd.dtab.show.strip() == Dtab.read(BASE_DTAB).show.strip()
            assert r.active == {}
            flat = metrics.flatten()
            assert flat["control/reactor/overrides_reverted"] == 1

        run(go())

    def test_subcluster_scores_aggregate_to_cluster(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _FakeBoard()
            r = _reactor(store, board)
            board.levels["/svc/web/v2"] = 0.95  # child path of the cluster
            assert r.cluster_levels()["/svc/web"] == 0.95
            board.levels = {"/svc/webstore": 0.95}  # NOT under /svc/web
            assert r.cluster_levels()["/svc/web"] == 0.0

        run(go())

    def test_bad_override_rejected_not_published(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _FakeBoard()
            metrics = MetricsTree()
            # failover target reaches no configured namer: l5dcheck
            # must reject the generated override pre-publish
            r = _reactor(store, board,
                         failover={"/svc/web": "/#/io.l5d.nope/x"},
                         metrics=metrics)
            board.levels["/svc/web"] = 0.9
            before = (await store.observe("default").to_future()).dtab.show
            await r.step(now=1.0)
            after = (await store.observe("default").to_future()).dtab.show
            assert after == before, "rejected override was published!"
            assert r.active == {}
            assert "unroutable" in r.rejected["/svc/web"]
            flat = metrics.flatten()
            assert flat["control/reactor/overrides_rejected"] >= 1
            assert "overrides_published" not in {
                k: v for k, v in flat.items() if v} or \
                flat["control/reactor/overrides_published"] == 0

        run(go())

    def test_oscillating_scores_zero_flaps(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _FakeBoard()
            metrics = MetricsTree()
            r = _reactor(store, board, quorum=3, dwell=0.5,
                         metrics=metrics)
            t = 0.0
            for i in range(100):
                board.levels["/svc/web"] = 0.9 if i % 2 == 0 else 0.1
                await r.step(now=t)
                t += 0.05
            flat = metrics.flatten()
            assert flat["control/reactor/overrides_published"] == 0
            assert flat["control/reactor/overrides_reverted"] == 0

        run(go())

    def test_concurrent_operator_write_wins_cas(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _FakeBoard()

            class _RacingClient(LocalStoreClient):
                """An operator write lands between fetch and cas."""

                def __init__(self, store):
                    super().__init__(store)
                    self.race_once = True

                async def fetch(self, ns):
                    vd = await super().fetch(ns)
                    if self.race_once:
                        self.race_once = False
                        await store.put(ns, Dtab.read(
                            BASE_DTAB + " /ops => /#/io.l5d.fs/ops ;"))
                    return vd

            metrics = MetricsTree()
            r = MeshReactor(
                board, _RacingClient(store), "default",
                {"/svc/web": "/svc/web-b"},
                governor=HysteresisGovernor(enter=0.6, exit=0.2,
                                            quorum=1, dwell_s=0.0),
                metrics_node=metrics.scope("control", "reactor"),
                namer_prefixes=[Path.read("/io.l5d.fs")])
            board.levels["/svc/web"] = 0.9
            await r.step(now=1.0)   # CAS loses to the operator write
            assert r.active == {}
            assert metrics.flatten()["control/reactor/cas_conflicts"] == 1
            await r.step(now=2.0)   # retried against the new version
            vd = await store.observe("default").to_future()
            assert "/svc/web => /svc/web-b" in vd.dtab.show
            assert "/ops" in vd.dtab.show  # operator's dentry preserved

        run(go())

    def test_peer_published_override_is_adopted_not_duplicated(self):
        """N fleet linkerds share one failover config: the second
        reactor to trip must ADOPT the peer's identical dentry instead
        of stacking a duplicate — and its revert stays idempotent."""
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board_a, board_b = _FakeBoard(), _FakeBoard()
            metrics_b = MetricsTree()
            r_a = _reactor(store, board_a)
            r_b = _reactor(store, board_b, metrics=metrics_b)
            board_a.levels["/svc/web"] = 0.9
            board_b.levels["/svc/web"] = 0.9
            await r_a.step(now=1.0)
            await r_b.step(now=1.0)
            vd = await store.observe("default").to_future()
            assert vd.dtab.show.count("/svc/web => /svc/web-b") == 1
            assert metrics_b.flatten()[
                "control/reactor/overrides_adopted"] == 1
            # either reactor reverting removes the single dentry
            board_b.levels["/svc/web"] = 0.0
            await r_b.step(now=2.0)
            vd = await store.observe("default").to_future()
            assert "web-b" not in vd.dtab.show

        run(go())

    def test_hung_store_costs_one_bounded_step(self):
        """A blackholed namerd must cost one timed-out step (counted as
        an error), never wedge the control loop behind the reactor's
        lock — the adaptive-admission ticks share that driver."""
        async def go():
            board = _FakeBoard()

            class _HungClient:
                async def fetch(self, ns):
                    await asyncio.Event().wait()  # forever; cancellable

                async def cas(self, ns, dtab, version):
                    pass

                async def aclose(self):
                    pass

            metrics = MetricsTree()
            r = MeshReactor(
                board, _HungClient(), "default",
                {"/svc/web": "/svc/web-b"},
                governor=HysteresisGovernor(enter=0.6, exit=0.2,
                                            quorum=1, dwell_s=0.0),
                metrics_node=metrics.scope("control", "reactor"),
                store_timeout_s=0.05)
            board.levels["/svc/web"] = 0.9
            t0 = time.monotonic()
            await r.step(now=1.0)  # must return, not hang
            assert time.monotonic() - t0 < 2.0
            # a hung store is classified as CONNECTIVITY loss, not a
            # generic error: the reactor enters partition mode (where a
            # LocalOverrideBook, when configured, keeps actuating)
            flat = metrics.flatten()
            assert flat["control/reactor/errors"] == 0
            assert flat["control/reactor/partitioned"] == 1.0
            assert r.active == {}

        run(go())

    def test_degraded_board_reads_zero_levels(self):
        board = _FakeBoard()
        board.levels["/svc/web"] = 0.95
        board.degraded = True
        store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
        r = _reactor(store, board)
        assert r.cluster_levels() == {"/svc/web": 0.0}


# ---- reactor interleavings (DeterministicScheduler) ------------------------


class TestReactorInterleaving:
    def test_actuate_vs_revert_schedules_stay_consistent(self):
        """Concurrent reactor steps (the run() tick racing an admin- or
        test-driven step) through every seeded interleaving of the store
        client's fetch/cas awaits: the published dtab and the reactor's
        `active` book-keeping must never disagree, and the base dtab
        must never be corrupted."""
        from linkerd_tpu.testing.schedules import explore

        def mk(sched):
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _FakeBoard()

            class _Gated(LocalStoreClient):
                async def fetch(self, ns):
                    await sched.point("fetch")
                    return await super().fetch(ns)

                async def cas(self, ns, dtab, version):
                    await sched.point("cas")
                    await super().cas(ns, dtab, version)

            r = _reactor(store, board)
            r._client = _Gated(store)

            async def sick_step():
                board.levels["/svc/web"] = 0.9
                await r.step(now=1.0)

            async def recover_step():
                await sched.point("flip-healthy")
                board.levels["/svc/web"] = 0.0
                await r.step(now=2.0)

            async def check():
                # runs last (scheduler drains): consistency invariant
                await sched.point("check")
                vd = await store.observe("default").to_future()
                dentry_present = "/svc/web => /svc/web-b" in vd.dtab.show
                assert dentry_present == ("/svc/web" in r.active), (
                    f"store/active diverged: present={dentry_present} "
                    f"active={list(r.active)}")
                assert "/svc => /#/io.l5d.fs" in vd.dtab.show
                return True

            return [sick_step(), recover_step(), check()]

        def invariant(results):
            for res in results:
                if isinstance(res, BaseException):
                    raise AssertionError(repr(res))

        failure = explore(mk, invariant, seeds=range(24), timeout=10.0)
        assert failure is None, f"schedule violated invariant: {failure}"


# ---- satellites: ClassifierFilter + RewriteHostHeader ----------------------


class TestClassifierFilterChain:
    def test_two_linkerd_chain_trusts_inner_verdict(self, tmp_path):
        """The inner router (allSuccessful) stamps l5d-success-class:
        1.0 on a backend 503; the edge (io.l5d.http.successClass over a
        retrying fallback) TRUSTS it: no retry, classified success —
        exactly how the reference's ClassifierFilter chains behave."""
        calls = []

        async def flaky(req):
            calls.append(1)
            return Response(503, body=b"nope")

        async def go():
            backend = await serve(FnService(flaky))
            disco_b = tmp_path / "disco-b"
            disco_b.mkdir()
            (disco_b / "web").write_text(
                f"127.0.0.1 {backend.bound_port}\n")
            inner = load_linker(f"""
routers:
- protocol: http
  label: inner
  dtab: |
    /svc => /#/io.l5d.fs ;
  service:
    responseClassifier: {{kind: io.l5d.http.allSuccessful}}
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco_b}
""")
            await inner.start()
            disco_a = tmp_path / "disco-a"
            disco_a.mkdir()
            (disco_a / "web").write_text(
                f"127.0.0.1 {inner.routers[0].server_ports[0]}\n")
            edge = load_linker(f"""
routers:
- protocol: http
  label: edge
  dtab: |
    /svc => /#/io.l5d.fs ;
  service:
    responseClassifier:
      kind: io.l5d.http.successClass
      fallback: io.l5d.http.retryableRead5XX
    retries: {{backoff: {{kind: constant, ms: 5}}}}
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco_a}
""")
            await edge.start()
            proxy = HttpClient("127.0.0.1",
                               edge.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "web")
                rsp = await proxy(req)
                assert rsp.status == 503
                # the inner router's verdict rode the wire...
                assert rsp.headers.get("l5d-success-class") == "1.0"
                # ...and the edge trusted it: no retry fired even though
                # the fallback alone would have retried a GET 503
                assert len(calls) == 1
                flat = edge.metrics.flatten()
                assert flat.get(
                    "rt/edge/service/svc.web/retries/total", 0) == 0
            finally:
                await proxy.close()
                await edge.close()
                await inner.close()
                await backend.close()

        run(go())

    def test_edge_retries_when_inner_says_failure(self, tmp_path):
        """Inverse chain: the inner router classifies the 503 as a
        failure (nonRetryable5XX -> stamp 0.0); the edge honors the
        failure verdict and its fallback's retryability (GET + read5XX
        -> retry)."""
        calls = []
        gate = {"fail": True}

        async def recovering(req):
            calls.append(1)
            if gate["fail"]:
                gate["fail"] = False
                return Response(503, body=b"nope")
            return Response(200, body=b"ok")

        async def go():
            backend = await serve(FnService(recovering))
            disco_b = tmp_path / "db"
            disco_b.mkdir()
            (disco_b / "web").write_text(
                f"127.0.0.1 {backend.bound_port}\n")
            inner = load_linker(f"""
routers:
- protocol: http
  label: inner
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco_b}
""")
            await inner.start()
            disco_a = tmp_path / "da"
            disco_a.mkdir()
            (disco_a / "web").write_text(
                f"127.0.0.1 {inner.routers[0].server_ports[0]}\n")
            edge = load_linker(f"""
routers:
- protocol: http
  label: edge
  dtab: |
    /svc => /#/io.l5d.fs ;
  service:
    responseClassifier:
      kind: io.l5d.http.successClass
      fallback: io.l5d.http.retryableRead5XX
    retries: {{backoff: {{kind: constant, ms: 5}}}}
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco_a}
""")
            await edge.start()
            proxy = HttpClient("127.0.0.1",
                               edge.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "web")
                rsp = await proxy(req)
                assert rsp.status == 200
                assert len(calls) == 2  # retried once, then succeeded
            finally:
                await proxy.close()
                await edge.close()
                await inner.close()
                await backend.close()

        run(go())

    def test_h2_success_class_classifier(self):
        from linkerd_tpu.config import lookup
        from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
        from linkerd_tpu.router.classifiers import ResponseClass

        cls = lookup("h2classifier", "io.l5d.h2.successClass")(
            fallback="io.l5d.h2.retryableRead5XX").mk()
        req = H2Request(method="GET", path="/x")
        # downstream says success: a 503 classifies SUCCESS
        rsp = H2Response(status=503)
        rsp.headers.set("l5d-success-class", "1.0")
        assert cls.early(req, rsp) is ResponseClass.SUCCESS
        assert cls.classify(req, rsp, None, None) \
            is ResponseClass.SUCCESS
        # downstream says failure: a 200 classifies FAILURE
        rsp = H2Response(status=200)
        rsp.headers.set("l5d-success-class", "0.0")
        assert cls.early(req, rsp) is None  # retryability needs final
        assert cls.classify(req, rsp, None, None) \
            is ResponseClass.FAILURE
        # no header: fallback behavior (retryable read 5xx)
        rsp = H2Response(status=503)
        assert cls.classify(req, rsp, None, None) \
            is ResponseClass.RETRYABLE_FAILURE

    def test_h2_classifier_filter_stamps_ctx_verdict(self):
        from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
        from linkerd_tpu.router.classifiers import ResponseClass
        from linkerd_tpu.router.h2_layer import H2ClassifierFilter

        async def go():
            async def svc(req):
                req.ctx["response_class"] = ResponseClass.FAILURE
                return H2Response(status=200)

            rsp = await H2ClassifierFilter().apply(
                H2Request(method="GET", path="/x"), FnService(svc))
            assert rsp.headers.get("l5d-success-class") == "0.0"

        run(go())


class TestRewriteHostHeader:
    def _addr_var(self, authority=None):
        meta = (("authority", authority),) if authority else ()
        return Var(Bound(frozenset(
            {Address("127.0.0.1", 80, 1.0, meta)})))

    def test_rewrites_host_and_reverses_location(self):
        from linkerd_tpu.protocol.http.filters import RewriteHostHeader

        seen = {}

        async def svc(req):
            seen["host"] = req.headers.get("host")
            rsp = Response(302)
            rsp.headers.set(
                "Location", "http://web.svc.dc1.consul/login?x=1")
            rsp.headers.set("Refresh",
                            "5; url=http://web.svc.dc1.consul/retry")
            return rsp

        async def go():
            f = RewriteHostHeader(
                self._addr_var("web.svc.dc1.consul"))
            req = Request(uri="/login")
            req.headers.set("Host", "web")
            rsp = await f.apply(req, FnService(svc))
            # consul setHost authority reached the backend...
            assert seen["host"] == "web.svc.dc1.consul"
            # ...and the redirect points back at the caller's vhost
            assert rsp.headers.get("location") == \
                "http://web/login?x=1"
            assert rsp.headers.get("refresh") == \
                "5; url=http://web/retry"

        run(go())

    def test_no_authority_meta_is_noop(self):
        from linkerd_tpu.protocol.http.filters import RewriteHostHeader

        seen = {}

        async def svc(req):
            seen["host"] = req.headers.get("host")
            return Response(200)

        async def go():
            f = RewriteHostHeader(self._addr_var(None))
            req = Request(uri="/")
            req.headers.set("Host", "web")
            await f.apply(req, FnService(svc))
            assert seen["host"] == "web"

        run(go())

    def test_foreign_location_untouched(self):
        from linkerd_tpu.protocol.http.filters import RewriteHostHeader

        async def svc(req):
            rsp = Response(302)
            rsp.headers.set("Location", "http://elsewhere.example/x")
            return rsp

        async def go():
            f = RewriteHostHeader(self._addr_var("web.svc.consul"))
            req = Request(uri="/")
            req.headers.set("Host", "web")
            rsp = await f.apply(req, FnService(svc))
            assert rsp.headers.get("location") == \
                "http://elsewhere.example/x"

        run(go())

    def test_consul_namer_meta_shape_is_consumed(self):
        """The filter reads exactly what consul's SvcAddr.mkMeta-style
        with_authority mapping produces (per-Address authority meta)."""
        from linkerd_tpu.protocol.http.filters import _authority_of

        a = Address.mk("10.0.0.1", 8080,
                       authority="web.service.dc1.consul")
        assert _authority_of(Bound(frozenset({a}))) == \
            "web.service.dc1.consul"


# ---- chaos e2e: two-router fleet + namerd ----------------------------------


class TestControlChaosE2E:
    def test_sick_cluster_shifts_and_reverts(self, tmp_path):
        """The acceptance scenario end-to-end, mixed-protocol: an http
        and an h2 router on one linker, both bound through a REAL namerd
        (HTTP control API + chunked watches). Scores rise -> the reactor
        CAS-publishes verified overrides -> both protocols' traffic
        shifts to the -b clusters; scores recover -> overrides revert ->
        traffic returns; an oscillation phase afterwards produces zero
        further actuations; a retry burst mid-shift all succeeds."""
        from linkerd_tpu.protocol.h2.client import H2Client
        from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
        from linkerd_tpu.protocol.h2.server import serve_h2

        counts = {"a": 0, "b": 0, "a2": 0, "b2": 0}

        def http_backend(name):
            async def handler(req):
                counts[name] += 1
                return Response(200, body=name.encode())
            return handler

        def h2_backend(name):
            async def handler(req):
                counts[name] += 1
                return H2Response(status=200, body=name.encode())
            return handler

        async def go():
            back_a = await serve(FnService(http_backend("a")))
            back_b = await serve(FnService(http_backend("b")))
            back_a2 = await serve_h2(FnService(h2_backend("a2")))
            back_b2 = await serve_h2(FnService(h2_backend("b2")))

            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text(f"127.0.0.1 {back_a.bound_port}\n")
            (disco / "web-b").write_text(
                f"127.0.0.1 {back_b.bound_port}\n")
            (disco / "web2").write_text(
                f"127.0.0.1 {back_a2.bound_port}\n")
            (disco / "web2-b").write_text(
                f"127.0.0.1 {back_b2.bound_port}\n")

            namerd = Namerd(
                InMemoryDtabStore(
                    {"default": Dtab.read("/svc => /#/io.l5d.fs ;")}),
                namers=[(Path.read("/io.l5d.fs"),
                         FsNamer(str(disco)))])
            ctl_srv = await HttpServer(HttpControlService(namerd)).start()
            ctl_port = ctl_srv.bound_port

            edge = load_linker(f"""
routers:
- protocol: http
  label: edge
  servers: [{{port: 0}}]
  interpreter:
    kind: io.l5d.namerd.http
    dst: /$/inet/127.0.0.1/{ctl_port}
    namespace: default
  service:
    responseClassifier: {{kind: io.l5d.http.retryableRead5XX}}
    retries: {{backoff: {{kind: constant, ms: 10}}}}
- protocol: h2
  label: edge-h2
  servers: [{{port: 0}}]
  interpreter:
    kind: io.l5d.namerd.http
    dst: /$/inet/127.0.0.1/{ctl_port}
    namespace: default
telemetry:
- kind: io.l5d.jaxAnomaly
  maxBatch: 64
  maxLingerMs: 1
  trainEveryBatches: 0
  scoreTtlSecs: 10
  control:
    intervalMs: 20
    warmupBatches: 1
    enterThreshold: 0.6
    exitThreshold: 0.2
    quorum: 2
    cooldownS: 0.1
    namespace: default
    namerdAddress: 127.0.0.1:{ctl_port}
    failover:
      /svc/web: /svc/web-b
      /svc/web2: /svc/web2-b
""")
            tele = edge.telemeters[0]
            scorer = _LevelScorer(0.0)
            tele._scorer = scorer
            await edge.start()
            drain = asyncio.ensure_future(tele.run())
            http_port = edge.routers[0].server_ports[0]
            h2_port = edge.routers[1].server_ports[0]
            proxy = HttpClient("127.0.0.1", http_port)
            h2c = H2Client("127.0.0.1", h2_port)
            flat = edge.metrics.flatten

            async def one_http():
                req = Request(uri="/")
                req.headers.set("Host", "web")
                rsp = await proxy(req)
                assert rsp.status == 200
                return rsp.body

            async def one_h2():
                rsp = await h2c(H2Request(method="GET", path="/",
                                          authority="web2"))
                body, _trailers = await rsp.stream.read_all()
                assert rsp.status == 200
                return body

            async def tick():
                await one_http()
                await one_h2()

            try:
                # healthy: traffic lands on the A clusters
                for _ in range(5):
                    await tick()
                assert counts["a"] >= 5 and counts["a2"] >= 5
                assert counts["b"] == 0 and counts["b2"] == 0

                # ---- fault: every scored row reads anomalous ----
                scorer.level = 0.9
                await eventually(
                    lambda: flat().get(
                        "control/reactor/overrides_published", 0) >= 2,
                    timeout=15.0, what="override publish", tick=tick)
                vd = await namerd.store.observe("default").to_future()
                assert "/svc/web => /svc/web-b" in vd.dtab.show
                assert "/svc/web2 => /svc/web2-b" in vd.dtab.show

                # both protocols shift to the -b clusters
                await eventually(
                    lambda: b"b" == counts.setdefault("_", b"")
                    or counts["b"] > 0, timeout=10.0,
                    what="http traffic shift", tick=one_http)
                await eventually(
                    lambda: counts["b2"] > 0, timeout=10.0,
                    what="h2 traffic shift", tick=one_h2)
                a_plateau, a2_plateau = counts["a"], counts["a2"]
                for _ in range(5):
                    await tick()
                assert counts["a"] == a_plateau, "http still leaks to A"
                assert counts["a2"] == a2_plateau, "h2 still leaks to A"

                # retry-storm under shifted traffic: a concurrent burst
                # through the override path all succeeds, and the
                # override does not flap
                bodies = await asyncio.gather(
                    *[one_http() for _ in range(20)])
                assert all(b == b"b" for b in bodies)
                assert flat()[
                    "control/reactor/overrides_published"] == 2

                # ---- recovery: scores fall, override reverts ----
                scorer.level = 0.0
                await eventually(
                    lambda: flat().get(
                        "control/reactor/overrides_reverted", 0) >= 2,
                    timeout=15.0, what="override revert", tick=tick)
                vd = await namerd.store.observe("default").to_future()
                assert "web-b" not in vd.dtab.show
                await eventually(
                    lambda: counts["a"] > a_plateau, timeout=10.0,
                    what="http traffic return", tick=one_http)

                # ---- oscillation: zero further flaps ----
                published = flat()["control/reactor/overrides_published"]
                reverted = flat()["control/reactor/overrides_reverted"]
                for i in range(20):
                    scorer.level = 0.9 if i % 2 == 0 else 0.0
                    await tick()
                    await asyncio.sleep(0.03)
                scorer.level = 0.0
                assert flat()[
                    "control/reactor/overrides_published"] == published
                assert flat()[
                    "control/reactor/overrides_reverted"] == reverted

                # the whole loop is observable
                status = tele.control.status()
                assert status["reactor"]["active_overrides"] == {}
                assert status["actuators"]["mesh_reactor"] is True
                assert flat()["control/steps"] > 0
            finally:
                drain.cancel()
                await asyncio.gather(drain, return_exceptions=True)
                await proxy.close()
                await h2c.close()
                await edge.close()
                await ctl_srv.close()
                await namerd.close()
                for b in (back_a, back_b, back_a2, back_b2):
                    await b.close()

        run(go())

    def test_sick_replica_drains_before_ejection(self, tmp_path):
        """One cluster, two replicas: per-endpoint scores degrade for
        replica A -> the score-weighted balancer shifts its share down
        to a trickle while the endpoint stays OPEN (failure accrual
        never fired — nothing failed)."""
        counts = {"a": 0, "b": 0}

        async def go():
            async def mk_handler(name):
                async def h(req):
                    counts[name] += 1
                    return Response(200, body=name.encode())
                return h

            back_a = await serve(FnService(await mk_handler("a")))
            back_b = await serve(FnService(await mk_handler("b")))
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text(
                f"127.0.0.1 {back_a.bound_port}\n"
                f"127.0.0.1 {back_b.bound_port}\n")
            linker = load_linker(f"""
routers:
- protocol: http
  label: drain
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
telemetry:
- kind: io.l5d.jaxAnomaly
  trainEveryBatches: 0
  scoreTtlSecs: 30
  control:
    intervalMs: 20
    warmupBatches: 0   # scores seeded out-of-band; no drain loop runs
    weightThreshold: 0.3
    weightFloor: 0.05
""")
            tele = linker.telemeters[0]
            await linker.start()
            proxy = HttpClient("127.0.0.1",
                               linker.routers[0].server_ports[0])

            async def one():
                req = Request(uri="/")
                req.headers.set("Host", "web")
                rsp = await proxy(req)
                assert rsp.status == 200

            try:
                # warmup: both replicas share traffic
                for _ in range(40):
                    await one()
                assert counts["a"] > 5 and counts["b"] > 5

                # replica A trends anomalous (per-endpoint scores)
                sick_ep = f"127.0.0.1:{back_a.bound_port}"
                for _ in range(10):
                    tele.board.update_batch(
                        ["/svc/web"], np.array([0.95], np.float32),
                        endpoints=[sick_ep])
                assert tele.board.endpoint_score_of(sick_ep) > 0.8

                counts["a"] = counts["b"] = 0
                for _ in range(300):
                    await one()
                total = counts["a"] + counts["b"]
                share_a = counts["a"] / total
                # measurably drained (fair share would be 0.5), NOT
                # ejected: a trickle remains possible and the endpoint
                # is still OPEN
                assert share_a < 0.25, f"sick share {share_a:.2f}"
                assert counts["b"] > 200
                flat = linker.metrics.flatten()
                # nothing failed, so accrual never removed anything
                assert flat.get("rt/drain/server/failures", 0) == 0
            finally:
                await proxy.close()
                await linker.close()
                for b in (back_a, back_b):
                    await b.close()

        run(go())


# ---- /control.json + config validation -------------------------------------


class TestControlConfigSurface:
    def test_control_json_admin_handler(self):
        from linkerd_tpu.config.parser import instantiate

        cfg = instantiate("telemeter", {
            "kind": "io.l5d.jaxAnomaly",
            "control": {"intervalMs": 50},
        }, "t")
        tele = cfg.mk(MetricsTree())
        paths = [p for p, _ in tele.admin_handlers()]
        assert "/control.json" in paths

        async def go():
            handler = dict(tele.admin_handlers())["/control.json"]
            rsp = await handler(Request(uri="/control.json"))
            assert rsp.status == 200
            import json
            data = json.loads(rsp.body)
            assert data["actuators"]["balancer_weighting"] is True

        run(go())

    def test_l5dcheck_flags_bad_control_blocks(self):
        from tools.analysis.semantic.engine import check_text

        findings = check_text("""
routers:
- protocol: http
  servers: [{port: 0}]
telemetry:
- kind: io.l5d.jaxAnomaly
  control:
    enterThreshold: 0.2
    exitThreshold: 0.7
    namespace: default
    failover:
      /svc/web: /svc/web
""")
        rules = {f.rule for f in findings if not f.suppressed}
        assert "scorer-config" in rules      # inverted thresholds
        assert "override-unsafe" in rules    # self-shift failover

    def test_clean_control_block_passes(self):
        from tools.analysis.semantic.engine import check_text

        findings = check_text("""
routers:
- protocol: http
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{port: 0}]
namers:
- kind: io.l5d.fs
  rootDir: /tmp
telemetry:
- kind: io.l5d.jaxAnomaly
  control:
    namespace: default
    namerdAddress: 127.0.0.1:4180
    failover:
      /svc/web: /svc/web-b
""")
        assert [f for f in findings if not f.suppressed
                and f.rule in ("scorer-config", "override-unsafe")] == []
