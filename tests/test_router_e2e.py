"""Router end-to-end: full linker from YAML, real downstream servers,
live re-routing via fs-namer file edits.

Modeled on the reference's HttpEndToEndTest
(/root/reference/linkerd/protocol/http/src/e2e/.../HttpEndToEndTest.scala:
in-process downstreams + YAML-configured linker + stats assertions).
"""

import asyncio
import os

import pytest

from linkerd_tpu.linker import load_linker
from linkerd_tpu.protocol.http import Request, Response
from linkerd_tpu.protocol.http.client import HttpClient
from linkerd_tpu.protocol.http.server import serve
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def downstream(name: str):
    async def handler(req: Request) -> Response:
        return Response(status=200, body=name.encode())

    return FnService(handler)


CONFIG = """
routers:
- protocol: http
  label: out
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
  client:
    loadBalancer: {kind: roundRobin}
"""


class TestRouterEndToEnd:
    def test_routes_by_host_and_rebinds_on_file_change(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            d_a = await serve(downstream("svc-a"))
            d_b = await serve(downstream("svc-b"))
            (disco / "web").write_text(f"127.0.0.1 {d_a.bound_port}\n")

            cfg = CONFIG + f"namers:\n- kind: io.l5d.fs\n  rootDir: {disco}\n"
            linker = load_linker(cfg)
            await linker.start()
            router = linker.routers[0]
            proxy = HttpClient("127.0.0.1", router.server_ports[0])
            try:
                # 1. routes to svc-a by Host header
                req = Request(uri="/")
                req.headers.set("Host", "web")
                r = await proxy(req)
                assert (r.status, r.body) == (200, b"svc-a")

                # 2. unknown host -> 400 unbound
                bad = Request(uri="/")
                bad.headers.set("Host", "nope")
                r = await proxy(bad)
                assert r.status == 400
                assert r.headers.get("l5d-err") is not None

                # 3. live rebind: point the file at svc-b
                (disco / "web").write_text(f"127.0.0.1 {d_b.bound_port}\n")
                fs_namer = linker.namers[0][1]
                fs_namer.refresh()  # deterministic poll
                req2 = Request(uri="/")
                req2.headers.set("Host", "web")
                r2 = await proxy(req2)
                assert r2.body == b"svc-b"

                # 4. stats recorded under the reference scope convention
                flat = linker.metrics.flatten()
                assert flat["rt/out/server/requests"] == 3
                assert flat["rt/out/server/status/200"] == 2
                assert flat["rt/out/server/status/400"] == 1
                assert flat["rt/out/service/svc.web/requests"] == 2
                client_keys = [k for k in flat if k.startswith("rt/out/client/")]
                assert any(k.endswith("/requests") for k in client_keys)
            finally:
                await proxy.close()
                await linker.close()
                await d_a.close()
                await d_b.close()

        run(go())

    def test_weighted_union_dtab_and_balancing(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            d_a = await serve(downstream("A"))
            d_b = await serve(downstream("B"))
            (disco / "a").write_text(f"127.0.0.1 {d_a.bound_port}\n")
            (disco / "b").write_text(f"127.0.0.1 {d_b.bound_port}\n")

            cfg = f"""
routers:
- protocol: http
  label: w
  dtab: |
    /svc/mix => 0.5 * /#/io.l5d.fs/a & 0.5 * /#/io.l5d.fs/b ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                seen = set()
                for _ in range(40):
                    req = Request(uri="/")
                    req.headers.set("Host", "mix")
                    r = await proxy(req)
                    assert r.status == 200
                    seen.add(r.body)
                assert seen == {b"A", b"B"}  # both union branches served
            finally:
                await proxy.close()
                await linker.close()
                await d_a.close()
                await d_b.close()

        run(go())

    def test_alt_failover_to_second_branch(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            d_b = await serve(downstream("backup"))
            # primary points at an empty file -> empty replica set
            (disco / "primary").write_text("")
            (disco / "backup").write_text(f"127.0.0.1 {d_b.bound_port}\n")

            cfg = f"""
routers:
- protocol: http
  label: alt
  dtab: |
    /svc/x => /#/io.l5d.fs/primary | /#/io.l5d.fs/backup ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "x")
                r = await proxy(req)
                assert (r.status, r.body) == (200, b"backup")
            finally:
                await proxy.close()
                await linker.close()
                await d_b.close()

        run(go())

    def test_per_request_dtab_override_header(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            d_a = await serve(downstream("prod"))
            d_b = await serve(downstream("staging"))
            (disco / "prod").write_text(f"127.0.0.1 {d_a.bound_port}\n")
            (disco / "staging").write_text(f"127.0.0.1 {d_b.bound_port}\n")

            cfg = f"""
routers:
- protocol: http
  label: ovr
  dtab: |
    /svc => /#/io.l5d.fs/prod ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "anything")
                r = await proxy(req)
                assert r.body == b"prod"

                # l5d-dtab header overrides (later entries win)
                req2 = Request(uri="/")
                req2.headers.set("Host", "anything")
                req2.headers.set("l5d-dtab", "/svc => /#/io.l5d.fs/staging")
                r2 = await proxy(req2)
                assert r2.body == b"staging"
            finally:
                await proxy.close()
                await linker.close()
                await d_a.close()
                await d_b.close()

        run(go())

    def test_inet_utility_namer(self, tmp_path):
        async def go():
            d = await serve(downstream("direct"))
            cfg = f"""
routers:
- protocol: http
  label: direct
  dtab: |
    /svc => /$/inet/127.0.0.1/{d.bound_port} ;
  servers: [{{port: 0}}]
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "whatever")
                r = await proxy(req)
                assert (r.status, r.body) == (200, b"direct")
            finally:
                await proxy.close()
                await linker.close()
                await d.close()

        run(go())

    def test_config_errors(self):
        from linkerd_tpu.config import ConfigError

        with pytest.raises(ConfigError, match="at least one router"):
            load_linker("admin: {port: 9990}")
        with pytest.raises(ConfigError, match="unknown field"):
            load_linker("routers:\n- protocol: http\n  bogus: 1\n")
        with pytest.raises(ConfigError, match="unknown namer kind"):
            load_linker(
                "routers:\n- protocol: http\nnamers:\n- kind: io.l5d.nope\n")
