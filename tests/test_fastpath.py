"""Native fastpath data plane: engine semantics + linker integration.

The hot loop runs in C++ (native/fastpath.cpp); these tests drive it
through real sockets and assert parity with the Python path's routing
behavior: route-by-Host, 400 on unbound (ref: RoutingFactory.UnknownDst),
live re-route on fs-namer change (ref: HttpEndToEndTest), pooling, and
feature/stat export for the anomaly telemeter.
"""

import asyncio

import pytest

from linkerd_tpu import native
from linkerd_tpu.linker import load_linker
from linkerd_tpu.protocol.http import Request, Response
from linkerd_tpu.protocol.http.server import serve
from linkerd_tpu.router.service import FnService

pytestmark = pytest.mark.skipif(
    not native.ensure_built(), reason="native toolchain unavailable")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60))


def downstream(name: str):
    async def handler(req: Request) -> Response:
        if req.uri == "/echo-body":
            return Response(status=200, body=req.body)
        return Response(status=200, body=name.encode())

    return FnService(handler)


async def http_get(port: int, host: str, uri: str = "/",
                   body: bytes = b"") -> tuple:
    r, w = await asyncio.open_connection("127.0.0.1", port)
    try:
        head = f"GET {uri} HTTP/1.1\r\nHost: {host}\r\n"
        if body:
            head += f"Content-Length: {len(body)}\r\n"
        w.write(head.encode() + b"\r\n" + body)
        await w.drain()
        status_line = await asyncio.wait_for(r.readline(), 10)
        status = int(status_line.split()[1])
        headers = {}
        while True:
            line = await r.readline()
            if line in (b"\r\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", 0))
        rsp_body = await r.readexactly(n) if n else b""
        return status, headers, rsp_body
    finally:
        w.close()


CONFIG = """
routers:
- protocol: http
  label: fp
  fastPath: true
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""


class TestFastPathEngine:
    def test_routes_chunked_and_pooled(self):
        async def go():
            eng = native.FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()

            async def chunky(req: Request) -> Response:
                async def gen():
                    yield b"hello "
                    yield b"world"
                return Response(status=200, body_stream=gen())

            d = await serve(FnService(chunky))
            eng.set_route("c", [("127.0.0.1", d.bound_port)])
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(b"GET / HTTP/1.1\r\nHost: c\r\n\r\n")
                head = await asyncio.wait_for(r.readuntil(b"\r\n\r\n"), 10)
                assert b"200" in head.split(b"\r\n")[0]
                assert b"chunked" in head.lower()
                # read chunked body to terminator
                data = b""
                while b"0\r\n\r\n" not in data:
                    data += await asyncio.wait_for(r.read(64), 10)
                assert b"hello " in data and b"world" in data
                w.close()
            finally:
                eng.close()
                await d.close()

        run(go())

    def test_request_body_forwarded(self):
        async def go():
            eng = native.FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            d = await serve(downstream("x"))
            eng.set_route("b", [("127.0.0.1", d.bound_port)])
            try:
                status, _, body = await http_get(
                    port, "b", uri="/echo-body", body=b"payload-123")
                assert (status, body) == (200, b"payload-123")
            finally:
                eng.close()
                await d.close()

        run(go())


class TestFastPathLinker:
    def test_linker_fastpath_end_to_end(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            d_a = await serve(downstream("svc-a"))
            d_b = await serve(downstream("svc-b"))
            (disco / "web").write_text(f"127.0.0.1 {d_a.bound_port}\n")

            linker = load_linker(CONFIG.format(disco=disco))
            await linker.start()
            router = linker.routers[0]
            port = router.server_ports[0]
            try:
                # 1. cold host: miss -> python resolves -> route installed
                status, headers, body = await http_get(port, "web")
                assert (status, body) == (200, b"svc-a")

                # 2. unknown host -> 400 with l5d-err (2s park timeout)
                status, headers, _ = await http_get(port, "nope")
                assert status == 400
                assert "l5d-err" in headers

                # 3. live rebind: fs file now points at svc-b
                (disco / "web").write_text(f"127.0.0.1 {d_b.bound_port}\n")
                linker.namers[0][1].refresh()
                for _ in range(100):
                    await asyncio.sleep(0.02)
                    status, _, body = await http_get(port, "web")
                    if body == b"svc-b":
                        break
                assert body == b"svc-b"

                # 4. stats + features flowed
                ctl = router.controller
                ctl._export_stats()
                snap = ctl.engine.stats()
                assert snap["routes"]["web"]["requests"] >= 2
            finally:
                await linker.close()
                await d_a.close()
                await d_b.close()

        run(go())
