"""Tests for the fused Pallas scoring kernel (interpret mode on CPU)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from linkerd_tpu.models.anomaly import (
    AnomalyModelConfig, init_params, anomaly_scores,
)
from linkerd_tpu.ops.scoring import fused_anomaly_scores


@pytest.fixture(scope="module")
def setup():
    cfg = AnomalyModelConfig(compute_dtype=jnp.float32)  # exact compare on CPU
    params = init_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (512, cfg.in_dim), jnp.float32)
    return cfg, params, x


class TestFusedScoring:
    def test_matches_xla_path(self, setup):
        cfg, params, x = setup
        ref = anomaly_scores(params, x, cfg)
        got = fused_anomaly_scores(params, x, cfg, block_rows=256, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grid_tiling_covers_all_rows(self, setup):
        cfg, params, x = setup
        # distinct rows per tile: make tile 1 anomalous
        x = x.at[256:].add(10.0)
        ref = anomaly_scores(params, x, cfg)
        got = fused_anomaly_scores(params, x, cfg, block_rows=256, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_ragged_batch_padded(self, setup):
        cfg, params, x = setup
        ref = anomaly_scores(params, x[:300], cfg)
        got = fused_anomaly_scores(params, x[:300], cfg, block_rows=256,
                                   interpret=True)
        assert got.shape == (300,)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
