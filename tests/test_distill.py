"""Specialist model bank + continuous in-plane learning tests
(linkerd_tpu/distill/, native/scorer.h bank/delta/int4, COMPONENTS.md
§2.18).

The contracts under test:

- blob-format compatibility: ``L5DWTS01`` blobs load unchanged through
  the new bank reader; ``L5DWTS02`` banks roundtrip with per-route head
  select; corruption/truncation/unsorted heads/bad fences are rejected
  publishes, never silently-wrong scores;
- int4: the third quant level's parity bound vs the f32 evaluator AND
  the jitted serving scorer is pinned (alongside the existing f32 1e-5
  and int8 3e-2 bounds), and its blobs are the smallest;
- delta patches: generation-fenced apply under the same double-buffered
  reader-recheck discipline — torn-weights stress extended to deltas on
  the multi-worker shared slab;
- the continuous-learning loop: injected per-route distribution shift
  -> RouteDriftMonitor trigger -> retrain from the route's replay rows
  -> PromotionGate shadow pass -> delta publish -> 2-worker engines
  score that route with the specialist head (stats + /model.json),
  while a poisoned candidate is rejected and a single-route rollback
  leaves the other heads serving.
"""

import asyncio
import struct
import threading
import time
import zlib
from types import SimpleNamespace

import numpy as np
import pytest

from linkerd_tpu.distill import DistillConfig
from linkerd_tpu.distill.monitor import RouteDriftMonitor, RouteReplayWindow
from linkerd_tpu.lifecycle.export import (
    BANK_MAGIC, blob_meta, export_bank_blob, export_delta_blob,
    export_weight_blob, route_hash, _model_section, _sealed,
)
from linkerd_tpu.telemetry.anomaly import (
    FeatureVector, InProcessScorer, JaxAnomalyConfig, JaxAnomalyTelemeter,
)
from linkerd_tpu.telemetry.linerate import NATIVE_COL_SCORED, NATIVE_ROW_WIDTH
from linkerd_tpu.telemetry.metrics import MetricsTree

native = pytest.importorskip("linkerd_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 180))


# -- numpy-only fake snapshots (export/parse paths need no JAX) --------------


def _fake_snap(seed: int = 0, scale: float = 0.2):
    """A snapshot-shaped object with tiny seeded dense layers in the
    geometry the parser requires (36 -> 8 -> 36 recon, 8 -> 1 cls)."""
    rng = np.random.default_rng(seed)
    dim, z = 36, 8

    def layer(rows, cols):
        return {"w": rng.standard_normal((rows, cols)).astype(np.float32)
                * scale,
                "b": rng.standard_normal(cols).astype(np.float32) * 0.1}

    return SimpleNamespace(
        params={"enc": [layer(dim, z)], "dec": [layer(z, dim)],
                "cls": [layer(z, 1)]},
        mu=np.zeros(dim, np.float32),
        var=np.ones(dim, np.float32),
        norm_initialized=True, step=seed,
        cfg=SimpleNamespace(recon_weight=0.5))


@pytest.fixture(scope="module")
def trained_snapshot():
    """One real trained snapshot shared by the parity tests."""
    async def go():
        scorer = InProcessScorer(seed=3, learning_rate=5e-3)
        rng = np.random.default_rng(3)
        try:
            for _ in range(6):
                x = rng.standard_normal(
                    (32, scorer.cfg.in_dim)).astype(np.float32) * 2.0 + 1.0
                labels = (rng.random(32) > 0.8).astype(np.float32)
                await scorer.fit(x, labels, np.ones(32, np.float32))
            ref_x = rng.standard_normal(
                (256, scorer.cfg.in_dim)).astype(np.float32)
            jitted = np.asarray(await scorer.score(ref_x))
            return scorer.snapshot(), ref_x, jitted
        finally:
            scorer.close()

    return run(go())


class TestRouteHashParity:
    def test_python_hash_matches_engines(self):
        """route_hash must be the engines' FNV-1a bit for bit — the
        head a delta upserts is the head the data plane selects."""
        for s in ("/svc/web", "/fp/a", "x", "/#/io.l5d.fs/big-svc"):
            assert route_hash(s) == native.tenant_hash_native(s.encode())
        # 0 is reserved for "no head pushed"
        assert route_hash("/svc/web") != 0

    def test_python_hash_matches_tenancy(self):
        from linkerd_tpu.router.tenancy import tenant_hash
        assert route_hash("/svc/web") == tenant_hash("/svc/web")


class TestBankBlobFormat:
    def test_bank_roundtrips_with_head_select(self):
        base = _fake_snap(1)
        h_a, h_b = _fake_snap(2, scale=0.5), _fake_snap(3, scale=0.05)
        ra, rb = route_hash("/svc/a"), route_hash("/svc/b")
        bank = export_bank_blob(base, 7, 3,
                                {ra: (11, h_a), rb: (12, h_b)})
        meta = blob_meta(bank)
        assert meta["format"] == "bank"
        assert meta["generation"] == 3 and meta["heads"] == 2
        info = native.score_blob_info(bank)
        assert info["format"] == 2 and info["heads"] == 2
        assert info["generation"] == 3 and info["version"] == 7
        x = np.random.default_rng(0).standard_normal(
            (16, 36)).astype(np.float32)
        s_base, spec = native.score_eval_route(bank, 12345, x)
        assert not spec  # unknown hash: base model serves
        s_a, spec_a = native.score_eval_route(bank, ra, x)
        s_b, spec_b = native.score_eval_route(bank, rb, x)
        assert spec_a and spec_b
        assert np.abs(s_a - s_base).max() > 1e-6
        assert np.abs(s_a - s_b).max() > 1e-6
        # base eval equals the plain v1 export of the same base
        v1 = export_weight_blob(base, 7)
        assert np.allclose(native.score_eval(v1, x), s_base, atol=1e-6)

    def test_v1_blobs_load_in_the_new_reader(self):
        """Backward compatibility: every pre-bank blob keeps working —
        engine publish, slab publish, bank-reader eval (headless bank,
        generation = model version)."""
        v1 = export_weight_blob(_fake_snap(4), 9)
        assert blob_meta(v1)["format"] == "model"
        info = native.score_blob_info(v1)
        assert info["format"] == 1
        assert info["generation"] == 9 and info["heads"] == 0
        slab = native.ScoreSlab()
        try:
            slab.publish(v1)
            st = slab.stats()
            assert st["version"] == 9 and st["generation"] == 9
            assert st["heads"] == 0
            x = np.zeros((2, 36), np.float32)
            scores, spec = slab.score_route(x, route_hash("/svc/a"))
            assert (spec == 0).all()
        finally:
            slab.close()
        eng = native.FastPathEngine()
        try:
            eng.publish_weights(v1)  # no exception: accepted
        finally:
            eng.close()

    def test_unsorted_heads_rejected(self):
        base, head = _fake_snap(1), _fake_snap(2)
        chunks = [BANK_MAGIC, struct.pack("<II", 1, 2)]
        chunks += _model_section(base, 1, "f32")
        for rh in (2000, 1000):  # descending: must be rejected
            chunks.append(struct.pack("<I", rh))
            chunks += _model_section(head, 1, "f32")
        bad = _sealed(chunks)
        with pytest.raises(ValueError, match="ascending"):
            native.score_blob_info(bad)

    def test_corrupted_bank_rejected(self):
        bank = bytearray(export_bank_blob(
            _fake_snap(1), 1, 1, {1000: (1, _fake_snap(2))}))
        bank[len(bank) // 2] ^= 0x20
        with pytest.raises(ValueError, match="crc"):
            native.score_blob_info(bytes(bank))
        with pytest.raises(ValueError):
            native.score_blob_info(bytes(bank[:100]))

    def test_export_caps_head_count(self):
        from linkerd_tpu.lifecycle.export import MAX_HEADS
        heads = {1000 + i: (i, _fake_snap(0)) for i in range(MAX_HEADS + 1)}
        with pytest.raises(ValueError, match="heads"):
            export_bank_blob(_fake_snap(1), 1, 1, heads)


class TestInt4:
    def test_int4_blob_is_smallest(self):
        snap = _fake_snap(5)
        f32 = export_weight_blob(snap, 1, "f32")
        i8 = export_weight_blob(snap, 1, "int8")
        i4 = export_weight_blob(snap, 1, "int4")
        assert len(i4) < len(i8) < len(f32)
        # the weight payload halves again vs int8 (nibble packing)
        assert native.score_blob_info(i4)["quant"] == 2

    def test_int4_parity_bounds_pinned(self, trained_snapshot):
        """The acceptance bound: int4 native eval vs the f32 evaluator
        AND vs the jitted serving scorer, pinned alongside the existing
        f32 1e-5 / int8 3e-2 bounds (measured ~0.06 max; 2x headroom).
        """
        snap, x, jitted = trained_snapshot
        f32 = export_weight_blob(snap, 1, "f32")
        i4 = export_weight_blob(snap, 1, "int4")
        a = native.score_eval(f32, x)
        b = native.score_eval(i4, x)
        assert np.abs(a - b).max() < 0.12
        assert np.abs(a - b).mean() < 0.04
        assert np.abs(jitted - b).max() < 0.12
        assert np.abs(jitted - b).mean() < 0.04
        assert np.isfinite(b).all()
        assert (b >= 0.0).all() and (b <= 1.0).all()

    def test_existing_bounds_still_hold(self, trained_snapshot):
        snap, x, jitted = trained_snapshot
        f32 = export_weight_blob(snap, 1, "f32")
        i8 = export_weight_blob(snap, 1, "int8")
        a = native.score_eval(f32, x)
        assert np.abs(a - jitted).max() < 0.05          # f32 vs bf16 jit
        assert np.abs(a - native.score_eval(i8, x)).max() < 0.03

    def test_int4_engine_publish(self):
        eng = native.FastPathEngine()
        try:
            eng.publish_weights(export_weight_blob(_fake_snap(2), 3,
                                                   "int4"))
            st = eng.stats()["native_scorer"]
            assert st["weights"] and st["version"] == 3
        finally:
            eng.close()


class TestDeltaFormat:
    def test_delta_roundtrip_meta(self):
        d = export_delta_blob(4, 5, {1000: (2, _fake_snap(1))},
                              removes=[2000])
        meta = blob_meta(d)
        assert meta["format"] == "delta"
        assert meta["base_generation"] == 4
        assert meta["new_generation"] == 5 and meta["ops"] == 2
        info = native.score_blob_info(d)
        assert info["format"] == 3 and info["ops"] == 2

    def test_corrupted_and_truncated_deltas_rejected(self):
        d = bytearray(export_delta_blob(1, 2, {1000: (1, _fake_snap(1))}))
        flipped = bytearray(d)
        flipped[len(flipped) // 2] ^= 0x08
        slab = native.ScoreSlab()
        try:
            slab.publish(export_bank_blob(_fake_snap(0), 1, 1, {}))
            with pytest.raises(ValueError, match="crc"):
                slab.publish_delta(bytes(flipped))
            with pytest.raises(ValueError):
                slab.publish_delta(bytes(d[: len(d) // 2]))
            # unknown op id survives CRC but fails the parse
            bad_op = bytearray(d[:-4])
            struct.pack_into("<I", bad_op, 8 + 12, 7)
            bad_op = bytes(bad_op) + struct.pack(
                "<I", zlib.crc32(bytes(bad_op)))
            with pytest.raises(ValueError, match="op"):
                slab.publish_delta(bad_op)
            # every rejection left the serving bank untouched
            assert slab.stats()["generation"] == 1
            assert slab.stats()["delta_swaps"] == 0
        finally:
            slab.close()

    def test_generation_fence_and_absent_remove(self):
        slab = native.ScoreSlab()
        try:
            with pytest.raises(ValueError, match="no bank"):
                slab.publish_delta(export_delta_blob(
                    0, 1, {1000: (1, _fake_snap(1))}))
            slab.publish(export_bank_blob(_fake_snap(0), 1, 5, {}))
            with pytest.raises(ValueError, match="generation"):
                slab.publish_delta(export_delta_blob(
                    4, 6, {1000: (1, _fake_snap(1))}))
            with pytest.raises(ValueError, match="absent"):
                slab.publish_delta(export_delta_blob(5, 6,
                                                     removes=[1234]))
            ok = export_delta_blob(5, 6, {1000: (1, _fake_snap(1))})
            slab.publish_delta(ok)
            assert slab.stats()["generation"] == 6
            assert slab.stats()["heads"] == 1
            # replaying the SAME delta is fenced out (gen moved on)
            with pytest.raises(ValueError, match="generation"):
                slab.publish_delta(ok)
        finally:
            slab.close()

    def test_export_refuses_degenerate_deltas(self):
        with pytest.raises(ValueError, match="exceed"):
            export_delta_blob(3, 3, {1000: (1, _fake_snap(1))})
        with pytest.raises(ValueError, match="at least one"):
            export_delta_blob(1, 2)


class TestTornWeightsDeltaStress:
    def test_concurrent_delta_and_full_publish_never_torn(self):
        """The §2.14 torn-weights stress extended to delta patches on
        the multi-worker shared slab: while one publisher alternates a
        full bank publish and a generation-fenced delta upsert as fast
        as it can, every concurrently observed score for the patched
        route matches the bank's head or the delta's head EXACTLY — a
        half-applied patch would produce a third value."""
        rh = 1000  # the C test bank keys heads from 1000
        bank = native.score_test_bank(generation=1, seed=5, n_heads=1)
        delta = native.score_test_delta(1, 2, rh, seed=77)
        x = np.random.default_rng(4).standard_normal(
            (1, native.score_feature_dim())).astype(np.float32)
        slab = native.ScoreSlab()
        try:
            slab.publish(bank)
            s_bank = float(slab.score_route(x, rh)[0][0])
            slab.publish_delta(delta)
            s_delta = float(slab.score_route(x, rh)[0][0])
            assert abs(s_bank - s_delta) > 1e-6
            stop = threading.Event()
            bad = []
            applied = [0]

            def publisher():
                while not stop.is_set():
                    slab.publish(bank)        # resets to generation 1
                    slab.publish_delta(delta)  # fenced 1 -> 2
                    applied[0] += 1

            def scorer_thread():
                while not stop.is_set():
                    out = slab.score_route(x, rh)
                    s = float(out[0][0])
                    if (abs(s - s_bank) > 1e-6
                            and abs(s - s_delta) > 1e-6):
                        bad.append(s)

            threads = [threading.Thread(target=publisher)] + [
                threading.Thread(target=scorer_thread) for _ in range(3)]
            for t in threads:
                t.start()
            time.sleep(1.0)
            stop.set()
            for t in threads:
                t.join()
            assert applied[0] > 10
            assert bad == [], f"torn scores observed: {bad[:5]}"
            assert slab.stats()["delta_swaps"] > 10
        finally:
            slab.close()


async def _echo_server():
    async def handle(r, w):
        try:
            while True:
                await r.readuntil(b"\r\n\r\n")
                w.write(b"HTTP/1.1 200 OK\r\n"
                        b"Content-Length: 2\r\n\r\nok")
                await w.drain()
        except Exception:  # noqa: BLE001 — client went away
            pass

    srv = await asyncio.start_server(handle, "127.0.0.1", 0)
    return srv, srv.sockets[0].getsockname()[1]


async def _paced(port: int, n: int, host: bytes = b"svc"):
    r, w = await asyncio.open_connection("127.0.0.1", port)
    rsp = b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok"
    try:
        for _ in range(n):
            w.write(b"GET / HTTP/1.1\r\nHost: " + host + b"\r\n\r\n")
            await w.drain()
            await r.readexactly(len(rsp))
    finally:
        w.close()
        try:
            await w.wait_closed()
        except Exception:  # noqa: BLE001
            pass


class TestEngineBankServing:
    def test_two_worker_engine_serves_specialist_head(self):
        """Real loopback traffic through a 2-worker shard group: rows
        for the hashed route score on the specialist head (shared
        slab), a REMOVE delta rolls just that route back to the base
        model, and the merged stats carry the bank generation/heads."""
        dst = "/fp/spec"
        rh = route_hash(dst)
        base, head = _fake_snap(1), _fake_snap(2, scale=0.5)

        async def go():
            eng = native.FastPathEngine(workers=2)
            port = eng.listen("127.0.0.1", 0)
            srv, bport = await _echo_server()
            try:
                eng.start()
                eng.set_route("svc", [("127.0.0.1", bport)])
                assert eng.set_route_feature("svc", 14, 1.0)
                assert eng.set_route_hash("svc", rh)
                assert not eng.set_route_hash("ghost", rh)
                eng.publish_weights(export_bank_blob(
                    base, 1, 3, {rh: (1, head)}))
                # spread over both workers: several connections
                for _ in range(4):
                    await _paced(port, 10)
                await asyncio.sleep(0.15)
                st = eng.stats()["native_scorer"]
                assert st["weights"] and st["generation"] == 3
                assert st["heads"] == 1
                assert st["scored"] == 40
                assert st["specialist_scored"] == 40
                rows = eng.drain_features()
                assert (rows[:, NATIVE_COL_SCORED] == 1.0).all()
                # single-route rollback: REMOVE delta, base serves
                eng.publish_delta(export_delta_blob(3, 4, removes=[rh]))
                for _ in range(2):
                    await _paced(port, 10)
                await asyncio.sleep(0.15)
                st = eng.stats()["native_scorer"]
                assert st["generation"] == 4 and st["heads"] == 0
                assert st["scored"] == 60
                assert st["specialist_scored"] == 40  # frozen: base now
                assert st["delta_swaps"] == 1
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_h2_engine_control_surface(self):
        eng = native.H2FastPathEngine()
        try:
            eng.set_route("svc", [("127.0.0.1", 1)])
            assert eng.set_route_hash("svc", 77)
            eng.publish_weights(native.score_test_bank(
                generation=1, seed=1, n_heads=1))
            eng.publish_delta(native.score_test_delta(1, 2, 1000,
                                                      seed=2))
            st = eng.stats()["native_scorer"]
            assert st["generation"] == 2 and st["heads"] == 1
        finally:
            eng.close()


class TestRouteMonitors:
    def test_drift_trigger_and_re_anchor(self):
        mon = RouteDriftMonitor(threshold=1.0, min_rows=16)
        rng = np.random.default_rng(0)
        for _ in range(4):
            mon.observe(["/a"] * 8, rng.normal(0.2, 0.02, 8))
        assert mon.score_shift("/a") < 0.5
        assert mon.triggered() == []
        for _ in range(8):
            mon.observe(["/a"] * 8, rng.normal(0.8, 0.02, 8))
        assert mon.score_shift("/a") > 1.0
        assert mon.triggered() == ["/a"]
        mon.re_anchor("/a")
        assert mon.score_shift("/a") == 0.0
        assert mon.triggered() == []

    def test_replay_window_bounds(self):
        w = RouteReplayWindow(per_route_rows=16, max_routes=2)
        x = np.arange(40, dtype=np.float32).reshape(10, 4)
        lab = np.zeros(10, np.float32)
        w.add(["/a"] * 10, x, lab, lab)
        w.add(["/a"] * 10, x + 100, lab, lab)
        assert w.rows("/a") == 16
        xa, _, _ = w.sample("/a")
        assert xa[-1, 0] == 136.0  # newest rows kept
        w.add(["/b"] * 10, x, lab, lab)
        w.add(["/c"] * 10, x, lab, lab)  # evicts the stalest (/a)
        assert w.rows("/a") == 0
        assert w.rows("/b") == 10 and w.rows("/c") == 10


class _SnapScorer:
    """Sync-snapshot scorer stub: the pipeline only needs snapshot()."""

    def __init__(self, snap):
        self._snap = snap
        self._step = snap.step

    def snapshot(self):
        return self._snap


def _shifted_pipeline(cfg=None, store=None):
    """A pipeline with route /a warmed on low scores then shifted —
    pending_route() == '/a'."""
    p = (cfg or DistillConfig(maxHeads=4, driftThreshold=0.5,
                              minRouteRows=32, retrainSteps=2,
                              cooldownS=0.0)).mk(None, store=store)
    rng = np.random.default_rng(0)
    dim = 36
    for loc, n in ((0.1, 6), (0.9, 6)):
        for _ in range(n):
            x = rng.standard_normal((16, dim)).astype(np.float32)
            s = rng.normal(loc, 0.02, 16).astype(np.float32)
            p.observe_batch(["/a"] * 16, x, s, np.zeros(16, np.float32),
                            np.zeros(16, np.float32))
    return p


class TestPipeline:
    def test_promote_publishes_delta_and_records_lineage(
            self, trained_snapshot, tmp_path):
        from linkerd_tpu.lifecycle import CheckpointStore
        snap, _, _ = trained_snapshot
        store = CheckpointStore(str(tmp_path / "ck"))
        pipe = _shifted_pipeline(store=store)
        published = []
        pipe.set_publisher(lambda full, delta:
                           published.append((full, delta)) or True)
        assert pipe.pending_route() == "/a"
        out = run(pipe.run_once(_SnapScorer(snap), base_version=42))
        assert out is not None and out["action"] == "promoted"
        assert out["delta_published"]
        assert pipe.bank.generation == 1 and len(pipe.bank) == 1
        (full, delta), = published
        assert blob_meta(full)["format"] == "bank"
        dm = blob_meta(delta)
        assert dm["format"] == "delta" and dm["new_generation"] == 1
        # delta is the per-route increment, smaller than the full bank
        assert len(delta) < len(full)
        # manifest lineage: the head's dst/base checkpoint/delta CRC
        rh = str(route_hash("/a"))
        spec = store.specialists()
        assert spec[rh]["dst"] == "/a"
        assert spec[rh]["base_version"] == 42
        assert spec[rh]["delta_crc"] == dm["crc"]
        # survives a reload
        assert CheckpointStore(str(tmp_path / "ck")).specialists() == spec
        # the trigger cleared: reference re-anchored
        assert pipe.pending_route() is None

    def test_poisoned_candidate_rejected(self, trained_snapshot,
                                         monkeypatch, tmp_path):
        """A candidate whose fine-tune went bad (poisoned rows -> NaN
        params) regresses on the held-out rows and never publishes."""
        import linkerd_tpu.distill.pipeline as pipeline_mod
        snap, _, _ = trained_snapshot
        real = pipeline_mod.distill_head

        def poisoned(base_snap, x, labels, mask, steps, lr):
            import copy
            bad = copy.deepcopy(real(base_snap, x, labels, mask, 1, lr))
            bad.params["enc"][0]["w"] = np.full_like(
                np.asarray(bad.params["enc"][0]["w"]), np.nan)
            return bad

        monkeypatch.setattr(pipeline_mod, "distill_head", poisoned)
        from linkerd_tpu.lifecycle import CheckpointStore
        store = CheckpointStore(str(tmp_path / "ck"))
        pipe = _shifted_pipeline(store=store)
        published = []
        pipe.set_publisher(lambda full, delta:
                           published.append((full, delta)) or True)
        out = run(pipe.run_once(_SnapScorer(snap)))
        assert out is not None and out["action"] == "rejected"
        assert "finite" in out["decision"]["reason"] \
            or "regressed" in out["decision"]["reason"]
        assert published == []
        assert pipe.bank.generation == 0 and len(pipe.bank) == 0
        assert store.specialists() == {}

    def test_rollback_route_removes_single_head(self, trained_snapshot,
                                                tmp_path):
        from linkerd_tpu.lifecycle import CheckpointStore
        snap, _, _ = trained_snapshot
        store = CheckpointStore(str(tmp_path / "ck"))
        pipe = _shifted_pipeline(store=store)
        published = []
        pipe.set_publisher(lambda full, delta:
                           published.append((full, delta)) or True)
        run(pipe.run_once(_SnapScorer(snap)))
        assert len(pipe.bank) == 1
        assert run(pipe.rollback_route("/a")) is True
        assert len(pipe.bank) == 0 and pipe.bank.generation == 2
        _, delta = published[-1]
        assert blob_meta(delta)["ops"] == 1
        assert store.specialists() == {}
        assert run(pipe.rollback_route("/a")) is False

    def test_bank_capacity_blocks_new_routes(self):
        pipe = DistillConfig(maxHeads=1, driftThreshold=0.5,
                             minRouteRows=16, cooldownS=0.0).mk(None)
        rng = np.random.default_rng(1)
        for dst in ("/a", "/b"):
            for loc in (0.1, 0.9):
                for _ in range(4):
                    x = rng.standard_normal((16, 36)).astype(np.float32)
                    s = rng.normal(loc, 0.02, 16).astype(np.float32)
                    pipe.observe_batch([dst] * 16, x, s,
                                       np.zeros(16, np.float32),
                                       np.zeros(16, np.float32))
        # both shifted; fill the bank with /a manually
        pipe.bank.upsert("/a", _fake_snap(1), 1, 1, 1)
        # /a may retrain (existing head), /b may not (bank full)
        assert pipe.pending_route() in ("/a",)

    def test_cooldown_blocks_immediate_retrain(self, trained_snapshot):
        snap, _, _ = trained_snapshot
        pipe = _shifted_pipeline(
            DistillConfig(maxHeads=4, driftThreshold=0.5,
                          minRouteRows=32, retrainSteps=1,
                          cooldownS=3600.0))
        pipe.set_publisher(lambda full, delta: True)
        out = run(pipe.run_once(_SnapScorer(snap)))
        assert out is not None
        # even if the route drifts again, the cooldown holds it
        rng = np.random.default_rng(2)
        for _ in range(6):
            x = rng.standard_normal((16, 36)).astype(np.float32)
            s = rng.normal(0.02, 0.01, 16).astype(np.float32)
            pipe.observe_batch(["/a"] * 16, x, s,
                               np.zeros(16, np.float32),
                               np.zeros(16, np.float32))
        assert pipe.pending_route() is None


class TestContinuousLearningE2E:
    def test_drift_to_specialist_loop(self):
        """The acceptance loop: per-route shift -> trigger -> retrain
        from the route's replay -> shadow gate -> delta publish -> a
        2-worker engine serves the route with the specialist head
        (stats + /model.json), a poisoned candidate is rejected, and a
        single-route rollback leaves the other head serving."""
        dst_a, dst_b = "/fp/spec", "/fp/beta"

        async def go():
            cfg = JaxAnomalyConfig(
                maxBatch=256, trainEveryBatches=0,
                distill=DistillConfig(maxHeads=4, driftThreshold=0.5,
                                      minRouteRows=32, retrainSteps=2,
                                      cooldownS=0.0))
            mt = MetricsTree()
            tele = JaxAnomalyTelemeter(cfg, mt)
            eng = native.FastPathEngine(workers=2)
            port = eng.listen("127.0.0.1", 0)
            srv, bport = await _echo_server()
            try:
                eng.start()
                eng.set_route("svc", [("127.0.0.1", bport)])
                eng.set_route_feature("svc", 14, 1.0)
                eng.set_route_hash("svc", route_hash(dst_a))
                eng.set_route("beta", [("127.0.0.1", bport)])
                eng.set_route_feature("beta", 15, -1.0)
                eng.set_route_hash("beta", route_hash(dst_b))
                tele.register_weight_sink(
                    eng.publish_weights, delta_sink=eng.publish_delta)
                assert await tele.refresh_native_weights() is True
                assert eng.stats()["native_scorer"]["weights"]

                rng = np.random.default_rng(0)

                async def feed(dst, lat, status, batches):
                    for _ in range(batches):
                        for _ in range(32):
                            tele.ring.append((FeatureVector(
                                dst_path=dst,
                                latency_ms=float(rng.uniform(*lat)),
                                status=status), None))
                        await tele.drain_once()

                async def wait_outcome(action, route):
                    for _ in range(600):
                        o = tele.distill.last_outcome
                        if o is not None and o["action"] == action \
                                and o["route"] == route:
                            return o
                        await asyncio.sleep(0.05)
                    raise AssertionError(
                        f"no {action} outcome for {route}; last: "
                        f"{tele.distill.last_outcome}")

                # route A: normal phase anchors, shift triggers
                await feed(dst_a, (5, 10), 200, 6)
                await feed(dst_a, (2000, 4000), 503, 8)
                out = await wait_outcome("promoted", dst_a)
                assert out["delta_published"]
                gen_a = out["generation"]
                # the engines observed the delta: generation + head
                st = eng.stats()["native_scorer"]
                assert st["generation"] == gen_a and st["heads"] == 1
                # and the route's live traffic scores on the specialist
                await _paced(port, 20)
                await asyncio.sleep(0.15)
                st = eng.stats()["native_scorer"]
                assert st["specialist_scored"] >= 20
                # /model.json: bank generation + per-head lineage
                ms = tele.model_state()
                bank = ms["distill"]["bank"]
                assert bank["generation"] == gen_a
                assert str(route_hash(dst_a)) in bank["heads"]

                # route B promotes too (two heads serving)
                tele.distill.last_outcome = None
                await feed(dst_b, (5, 10), 200, 6)
                await feed(dst_b, (2000, 4000), 503, 8)
                out_b = await wait_outcome("promoted", dst_b)
                assert eng.stats()["native_scorer"]["heads"] == 2

                # poisoned candidate for a third route is rejected and
                # nothing about the serving bank changes
                import linkerd_tpu.distill.pipeline as pipeline_mod
                real = pipeline_mod.distill_head

                def poisoned(base_snap, x, labels, mask, steps, lr):
                    import copy
                    bad = copy.deepcopy(real(base_snap, x, labels,
                                             mask, 1, lr))
                    bad.params["enc"][0]["w"] = np.full_like(
                        np.asarray(bad.params["enc"][0]["w"]), np.nan)
                    return bad

                pipeline_mod.distill_head = poisoned
                try:
                    tele.distill.last_outcome = None
                    await feed("/fp/poison", (5, 10), 200, 6)
                    await feed("/fp/poison", (2000, 4000), 503, 8)
                    out_p = await wait_outcome("rejected", "/fp/poison")
                finally:
                    pipeline_mod.distill_head = real
                st = eng.stats()["native_scorer"]
                assert st["heads"] == 2
                assert st["generation"] == out_b["generation"]
                flat = mt.flatten()
                assert flat["anomaly/distill/rejections"] == 1
                assert flat["anomaly/distill/promotions"] == 2

                # single-route rollback: A's head goes, B's stays and
                # keeps serving its specialist
                assert await tele.distill.rollback_route(dst_a)
                st = eng.stats()["native_scorer"]
                assert st["heads"] == 1
                before = st["specialist_scored"]
                await _paced(port, 10, host=b"beta")   # B: specialist
                await _paced(port, 10, host=b"svc")    # A: base again
                await asyncio.sleep(0.15)
                st = eng.stats()["native_scorer"]
                assert st["specialist_scored"] == before + 10
            finally:
                tele.close()
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())


class TestControllerStatsExport:
    def test_specialist_stats_reach_metrics_tree(self):
        """The controller's stats loop exports the bank fields under
        rt/<label>/fastpath/scorer/* — the live proof surface the e2e
        acceptance reads (specialist_scored / delta_swaps counters,
        generation / heads gauges)."""
        from linkerd_tpu.core import Dtab, Path
        from linkerd_tpu.router.fastpath import FastPathController

        class StubEngine:
            def stats(self):
                return {"native_scorer": {
                    "weights": True, "version": 3, "crc": 1,
                    "generation": 5, "heads": 2,
                    "swaps": 4, "delta_swaps": 3, "retries": 0,
                    "scored": 100, "specialist_scored": 60,
                    "unscored": 0, "score_ns_hist": []}}

        mt = MetricsTree()
        ctl = FastPathController(
            StubEngine(), interpreter=None, base_dtab=Dtab.read(""),
            prefix=Path.read("/svc"), label="fp", metrics=mt)
        ctl._export_stats()
        flat = mt.flatten()
        assert flat["rt/fp/fastpath/scorer/scored"] == 100
        assert flat["rt/fp/fastpath/scorer/specialist_scored"] == 60
        assert flat["rt/fp/fastpath/scorer/delta_swaps"] == 3
        assert flat["rt/fp/fastpath/scorer/generation"] == 5.0
        assert flat["rt/fp/fastpath/scorer/heads"] == 2.0


class TestConfigAndState:
    def test_distill_config_parses_from_yaml(self):
        from linkerd_tpu.config.parser import instantiate
        cfg = instantiate("telemeter", {
            "kind": "io.l5d.jaxAnomaly",
            "distill": {"maxHeads": 8, "driftThreshold": 1.5,
                        "quant": "int4"},
        }, "telemetry[0]")
        assert cfg.distill.maxHeads == 8
        assert cfg.distill.quant == "int4"

    def test_telemeter_validates_distill_quant(self):
        with pytest.raises(ValueError, match="distill.quant"):
            JaxAnomalyTelemeter(
                JaxAnomalyConfig(distill=DistillConfig(quant="fp8")),
                MetricsTree())

    def test_pipeline_validates_knobs(self):
        for kw in ({"maxHeads": 0}, {"driftThreshold": 0.0},
                   {"minRouteRows": 2}, {"retrainSteps": 0},
                   {"learningRate": 0.0}, {"cooldownS": -1.0}):
            with pytest.raises(ValueError):
                DistillConfig(**kw).mk(None)
