"""Exporter + tracing tests: prometheus text, influxdb lines, statsd
push, tracelog/recentRequests/zipkin tracers, trace propagation e2e."""

import asyncio
import json

import pytest

from linkerd_tpu.linker import load_linker
from linkerd_tpu.protocol.http import Request, Response
from linkerd_tpu.protocol.http.client import HttpClient
from linkerd_tpu.protocol.http.server import serve
from linkerd_tpu.router.service import FnService
from linkerd_tpu.router.tracing import CTX_TRACE, TraceId
from linkerd_tpu.telemetry.exporters import (
    influxdb_line, prometheus_text, RecentRequestsConfig, StatsDConfig,
    ZipkinConfig,
)
from linkerd_tpu.telemetry.metrics import MetricsTree


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def sample_metrics() -> MetricsTree:
    mt = MetricsTree()
    mt.counter("rt", "out", "server", "requests").incr(10)
    mt.counter("rt", "out", "service", "svc.web", "success").incr(9)
    mt.counter("rt", "out", "client", "fs.web", "failures").incr(1)
    s = mt.stat("rt", "out", "server", "request_latency_ms")
    for v in (1.0, 2.0, 3.0):
        s.add(v)
    return mt


class TestPrometheus:
    def test_label_rewriting(self):
        text = prometheus_text(sample_metrics())
        assert 'requests{rt="out"} 10' in text
        assert 'success{rt="out",service="svc.web"} 9' in text
        assert 'failures{client="fs.web",rt="out"} 1' in text
        assert 'request_latency_ms{quantile="0.5",rt="out"}' in text
        assert 'request_latency_ms_count{rt="out"} 3' in text

    def test_sanitization(self):
        mt = MetricsTree()
        mt.counter("weird-name", "a b").incr()
        text = prometheus_text(mt)
        assert "weird_name_a_b 1" in text


class TestInfluxDb:
    def test_line_protocol(self):
        text = influxdb_line(sample_metrics(), host="h1")
        assert any(line.startswith("rt,host=h1,rt=out ")
                   for line in text.splitlines())
        assert "requests=10.0" in text


class TestTraceId:
    def test_roundtrip(self):
        t = TraceId.mk_root()
        assert TraceId.decode(t.encode()) == t

    def test_child_links(self):
        t = TraceId.mk_root()
        c = t.child()
        assert c.trace_id == t.trace_id
        assert c.parent_id == t.span_id
        assert c.span_id != t.span_id

    def test_decode_garbage(self):
        assert TraceId.decode("nope") is None
        assert TraceId.decode("zz-yy-xx-ww") is None


class TestTracingEndToEnd:
    def test_spans_recorded_and_propagated(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()
        seen_headers = []

        async def backend(req: Request) -> Response:
            seen_headers.append(req.headers.get(CTX_TRACE))
            return Response(200, body=b"ok")

        async def go():
            d = await serve(FnService(backend))
            (disco / "web").write_text(f"127.0.0.1 {d.bound_port}\n")
            cfg = f"""
routers:
- protocol: http
  label: tr
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
telemetry:
- kind: io.l5d.recentRequests
  capacity: 10
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                # caller supplies a trace context
                root = TraceId.mk_root()
                req = Request(uri="/")
                req.headers.set("Host", "web")
                req.headers.set(CTX_TRACE, root.encode())
                await proxy(req)

                # downstream received a child of the caller's trace
                assert seen_headers[0] is not None
                ds = TraceId.decode(seen_headers[0])
                assert ds.trace_id == root.trace_id
                assert ds.parent_id != root.span_id  # server child's child

                # recentRequests captured the server span
                tele = linker.telemeters[0]
                assert len(tele.ring) == 1
                span = tele.ring[0]
                assert span["tags"]["dst.path"] == "/svc/web"
                assert span["traceId"] == f"{root.trace_id:032x}"

                # admin handler serves it
                handlers = dict(tele.admin_handlers())
                rsp = await handlers["/requests.json"](Request())
                assert json.loads(rsp.body)[0]["kind"] == "SERVER"
            finally:
                await proxy.close()
                await linker.close()
                await d.close()

        run(go())


class TestStatsD:
    def test_flush_sends_udp(self):
        async def go():
            received = []

            class Proto(asyncio.DatagramProtocol):
                def datagram_received(self, data, addr):
                    received.append(data.decode())

            loop = asyncio.get_running_loop()
            transport, _ = await loop.create_datagram_endpoint(
                Proto, local_addr=("127.0.0.1", 0))
            port = transport.get_extra_info("sockname")[1]

            mt = sample_metrics()
            cfg = StatsDConfig(port=port, gaugeIntervalMs=50)
            tele = cfg.mk(mt)
            task = asyncio.create_task(tele.run())
            await asyncio.sleep(0.2)
            tele.close()
            task.cancel()
            transport.close()
            assert any("linkerd.rt.out.server.requests:10|c" in r
                       for r in received)

        run(go())


class TestZipkin:
    def test_flush_posts_spans(self):
        async def go():
            posted = []

            async def collector(req: Request) -> Response:
                posted.append(json.loads(req.body))
                return Response(status=202)

            srv = await serve(FnService(collector))
            cfg = ZipkinConfig(port=srv.bound_port, batchIntervalMs=50)
            tele = cfg.mk(MetricsTree())
            tele.tracer.record({"traceId": "ab", "id": "cd", "kind": "SERVER"})
            from linkerd_tpu.protocol.http.client import HttpClient as HC
            client = HC("127.0.0.1", srv.bound_port)
            await tele.flush(client)
            assert posted and posted[0][0]["traceId"] == "ab"
            await client.close()
            await srv.close()

        run(go())
