"""l5dlint self-tests: every rule fires on a positive fixture, stays
quiet on the matching negative, suppressions require justification, and
the real tree is clean (the tier-1 gate).

Fixtures are tiny synthetic repos written under tmp_path with the same
layout the scope filters expect (``linkerd_tpu/router/...`` etc.), so
the checkers run exactly as they do against the real tree.
"""

import os
import textwrap

import pytest

from tools.analysis import run_analysis, rule_ids

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_repo(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


def findings_of(tmp_path, files, rule):
    root = mk_repo(tmp_path, files)
    out = run_analysis(["linkerd_tpu"], repo_root=root, rules=[rule])
    return [f for f in out if f.rule == rule]


class TestAsyncBlocking:
    def test_direct_blocking_call_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import time
                async def handle(req):
                    time.sleep(0.1)
                    return req
            """}, "async-blocking")
        assert len(got) == 1 and "time.sleep" in got[0].message
        assert got[0].path == "linkerd_tpu/router/x.py"
        assert got[0].line == 4

    def test_reachable_through_sync_helper(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/x.py": """
                import time
                def helper():
                    time.sleep(1)
                async def handle(req):
                    helper()
            """}, "async-blocking")
        assert len(got) == 1 and "helper" in got[0].message

    def test_async_sleep_and_to_thread_are_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio, time
                async def handle(req):
                    await asyncio.sleep(0.1)
                    await asyncio.to_thread(time.sleep, 1)
            """}, "async-blocking")
        assert got == []

    def test_out_of_scope_package_is_ignored(self, tmp_path):
        # startup/control-plane code may block; the rule is data-plane
        got = findings_of(tmp_path, {
            "linkerd_tpu/namerd/x.py": """
                import time
                async def boot():
                    time.sleep(1)
            """}, "async-blocking")
        assert got == []

    def test_blocking_call_in_lambda_inside_async_def_fires(self, tmp_path):
        # regression: lambda bodies are frames body_calls skips, so a
        # blocking call hidden in one passed silently
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio, time
                async def handle(req, loop):
                    loop.call_soon(lambda: time.sleep(1))
                    return req
            """}, "async-blocking")
        assert len(got) == 1 and "lambda" in got[0].message

    def test_offloaded_lambda_is_clean(self, tmp_path):
        # to_thread/run_in_executor run the lambda in a worker thread —
        # blocking there is the sanctioned escape hatch
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio, time
                async def handle(req, loop):
                    await asyncio.to_thread(lambda: time.sleep(1))
                    await loop.run_in_executor(None, lambda: time.sleep(1))
                    return req
            """}, "async-blocking")
        assert got == []

    def test_lambda_in_nested_async_def_reported_once(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio, time
                async def outer(loop):
                    async def inner():
                        loop.call_soon(lambda: time.sleep(1))
                    await inner()
            """}, "async-blocking")
        assert len(got) == 1 and "inner" in got[0].message


class TestTaskLeak:
    def test_dropped_spawn_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                def go(loop, coro):
                    loop.create_task(coro)
            """}, "task-leak")
        assert len(got) == 1 and "dropped" in got[0].message

    def test_held_or_chained_spawn_is_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                def go(loop, coro, cb):
                    t = loop.create_task(coro)
                    loop.create_task(coro).add_done_callback(cb)
                    return t
            """}, "task-leak")
        assert got == []

    def test_spawn_inside_callback_lambda_fires(self, tmp_path):
        # regression: call_soon discards its callback's return value, so
        # a lambda-body spawn drops the Task — this passed silently
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                def go(loop, mk):
                    loop.call_soon(lambda: loop.create_task(mk()))
            """}, "task-leak")
        assert len(got) == 1 and "lambda" in got[0].message

    def test_spawning_lambda_used_as_factory_is_clean(self, tmp_path):
        # the lambda's return value is consumed — not a leak
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                def go(loop, mk):
                    factory = lambda: loop.create_task(mk())
                    t = factory()
                    return t
            """}, "task-leak")
        assert got == []


class TestSwallowedException:
    def test_broad_pass_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/grpc/x.py": """
                def f(x):
                    try:
                        return x()
                    except Exception:
                        pass
            """}, "swallowed-exception")
        assert len(got) == 1

    def test_bare_except_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                def f(x):
                    try:
                        return x()
                    except:
                        pass
            """}, "swallowed-exception")
        assert len(got) == 1 and "bare" in got[0].message

    def test_narrow_logged_or_reraised_are_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/x.py": """
                import logging
                log = logging.getLogger(__name__)
                def f(x):
                    try:
                        return x()
                    except (OSError, RuntimeError):
                        pass
                def g(x):
                    try:
                        return x()
                    except Exception as e:
                        log.debug("boom: %r", e)
                def h(x):
                    try:
                        return x()
                    except Exception:
                        raise
            """}, "swallowed-exception")
        assert got == []


class TestStreamRelease:
    def test_unreleased_frame_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/grpc/x.py": """
                async def recv(stream):
                    frame = await stream.read()
                    return bytes(frame.data)
            """}, "stream-release")
        assert len(got) == 1 and "frame" in got[0].message

    def test_dropped_read_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/h2/x.py": """
                async def drain(stream):
                    await stream.read()
            """}, "stream-release")
        assert len(got) == 1

    def test_released_or_forwarded_is_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/grpc/x.py": """
                async def recv(stream):
                    frame = await stream.read()
                    try:
                        return bytes(frame.data)
                    finally:
                        frame.release()
                async def tee(stream, out):
                    frame = await stream.read()
                    out.offer(frame)
                async def read_bytes(reader):
                    data = await reader.read(4096)  # byte read, not a frame
                    return data
            """}, "stream-release")
        assert got == []


class TestJaxPurity:
    def test_impure_jit_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/models/x.py": """
                import jax
                import numpy as np
                @jax.jit
                def used_step(x):
                    print("tracing")
                    return np.asarray(x)
            """,
            "linkerd_tpu/models/user.py": "from linkerd_tpu.models.x "
                                          "import used_step\n",
        }, "jax-purity")
        msgs = " ".join(f.message for f in got)
        assert "print" in msgs and "np.asarray" in msgs

    def test_captured_state_mutation_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/parallel/x.py": """
                import jax
                class M:
                    def mk(self):
                        @jax.jit
                        def step(x):
                            self.count = self.count + 1
                            return x
                        return step
            """}, "jax-purity")
        assert any("self.count" in f.message for f in got)

    def test_dead_helper_fires_and_wired_helper_is_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/ops/x.py": """
                def dead_helper(x):
                    return x + 1
                def live_helper(x):
                    return x * 2
            """,
            "tests/test_x.py": "from linkerd_tpu.ops.x import live_helper\n",
        }, "jax-purity")
        assert len(got) == 1 and "dead_helper" in got[0].message

    def test_pallas_kernel_via_partial_is_scanned(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/ops/x.py": """
                import functools
                from jax.experimental import pallas as pl
                def my_kernel(ref, out):
                    print("host io")
                    out[...] = ref[...]
                def run(x):
                    kernel = functools.partial(my_kernel)
                    return pl.pallas_call(kernel)(x)
            """}, "jax-purity")
        assert any("print" in f.message and "my_kernel" in f.message
                   for f in got)


class TestFloatTime:
    def test_direct_duration_subtraction_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import time
                def measure(fn):
                    t0 = time.time()
                    fn()
                    return time.time() - t0
            """}, "float-time")
        assert len(got) == 1 and got[0].line == 6

    def test_variable_flow_flags_the_assignment(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import time
                def deadline_of(timeout_s, now_mono):
                    wall = time.time()
                    return now_mono < wall + timeout_s
            """}, "float-time")
        assert len(got) == 1
        assert got[0].line == 4 and "assigned here" in got[0].message

    def test_deadline_comparison_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                import time
                def expired(deadline):
                    return time.time() > deadline
            """}, "float-time")
        assert len(got) == 1

    def test_method_bodies_are_scanned(self, tmp_path):
        # regression: walk_functions used to skip class methods entirely
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import time
                class Filter:
                    async def apply(self, req, service):
                        t0 = time.time()
                        rsp = await service(req)
                        self.latency = time.time() - t0
                        return rsp
            """}, "float-time")
        assert len(got) >= 1

    def test_lambda_bodies_are_scanned(self, tmp_path):
        # regression: lambdas are frames the per-frame walk skips, so a
        # wall-clock duration inside one passed silently
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import time
                def mk_age_fn(t0):
                    return lambda: time.time() - t0
            """}, "float-time")
        assert len(got) == 1

    def test_rebound_variable_clears_wall_clock_taint(self, tmp_path):
        # t0 first holds a reported wall timestamp, then is rebound to
        # monotonic before the arithmetic — no bug, no finding
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import time
                def span():
                    t0 = time.time()
                    stamp = int(t0 * 1e6)
                    t0 = time.monotonic()
                    return stamp, time.monotonic() - t0
            """}, "float-time")
        assert got == []

    def test_timestamps_and_unit_conversion_are_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import time
                def span_fields():
                    ts_us = int(time.time() * 1e6)  # unit conversion
                    t0 = time.monotonic()
                    return {"ts": round(time.time(), 3),  # reported stamp
                            "elapsed": time.monotonic() - t0,
                            "timestamp": ts_us}
            """}, "float-time")
        assert got == []

    def test_out_of_scope_control_plane_is_ignored(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/namerd/x.py": """
                import time
                def uptime(start):
                    return time.time() - start
            """}, "float-time")
        assert got == []


class TestConfigRegistry:
    FILES = {
        "linkerd_tpu/cfg.py": """
            from dataclasses import dataclass
            from linkerd_tpu.config import register
            @register("namer", "io.l5d.good")
            @dataclass
            class GoodConfig:
                '''A documented kind.'''
                port: int = 0
            @register("namer", "io.l5d.bad")
            class BadConfig:
                pass
        """,
        "tests/test_cfg.py": "KIND = 'io.l5d.good'\n",
        "README.md": "uses io.l5d.good\n",
    }

    def test_loose_undocumented_unexercised_fire(self, tmp_path):
        got = findings_of(tmp_path, self.FILES, "config-registry")
        bad = [f for f in got if "io.l5d.bad" in f.message]
        msgs = " ".join(f.message for f in bad)
        assert "not a @dataclass" in msgs
        assert "undocumented" in msgs
        assert "exercised by no test" in msgs

    def test_documented_exercised_dataclass_is_clean(self, tmp_path):
        got = findings_of(tmp_path, self.FILES, "config-registry")
        assert not [f for f in got if "io.l5d.good" in f.message]


class TestSuppressions:
    LEAK = """
        import asyncio
        def go(loop, coro):
            loop.create_task(coro)  {comment}
    """

    def test_justified_suppression_suppresses(self, tmp_path):
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": self.LEAK.format(
            comment="# l5d: ignore[task-leak] — daemon owns its lifetime")})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        leaks = [f for f in out if f.rule == "task-leak"]
        assert len(leaks) == 1 and leaks[0].suppressed
        assert "daemon" in leaks[0].justification
        assert not [f for f in out if f.rule == "suppression"]

    def test_suppression_requires_justification(self, tmp_path):
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": self.LEAK.format(
            comment="# l5d: ignore[task-leak]")})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        leaks = [f for f in out if f.rule == "task-leak"]
        # the bare ignore does NOT silence the finding...
        assert len(leaks) == 1 and not leaks[0].suppressed
        # ...and is itself reported
        sup = [f for f in out if f.rule == "suppression"]
        assert len(sup) == 1 and "justification" in sup[0].message

    def test_unknown_rule_in_suppression_is_reported(self, tmp_path):
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": self.LEAK.format(
            comment="# l5d: ignore[no-such-rule] — because")})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        sup = [f for f in out if f.rule == "suppression"]
        assert len(sup) == 1 and "unknown rule" in sup[0].message

    def test_trailing_suppression_binds_to_its_line_only(self, tmp_path):
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": textwrap.dedent("""
            import asyncio
            def go(loop, coro):
                x = 1  # l5d: ignore[task-leak] — wrong line on purpose
                loop.create_task(coro)
        """)})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        leaks = [f for f in out if f.rule == "task-leak"]
        assert len(leaks) == 1 and not leaks[0].suppressed

    def test_comment_line_above_applies(self, tmp_path):
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": textwrap.dedent("""
            import asyncio
            def go(loop, coro):
                # l5d: ignore[task-leak] — fire-and-forget by design here
                loop.create_task(coro)
        """)})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        leaks = [f for f in out if f.rule == "task-leak"]
        assert len(leaks) == 1 and leaks[0].suppressed


class TestStaleSuppressions:
    """The stale-suppression meta-rule: a justified waiver that no
    longer silences anything is itself a finding — it would hide the
    next regression on that line."""

    FIXED = """
        import asyncio
        def go(loop, coro):
            t = loop.create_task(coro)  {comment}
            return t
    """

    def test_stale_justified_waiver_is_flagged(self, tmp_path):
        # the task IS held: the waiver excuses nothing
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": self.FIXED.format(
            comment="# l5d: ignore[task-leak] — daemon owns its "
                    "lifetime")})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        stale = [f for f in out if f.rule == "stale-suppression"]
        assert len(stale) == 1, out
        assert "no longer silences" in stale[0].message
        assert "task-leak" in stale[0].message

    def test_live_waiver_is_not_stale(self, tmp_path):
        root = mk_repo(tmp_path, {
            "linkerd_tpu/x.py": TestSuppressions.LEAK.format(
                comment="# l5d: ignore[task-leak] — daemon owns its "
                        "lifetime")})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        assert not [f for f in out if f.rule == "stale-suppression"]

    def test_rule_filtered_runs_skip_the_stale_check(self, tmp_path):
        # with --rule only a subset of checkers runs, so "nothing
        # fired" is not evidence of staleness
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": self.FIXED.format(
            comment="# l5d: ignore[task-leak] — daemon owns its "
                    "lifetime")})
        out = run_analysis(["linkerd_tpu"], repo_root=root,
                           rules=["task-leak"])
        assert not [f for f in out if f.rule == "stale-suppression"]

    def test_unjustified_waiver_is_not_double_flagged(self, tmp_path):
        # the bare ignore is already a suppression finding; stale on
        # top would be noise
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": self.FIXED.format(
            comment="# l5d: ignore[task-leak]")})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        assert [f for f in out if f.rule == "suppression"]
        assert not [f for f in out if f.rule == "stale-suppression"]

    def test_foreign_suite_waivers_are_left_alone(self, tmp_path):
        # a waiver naming a race/seam rule is the other analyzer's to
        # judge — l5dlint never ran those checkers
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": self.FIXED.format(
            comment="# l5d: ignore[await-atomicity] — probe is "
                    "read-only")})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        assert not [f for f in out if f.rule == "stale-suppression"]

    def test_stale_finding_is_itself_suppressible(self, tmp_path):
        root = mk_repo(tmp_path, {"linkerd_tpu/x.py": textwrap.dedent("""
            import asyncio
            def go(loop, coro):
                # l5d: ignore[stale-suppression] — kept while the refactor lands
                t = loop.create_task(coro)  # l5d: ignore[task-leak] — daemon owns it
                return t
        """)})
        out = run_analysis(["linkerd_tpu"], repo_root=root)
        stale = [f for f in out if f.rule == "stale-suppression"]
        assert len(stale) == 1 and stale[0].suppressed
        assert "refactor" in stale[0].justification


class TestMetricsScope:
    def test_slashed_name_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                def install(metrics):
                    metrics.counter("rt/out/server/requests").incr()
            """}, "metrics-scope")
        assert len(got) == 1 and "rt/out/server/requests" in got[0].message

    def test_slashed_scope_component_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                def install(metrics):
                    node = metrics.scope("namerd/http")
                    node.stat("latency_ms")
            """}, "metrics-scope")
        assert len(got) == 1 and "namerd/http" in got[0].message

    def test_component_args_and_sanitized_dynamic_are_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                def install(metrics, path):
                    metrics.scope("rt", "out", "server").counter("requests")
                    metrics.gauge(path.replace("/", "."))
            """}, "metrics-scope")
        assert got == []

    def test_justified_suppression_suppresses(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                def install(metrics):
                    metrics.counter("a/b")  # l5d: ignore[metrics-scope] — wire-format key, not a scope
            """}, "metrics-scope")
        assert len(got) == 1 and got[0].suppressed


class TestJaxHotpath:
    """Per-call device seams reachable from the score dispatch path:
    device_put / to_thread / asarray readback must not creep back into
    the line-rate path (the 39.95 ms regression shape of BENCH_r04)."""

    def test_device_put_and_to_thread_in_score_fire(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                import asyncio
                import jax

                class Scorer:
                    async def score(self, x):
                        xd = jax.device_put(x, self.dev)
                        return await asyncio.to_thread(self._run, xd)
            """}, "jax-hotpath")
        assert len(got) == 2
        assert any("device_put" in f.message for f in got)
        assert any("to_thread" in f.message for f in got)

    def test_reachable_through_helper_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                import numpy as np

                class Scorer:
                    async def score(self, x):
                        return self._readback(x)

                    def _readback(self, r):
                        return np.asarray(r)
            """}, "jax-hotpath")
        assert len(got) == 1 and "asarray" in got[0].message

    def test_nested_step_closure_fires(self, tmp_path):
        # closures handed to the dispatcher execute on the path
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                import jax

                class Scorer:
                    async def score(self, x):
                        def step(staging):
                            return jax.device_put(staging, self.dev)
                        return await self.dispatcher.dispatch(x, step)
            """}, "jax-hotpath")
        assert len(got) == 1 and "device_put" in got[0].message

    def test_off_path_device_put_is_clean(self, tmp_path):
        # placement during init/restore is not the dispatch path
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                import jax

                class Scorer:
                    def restore(self, snap):
                        self.params = jax.device_put(snap.params, self.dev)

                    def _place_norm(self):
                        self.mu_d = jax.device_put(self.mu, self.dev)
            """}, "jax-hotpath")
        assert got == []

    def test_out_of_scope_package_is_ignored(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import jax
                async def score(x):
                    return jax.device_put(x, None)
            """}, "jax-hotpath")
        assert got == []

    def test_weight_export_root_fires_in_lifecycle(self, tmp_path):
        # the native weight export must stay host-side numpy on an
        # already-gathered snapshot: a readback inside it (or a helper
        # it calls) fires
        got = findings_of(tmp_path, {
            "linkerd_tpu/lifecycle/x.py": """
                import numpy as np

                def export_weight_blob(snap, version):
                    return _pack(snap.params)

                def _pack(params):
                    return np.asarray(params["w"]).tobytes()
            """}, "jax-hotpath")
        assert len(got) == 1 and "asarray" in got[0].message

    def test_native_publish_root_fires(self, tmp_path):
        # the in-data-plane tier's per-batch board publish is a root: a
        # device barrier there would put the old per-batch latency back
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                import jax

                class Tele:
                    def _publish_native_batch(self, ns):
                        jax.block_until_ready(ns["scores"])
            """}, "jax-hotpath")
        assert len(got) == 1 and "block_until_ready" in got[0].message

    def test_justified_suppression_suppresses(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/telemetry/x.py": """
                import numpy as np
                async def score(x):
                    return np.asarray(x, np.float32)  # l5d: ignore[jax-hotpath] — host dtype cast, not a readback
            """}, "jax-hotpath")
        assert len(got) == 1 and got[0].suppressed

    def test_real_tree_dispatch_path_is_clean(self):
        # the contract the rule exists to keep: the shipped score
        # dispatch path has no unsuppressed per-call seams
        out = run_analysis(["linkerd_tpu"], repo_root=REPO,
                           rules=["jax-hotpath"])
        unsuppressed = [f for f in out if not f.suppressed]
        assert unsuppressed == [], "\n" + "\n".join(
            f.show() for f in unsuppressed)


class TestRepoGate:
    """The tier-1 gate: the suite itself over the real tree."""

    def test_rule_inventory(self):
        assert sorted(rule_ids()) == [
            "async-blocking", "config-registry", "float-time",
            "jax-hotpath", "jax-purity", "metrics-scope",
            "stream-release", "swallowed-exception", "task-leak",
        ]

    def test_repo_has_zero_unsuppressed_findings(self):
        out = run_analysis(["linkerd_tpu"], repo_root=REPO)
        unsuppressed = [f for f in out if not f.suppressed]
        assert unsuppressed == [], "\n" + "\n".join(
            f.show() for f in unsuppressed)

    def test_every_repo_suppression_is_justified(self):
        # run_analysis already enforces this via the meta-rule; assert
        # the invariant directly so the intent is explicit in the gate
        out = run_analysis(["linkerd_tpu"], repo_root=REPO)
        for f in out:
            if f.suppressed:
                assert f.justification.strip(), f.show()
