"""In-data-plane scoring tests: the native C++ scorer evaluated inside
the fastpath engines (native/scorer.h + lifecycle/export.py).

The contracts under test (COMPONENTS.md §2.14):

- blob format: export_weight_blob <-> l5dscore::parse_blob stay in
  lockstep — a real JAX snapshot exports, parses, and validates; any
  corruption (magic, CRC, truncation, geometry) is a rejected publish,
  never silently-wrong scores;
- score parity: the native f32 evaluator matches the JAX reference
  within float tolerance, and int8 quantization stays inside its error
  bound — the parity gate for serving the distilled model in-engine;
- featurizer parity: the C featurizer and the Python
  NativeFeaturizer.encode_block produce identical features for the
  same raw rows and drift state;
- hot-swap: concurrent publish + score never yields torn weights (the
  slab's reader-recheck protocol: every observed score matches one of
  the published models exactly);
- tiering: pre-scored engine rows skip the JAX dispatch but still feed
  the board/training; unscored rows (no blob) fall back to JAX.
"""

import asyncio
import threading

import numpy as np
import pytest

from linkerd_tpu.lifecycle.export import blob_meta, export_weight_blob
from linkerd_tpu.telemetry.anomaly import (
    FeatureVector, InProcessScorer, JaxAnomalyConfig, JaxAnomalyTelemeter,
)
from linkerd_tpu.telemetry.linerate import (
    NATIVE_COL_SCORE, NATIVE_COL_SCORED, NATIVE_ROW_WIDTH, NativeFeaturizer,
)
from linkerd_tpu.telemetry.metrics import MetricsTree

native = pytest.importorskip("linkerd_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


def _trained_snapshot(seed: int = 3, steps: int = 6):
    """A snapshot with non-trivial weights + normalization stats: a few
    real fit steps so mu/var initialize and params move off init."""
    async def go():
        scorer = InProcessScorer(seed=seed, learning_rate=5e-3)
        rng = np.random.default_rng(seed)
        try:
            for _ in range(steps):
                x = rng.standard_normal(
                    (32, scorer.cfg.in_dim)).astype(np.float32) * 2.0 + 1.0
                labels = (rng.random(32) > 0.8).astype(np.float32)
                await scorer.fit(x, labels, np.ones(32, np.float32))
            return scorer.snapshot()
        finally:
            scorer.close()

    return run(go())


def _numpy_reference(snap, x: np.ndarray) -> np.ndarray:
    """Pure-numpy f32 forward pass with the exact serving semantics:
    normalize -> enc (relu all) -> dec (relu except last) + cls head
    from the bottleneck -> tanh/sigmoid blend, recon error vs the
    NORMALIZED input."""
    xn = (x - snap.mu) / np.sqrt(snap.var + 1e-2)
    xn = xn.astype(np.float32)

    def dense_chain(layers, h, final_act):
        n = len(layers)
        for i, layer in enumerate(layers):
            h = h @ layer["w"].astype(np.float32) \
                + layer["b"].astype(np.float32)
            if final_act or i < n - 1:
                h = np.maximum(h, 0.0)
        return h

    z = dense_chain(snap.params["enc"], xn, final_act=True)
    recon = dense_chain(snap.params["dec"], z, final_act=False)
    logits = dense_chain(snap.params["cls"], z, final_act=False)[:, 0]
    err = np.mean((recon - xn) ** 2, axis=1)
    rw = float(snap.cfg.recon_weight)
    return (rw * np.tanh(err)
            + (1.0 - rw) / (1.0 + np.exp(-logits))).astype(np.float32)


class TestBlobFormat:
    def test_export_parses_and_roundtrips_meta(self):
        snap = _trained_snapshot()
        blob = export_weight_blob(snap, version=42, quant="f32")
        meta = blob_meta(blob)
        assert meta is not None
        assert meta["version"] == 42 and meta["quant"] == "f32"
        assert meta["in_dim"] == snap.mu.shape[0]
        # the C parser agrees with the Python header reader
        info = native.score_blob_info(blob)
        assert info["version"] == 42 and info["crc"] == meta["crc"]
        assert info["in_dim"] == meta["in_dim"]
        assert info["n_enc"] + info["n_dec"] + info["n_cls"] \
            == meta["layers"]

    def test_int8_blob_is_smaller_and_valid(self):
        snap = _trained_snapshot()
        f32 = export_weight_blob(snap, version=1, quant="f32")
        i8 = export_weight_blob(snap, version=1, quant="int8")
        assert len(i8) < len(f32) * 0.5  # ~4x on the weight payload
        assert native.score_blob_info(i8)["quant"] == 1

    def test_corruption_is_rejected_not_served(self):
        snap = _trained_snapshot()
        blob = bytearray(export_weight_blob(snap, version=1))
        # flipped weight byte: CRC catches it
        flipped = bytearray(blob)
        flipped[len(flipped) // 2] ^= 0x40
        with pytest.raises(ValueError, match="crc"):
            native.score_blob_info(bytes(flipped))
        assert blob_meta(bytes(flipped)) is None
        # bad magic
        with pytest.raises(ValueError, match="magic"):
            native.score_blob_info(b"NOTMAGIC" + bytes(blob[8:]))
        # truncation
        with pytest.raises(ValueError):
            native.score_blob_info(bytes(blob[: len(blob) // 2]))
        # a structurally-bad but CRC-valid blob: geometry still rejects
        import struct
        import zlib
        body = bytes(blob[:-4])
        bad = bytearray(body)
        # in_dim field (offset 8 magic + 8 version/quant)
        struct.pack_into("<I", bad, 16, 9999)
        bad = bytes(bad) + struct.pack("<I", zlib.crc32(bytes(bad)))
        with pytest.raises(ValueError):
            native.score_blob_info(bad)

    def test_engine_rejects_wrong_in_dim_blob(self):
        """A valid blob whose in_dim disagrees with the engine
        featurizer must not publish (the engine would index out of
        bounds at featurize time otherwise)."""
        eng = native.FastPathEngine()
        try:
            snap = _trained_snapshot()
            ok = export_weight_blob(snap, version=1)
            eng.publish_weights(ok)  # FEATURE_DIM matches: accepted
            import struct
            import zlib
            body = bytearray(ok[:-4])
            struct.pack_into("<I", body, 16, 35)  # in_dim 36 -> 35
            bad = bytes(body) + struct.pack("<I", zlib.crc32(bytes(body)))
            with pytest.raises(ValueError):
                eng.publish_weights(bad)
        finally:
            eng.close()


class TestScoreParity:
    def test_f32_matches_numpy_reference_tight(self):
        snap = _trained_snapshot()
        blob = export_weight_blob(snap, version=1, quant="f32")
        rng = np.random.default_rng(0)
        x = rng.standard_normal((256, snap.mu.shape[0])).astype(np.float32)
        got = native.score_eval(blob, x)
        ref = _numpy_reference(snap, x)
        assert got.shape == ref.shape
        assert np.abs(got - ref).max() < 1e-5

    def test_f32_matches_jax_serving_scorer(self):
        """The end-to-end parity gate: native scores vs the REAL
        serving scorer (jitted, bf16 compute on this backend) agree
        within the compute-dtype tolerance."""
        async def go():
            scorer = InProcessScorer(seed=5, learning_rate=5e-3)
            rng = np.random.default_rng(5)
            try:
                for _ in range(4):
                    x = rng.standard_normal(
                        (32, scorer.cfg.in_dim)).astype(np.float32)
                    await scorer.fit(
                        x, np.zeros(32, np.float32),
                        np.zeros(32, np.float32))
                snap = scorer.snapshot()
                blob = export_weight_blob(snap, version=1)
                x = rng.standard_normal(
                    (128, scorer.cfg.in_dim)).astype(np.float32)
                ref = np.asarray(await scorer.score(x))
                got = native.score_eval(blob, x)
                # bf16 rounds ~3 decimal digits through the stack;
                # scores live in [0, 1]
                assert np.abs(got - ref).max() < 0.05
                assert np.abs(got - ref).mean() < 0.01
            finally:
                scorer.close()

        run(go())

    def test_int8_error_bound_vs_f32(self):
        snap = _trained_snapshot()
        f32 = export_weight_blob(snap, version=1, quant="f32")
        i8 = export_weight_blob(snap, version=1, quant="int8")
        rng = np.random.default_rng(1)
        x = rng.standard_normal((256, snap.mu.shape[0])).astype(np.float32)
        a = native.score_eval(f32, x)
        b = native.score_eval(i8, x)
        # symmetric per-output-column int8 with f32 accumulate: the
        # error is a weight-rounding effect, bounded well under the
        # anomaly thresholds the control loop actuates on (>= 0.05
        # would be actuation-visible)
        assert np.abs(a - b).max() < 0.03
        assert np.abs(a - b).mean() < 0.005

    def test_scores_are_probabilities(self):
        snap = _trained_snapshot()
        blob = export_weight_blob(snap, version=1)
        x = np.random.default_rng(2).standard_normal(
            (64, snap.mu.shape[0])).astype(np.float32) * 50.0
        got = native.score_eval(blob, x)
        assert np.isfinite(got).all()
        assert (got >= 0.0).all() and (got <= 1.0).all()


class TestFeaturizerParity:
    def test_c_features_match_python_encoder(self):
        """Same raw rows, same hash column, same drift -> bit-for-bit
        identical features from the C featurizer and the Python
        NativeFeaturizer (fresh route: drift 0 on both sides)."""
        from linkerd_tpu.models.features import path_hash_cols
        dst = "/svc/parity"
        col, sign = path_hash_cols(dst)
        rng = np.random.default_rng(3)
        n = 32
        rows = np.zeros((n, NATIVE_ROW_WIDTH), np.float32)
        rows[:, 0] = 9  # route id
        rows[:, 1] = rng.uniform(0.1, 500.0, n)      # lat_ms
        rows[:, 2] = rng.choice([200, 204, 404, 500, 503], n)
        rows[:, 3] = rng.integers(0, 1 << 16, n)     # req_b
        rows[:, 4] = rng.integers(0, 1 << 20, n)     # rsp_b
        rows[:, 5] = np.arange(n) * 0.01             # ts_s
        snap = _trained_snapshot()
        blob = export_weight_blob(snap, version=1)
        scores, feats = native.score_eval_raw(
            blob, rows, cols=np.full(n, col, np.int32),
            signs=np.full(n, sign, np.float32),
            drifts=np.zeros(n, np.float32), return_features=True)
        f = NativeFeaturizer(resolver=lambda rid: dst)
        x_py, inv, dsts = f.encode_block(rows)
        assert dsts == [dst]
        # drift col (32): the Python featurizer's FIRST block seeds the
        # EWMA (drift 0) — identical to the zero drift fed to C
        assert np.allclose(feats, x_py, atol=1e-6)
        # and the scores equal evaluating those features directly
        direct = native.score_eval(blob, feats)
        assert np.allclose(scores, direct, atol=1e-6)

    def test_c_feature_dim_matches_model_schema(self):
        from linkerd_tpu.models.features import FEATURE_DIM
        assert native.score_feature_dim() == FEATURE_DIM


class TestHotSwap:
    def test_concurrent_publish_and_score_never_torn(self):
        """The slab's reader-recheck protocol: while a publisher flips
        between two models as fast as it can, every concurrently
        observed score matches model A or model B EXACTLY — a torn
        (half-swapped) weight buffer would produce a third value."""
        blob_a = native.score_test_blob(version=1, seed=11)
        blob_b = native.score_test_blob(version=2, seed=22)
        x = np.random.default_rng(4).standard_normal(
            (1, native.score_feature_dim())).astype(np.float32)
        expect_a = float(native.score_eval(blob_a, x)[0])
        expect_b = float(native.score_eval(blob_b, x)[0])
        assert abs(expect_a - expect_b) > 1e-6  # distinct models
        slab = native.ScoreSlab()
        try:
            slab.publish(blob_a)
            stop = threading.Event()
            bad = []

            def publisher():
                flip = False
                while not stop.is_set():
                    slab.publish(blob_b if flip else blob_a)
                    flip = not flip

            def scorer_thread():
                while not stop.is_set():
                    out = slab.score(x)
                    s = float(out[0])
                    if (abs(s - expect_a) > 1e-6
                            and abs(s - expect_b) > 1e-6):
                        bad.append(s)

            threads = [threading.Thread(target=publisher)] + [
                threading.Thread(target=scorer_thread) for _ in range(3)]
            for t in threads:
                t.start()
            import time
            time.sleep(1.0)
            stop.set()
            for t in threads:
                t.join()
            stats = slab.stats()
            assert stats["swaps"] > 10  # the publisher really ran
            assert bad == [], f"torn scores observed: {bad[:5]}"
        finally:
            slab.close()

    def test_slab_stats_track_version_and_crc(self):
        slab = native.ScoreSlab()
        try:
            assert slab.score(np.zeros(
                (1, native.score_feature_dim()), np.float32)) is None
            blob = native.score_test_blob(version=9, seed=1)
            slab.publish(blob)
            st = slab.stats()
            assert st["version"] == 9 and st["swaps"] == 1
            assert st["crc"] == native.score_blob_info(blob)["crc"]
        finally:
            slab.close()

    def test_slab_guards_out_of_bounds_and_closed(self):
        """The standalone slab must fail as Python errors, never as
        native out-of-bounds reads: wrong-width score input, a valid
        blob with a different in_dim, and use-after-close all raise."""
        slab = native.ScoreSlab()
        try:
            blob = native.score_test_blob(version=1, seed=1)
            slab.publish(blob)
            with pytest.raises(ValueError, match="expected"):
                slab.score(np.zeros((2, 8), np.float32))  # engine-row w
            # valid blob, wrong in_dim: rejected by the C publish
            snap = _trained_snapshot()
            ok = export_weight_blob(snap, version=1)
            import struct
            import zlib
            body = bytearray(ok[:-4])
            struct.pack_into("<I", body, 16, 35)
            # keep geometry consistent: just assert the engine-width
            # check fires before any eval (crc recomputed so parse
            # succeeds up to the in_dim gate on a same-shape blob is
            # not constructible here — the dim gate rejects first)
            bad = bytes(body) + struct.pack(
                "<I", zlib.crc32(bytes(body)))
            with pytest.raises(ValueError):
                slab.publish(bad)
        finally:
            slab.close()
        with pytest.raises(RuntimeError, match="closed"):
            slab.score(np.zeros(
                (1, native.score_feature_dim()), np.float32))
        with pytest.raises(RuntimeError, match="closed"):
            slab.stats()


class TestEngineEndToEnd:
    def test_engine_scores_all_requests_in_data_plane(self):
        """Real loopback traffic through the h1 engine: with a blob
        published and the route feature pushed, 100% of drained rows
        arrive pre-scored, the score matches an out-of-band evaluation
        of the same blob on the same features, and the stats block
        reports the serving version/CRC."""
        snap = _trained_snapshot()
        blob = export_weight_blob(snap, version=7)

        async def go():
            eng = native.FastPathEngine()
            port = eng.listen("127.0.0.1", 0)

            async def handle(r, w):
                try:
                    while True:
                        await r.readuntil(b"\r\n\r\n")
                        w.write(b"HTTP/1.1 200 OK\r\n"
                                b"Content-Length: 2\r\n\r\nok")
                        await w.drain()
                except Exception:
                    pass

            srv = await asyncio.start_server(handle, "127.0.0.1", 0)
            bport = srv.sockets[0].getsockname()[1]
            try:
                eng.start()
                eng.set_route("svc", [("127.0.0.1", bport)])
                assert eng.set_route_feature("svc", 14, 1.0)
                assert not eng.set_route_feature("ghost", 14, 1.0)
                eng.publish_weights(blob)
                r, w = await asyncio.open_connection("127.0.0.1", port)
                rsp = b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok"
                for _ in range(25):
                    w.write(b"GET / HTTP/1.1\r\nHost: svc\r\n\r\n")
                    await w.drain()
                    await r.readexactly(len(rsp))
                w.close()
                await w.wait_closed()
                await asyncio.sleep(0.1)
                rows = eng.drain_features()
                assert rows.shape == (25, NATIVE_ROW_WIDTH)
                assert (rows[:, NATIVE_COL_SCORED] == 1.0).all()
                assert np.isfinite(rows[:, NATIVE_COL_SCORE]).all()
                st = eng.stats()["native_scorer"]
                assert st["weights"] and st["version"] == 7
                assert st["scored"] == 25 and st["unscored"] == 0
                assert st["crc"] == blob_meta(blob)["crc"]
                # scoring cost is measured per row: the ns histogram
                # holds exactly the scored count, all sub-ms (bucket
                # 20 ~= 2^20 ns = 1.05 ms)
                hist = st["score_ns_hist"]
                assert sum(hist) == 25
                assert sum(hist[:20]) == 25, f"score >1ms: {hist}"
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_rows_without_weights_fall_through_unscored(self):
        """No blob published: rows drain with scored == 0 (the JAX
        fallback tier's signal) and the stats count them unscored."""
        async def go():
            eng = native.FastPathEngine()
            port = eng.listen("127.0.0.1", 0)

            async def handle(r, w):
                try:
                    while True:
                        await r.readuntil(b"\r\n\r\n")
                        w.write(b"HTTP/1.1 200 OK\r\n"
                                b"Content-Length: 2\r\n\r\nok")
                        await w.drain()
                except Exception:
                    pass

            srv = await asyncio.start_server(handle, "127.0.0.1", 0)
            bport = srv.sockets[0].getsockname()[1]
            try:
                eng.start()
                eng.set_route("svc", [("127.0.0.1", bport)])
                eng.set_route_feature("svc", 14, 1.0)
                r, w = await asyncio.open_connection("127.0.0.1", port)
                rsp = b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok"
                for _ in range(5):
                    w.write(b"GET / HTTP/1.1\r\nHost: svc\r\n\r\n")
                    await w.drain()
                    await r.readexactly(len(rsp))
                w.close()
                await w.wait_closed()
                await asyncio.sleep(0.1)
                rows = eng.drain_features()
                assert (rows[:, NATIVE_COL_SCORED] == 0.0).all()
                st = eng.stats()["native_scorer"]
                assert not st["weights"]
                assert st["unscored"] == 5 and st["scored"] == 0
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())


class _StubJax:
    """A deterministic stand-in for the JAX tier."""

    def __init__(self, value=0.25):
        self.value = value
        self.score_calls = []
        self.fit_calls = []

    async def score(self, x):
        self.score_calls.append(np.array(x, copy=True))
        return np.full(len(x), self.value, np.float32)

    async def fit(self, x, labels, mask):
        self.fit_calls.append((np.array(x, copy=True), len(labels)))
        return 0.1

    def close(self):
        pass


def _nat_rows(n, route_id=4, score=0.9, scored=1.0):
    rows = np.zeros((n, NATIVE_ROW_WIDTH), np.float32)
    rows[:, 0] = route_id
    rows[:, 1] = 10.0
    rows[:, 2] = 200
    rows[:, NATIVE_COL_SCORE] = score
    rows[:, NATIVE_COL_SCORED] = scored
    return rows


class TestTieredTelemeter:
    def test_prescored_rows_skip_jax_and_feed_board(self):
        async def go():
            mt = MetricsTree()
            stub = _StubJax(value=0.25)
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(maxBatch=64, trainEveryBatches=0),
                mt, scorer=stub)
            tele.set_native_route_resolver(lambda rid: "/fp/nat")
            v = tele.native_ring.produce_views(4)
            v[0][:] = _nat_rows(4, score=0.9)
            tele.native_ring.commit(4)
            tele.native_committed(4)
            n = await tele.drain_once()
            assert n == 4
            # the JAX tier never saw the pre-scored rows
            assert stub.score_calls == []
            scores = tele.board.scores.sample()
            assert scores["/fp/nat"] == pytest.approx(0.9, abs=0.05)
            flat = mt.flatten()
            assert flat["anomaly/scored_total"] == 4
            assert flat["anomaly/native_scored_total"] == 4
            assert flat["anomaly/native_scored_fraction"] == 1.0
            assert flat["anomaly/scored_fraction"] == 1.0
            tele.close()

        run(go())

    def test_mixed_batch_splits_tiers(self):
        """Python rows + unscored native rows go to JAX; pre-scored
        native rows publish engine scores — one drained batch."""
        async def go():
            mt = MetricsTree()
            stub = _StubJax(value=0.25)
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(maxBatch=64, trainEveryBatches=0),
                mt, scorer=stub)
            tele.set_native_route_resolver(
                lambda rid: f"/fp/r{int(rid)}")
            tele.ring.append((FeatureVector(dst_path="/svc/py"), None))
            v = tele.native_ring.produce_views(4)
            block = np.concatenate([
                _nat_rows(2, route_id=1, score=0.9, scored=1.0),
                _nat_rows(2, route_id=2, score=0.0, scored=0.0),
            ])
            v[0][:] = block
            tele.native_ring.commit(4)
            tele.native_committed(4)
            n = await tele.drain_once()
            assert n == 5
            # JAX scored exactly python + unscored-native rows
            assert len(stub.score_calls) == 1
            assert len(stub.score_calls[0]) == 3
            scores = tele.board.scores.sample()
            assert scores["/fp/r1"] == pytest.approx(0.9, abs=0.05)
            assert scores["/fp/r2"] == pytest.approx(0.25, abs=0.05)
            flat = mt.flatten()
            assert flat["anomaly/scored_total"] == 5
            assert flat["anomaly/native_scored_total"] == 2
            tele.close()

        run(go())

    def test_mixed_batch_advances_drift_once(self):
        """A mixed scored/unscored block must advance the featurizer's
        per-route drift EWMA exactly ONCE per drain (a per-tier encode
        would double-step the baseline and compute the later subset's
        drift against an already-advanced EWMA)."""
        async def go():
            stub = _StubJax()
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(maxBatch=64, trainEveryBatches=0),
                MetricsTree(), scorer=stub)
            tele.set_native_route_resolver(lambda rid: "/fp/nat")
            block = np.concatenate([
                _nat_rows(3, route_id=4, score=0.9, scored=1.0),
                _nat_rows(3, route_id=4, score=0.0, scored=0.0),
            ])
            block[:, 1] = np.arange(6, dtype=np.float32) * 100.0
            v = tele.native_ring.produce_views(6)
            v[0][:] = block
            tele.native_ring.commit(6)
            tele.native_committed(6)
            await tele.drain_once()
            # reference: ONE single-pass encode over the same block
            ref = NativeFeaturizer(resolver=lambda rid: "/fp/nat")
            ref.encode_block(block)
            assert tele._native_featurizer.temporal._ewma \
                == ref.temporal._ewma
            # and the unscored rows' features the JAX tier saw match
            # the single-pass encoding (drift col 32 included)
            ref2 = NativeFeaturizer(resolver=lambda rid: "/fp/nat")
            x_ref, _, _ = ref2.encode_block(block)
            assert len(stub.score_calls) == 1
            assert np.array_equal(stub.score_calls[0], x_ref[3:])
            tele.close()

        run(go())

    def test_native_rows_still_train_jax_tier(self):
        """Engine-scored rows must keep feeding online training — the
        JAX model is the training tier for ALL traffic."""
        async def go():
            stub = _StubJax()
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(maxBatch=64, trainEveryBatches=1),
                MetricsTree(), scorer=stub)
            tele.set_native_route_resolver(lambda rid: "/fp/nat")
            v = tele.native_ring.produce_views(3)
            v[0][:] = _nat_rows(3, score=0.8)
            tele.native_ring.commit(3)
            tele.native_committed(3)
            await tele.drain_once()
            assert len(stub.fit_calls) == 1
            x_fit, n_labels = stub.fit_calls[0]
            assert len(x_fit) == 3 and n_labels == 3
            tele.close()

        run(go())

    def test_native_tier_survives_degraded_jax(self):
        """A dead JAX scorer flips degraded mode but engine-scored rows
        still publish — the native tier does not depend on the device
        being healthy."""
        class Dead:
            async def score(self, x):
                raise RuntimeError("device gone")

            async def fit(self, x, labels, mask):
                raise RuntimeError("device gone")

            def close(self):
                pass

        async def go():
            mt = MetricsTree()
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(maxBatch=64, trainEveryBatches=0),
                mt, scorer=Dead())
            tele.set_native_route_resolver(lambda rid: "/fp/nat")
            # a python row forces a JAX dispatch (which dies) alongside
            # the pre-scored native rows
            tele.ring.append((FeatureVector(dst_path="/svc/py"), None))
            v = tele.native_ring.produce_views(2)
            v[0][:] = _nat_rows(2, score=0.7)
            tele.native_ring.commit(2)
            tele.native_committed(2)
            n = await tele.drain_once()
            assert n == 2  # the native half landed
            assert tele.board.degraded
            assert tele.board.scores.sample()["/fp/nat"] == \
                pytest.approx(0.7, abs=0.05)
            # the failed JAX dispatch counts dropped, NOT completed —
            # and no scorer spans fire for the dropped Python item
            flat = mt.flatten()
            assert flat["anomaly/dropped_batches"] == 1
            assert flat.get("anomaly/batches", 0) == 0
            tele.close()

        run(go())


class TestWeightPublication:
    def test_refresh_exports_and_fans_out(self):
        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0), MetricsTree())
            got = []
            tele.register_weight_sink(got.append)
            assert await tele.refresh_native_weights() is True
            assert len(got) == 1
            meta = blob_meta(got[0])
            assert meta is not None and meta["quant"] == "f32"
            state = tele.native_tier_state()
            assert state["mode"] == "primary"
            assert state["blob"]["crc"] == meta["crc"]
            assert state["publishes"] == 1 and state["engines"] == 1
            # late registration replays the last blob
            late = []
            tele.register_weight_sink(late.append)
            assert late == got
            tele.close()

        run(go())

    def test_refresh_respects_native_tier_off(self):
        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0, nativeTier="off"),
                MetricsTree())
            got = []
            tele.register_weight_sink(got.append)
            assert await tele.refresh_native_weights() is False
            assert got == []
            assert tele.native_tier_state()["mode"] == "off"
            tele.close()

        run(go())

    def test_stub_scorer_without_snapshot_is_no_publish(self):
        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0), MetricsTree(),
                scorer=_StubJax())
            assert await tele.refresh_native_weights() is False
            assert tele.native_tier_state()["blob"] is None
            tele.close()

        run(go())

    def test_rejecting_sink_does_not_break_others(self):
        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0), MetricsTree())

            def bad(blob):
                raise ValueError("engine said no")

            got = []
            tele.register_weight_sink(bad)
            tele.register_weight_sink(got.append)
            assert await tele.refresh_native_weights() is True
            assert len(got) == 1
            tele.close()

        run(go())

    def test_online_training_republishes_without_lifecycle(self):
        """No lifecycle block: the ONLINE-trained model must still
        reach the engines on the nativeRefreshS cadence — the native
        tier may never serve the startup init blob forever while
        training improves only the JAX side."""
        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=1,
                                 nativeRefreshS=0.01), MetricsTree())
            got = []
            tele.register_weight_sink(got.append)
            assert await tele.refresh_native_weights() is True
            await asyncio.sleep(0.05)  # age past the refresh cadence
            tele.ring.append((FeatureVector(dst_path="/svc/py"), None))
            await tele.drain_once()  # scores + fits -> refresh task
            for _ in range(100):
                if len(got) >= 2:
                    break
                await asyncio.sleep(0.05)
            assert len(got) >= 2, "online fit never republished weights"
            tele.close()

        run(go())

    def test_int8_quant_config_exports_int8(self):
        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0,
                                 nativeQuant="int8"), MetricsTree())
            got = []
            tele.register_weight_sink(got.append)
            assert await tele.refresh_native_weights() is True
            assert blob_meta(got[0])["quant"] == "int8"
            tele.close()

        run(go())

    def test_blob_meta_rides_checkpoint_manifest(self, tmp_path):
        """The serving version's manifest entry records the exported
        blob (crc/quant/bytes): lineage from training state to the
        exact bits the engines serve."""
        from linkerd_tpu.lifecycle import LifecycleConfig

        async def go():
            lc = LifecycleConfig(directory=str(tmp_path / "ckpts"),
                                 checkpointEveryS=0)
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0, lifecycle=lc),
                MetricsTree())
            scorer = tele._ensure_scorer()
            snap = await asyncio.to_thread(scorer.snapshot)
            v = tele.lifecycle.store.save(snap, status="promoted")
            tele.lifecycle.serving_version = v
            got = []
            tele.register_weight_sink(got.append)
            assert await tele.refresh_native_weights() is True
            meta = blob_meta(got[0])
            assert meta["version"] == v  # blob stamped with the ckpt
            entry = next(e for e in tele.lifecycle.store.versions()
                         if e.version == v)
            assert entry.native_blob is not None
            assert entry.native_blob["crc"] == meta["crc"]
            # the manifest survives a reload with the annotation
            from linkerd_tpu.lifecycle import CheckpointStore
            store2 = CheckpointStore(str(tmp_path / "ckpts"))
            entry2 = next(e for e in store2.versions()
                          if e.version == v)
            assert entry2.native_blob == entry.native_blob
            tele.close()

        run(go())

    def test_config_validation(self):
        with pytest.raises(ValueError, match="nativeTier"):
            JaxAnomalyTelemeter(
                JaxAnomalyConfig(nativeTier="sometimes"), MetricsTree())
        with pytest.raises(ValueError, match="nativeQuant"):
            JaxAnomalyTelemeter(
                JaxAnomalyConfig(nativeQuant="fp4"), MetricsTree())


class TestControllerWiring:
    def test_controller_pushes_route_feature_and_weights(self):
        """The FastPathController registers the engine as a weight sink
        at start() and pushes the dst-path hash after set_route — the
        stub engine records both."""
        from linkerd_tpu.core import Dtab, Path
        from linkerd_tpu.models.features import path_hash_cols
        from linkerd_tpu.router.fastpath import FastPathController

        class StubEngine:
            def __init__(self):
                self.features = {}
                self.blobs = []

            def start(self):
                pass

            def set_route(self, host, eps):
                pass

            def set_route_feature(self, host, col, sign):
                self.features[host] = (col, sign)
                return True

            def publish_weights(self, blob):
                self.blobs.append(blob)

            def close(self):
                pass

        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0), MetricsTree())
            eng = StubEngine()
            ctl = FastPathController(
                eng, interpreter=None, base_dtab=Dtab.read(""),
                prefix=Path.read("/svc"), label="fp",
                metrics=MetricsTree(), telemeters=[tele])
            # a blob published BEFORE start() replays at registration
            assert await tele.refresh_native_weights() is True
            await ctl.start()
            assert len(eng.blobs) == 1
            ctl.push_route_feature("web")
            assert eng.features["web"] == path_hash_cols("/svc/web")
            await ctl.close()
            # close() unregistered the sink: a later promote must not
            # call into the (freed, in the real engine) publish hook
            assert await tele.refresh_native_weights() is True
            assert len(eng.blobs) == 1
            tele.close()

        run(go())

    def test_model_json_surfaces_native_tier(self):
        async def go():
            tele = JaxAnomalyTelemeter(
                JaxAnomalyConfig(trainEveryBatches=0), MetricsTree())
            await tele.refresh_native_weights()
            handlers = dict(tele.admin_handlers())
            rsp = await handlers["/model.json"](None)
            import json
            body = json.loads(rsp.body.decode())
            nt = body["native_tier"]
            assert nt["mode"] == "primary"
            assert nt["blob"]["version"] >= 0
            assert "native_scored_fraction" in nt
            tele.close()

        run(go())
