"""HTTP/2 stack: hpack, streams, flow control, e2e, curl interop.

Reference parity: finagle/h2 tests + router/h2 e2e
(FlowControlEndToEndTest, ConcurrentStreamsEndToEndTest,
LargeStreamEndToEndTest styles).
"""

import asyncio
import shutil
import subprocess

import pytest

from linkerd_tpu.protocol.h2 import hpack
from linkerd_tpu.protocol.h2.client import H2Client
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response, Headers
from linkerd_tpu.protocol.h2.server import serve_h2
from linkerd_tpu.protocol.h2.stream import (
    BufferedStream, DataFrame, H2Stream, StreamReset, Trailers, stream_of,
)
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


class TestHpack:
    def test_roundtrip_with_dynamic_table(self):
        enc, dec = hpack.Encoder(), hpack.Decoder()
        hs = [(":method", "POST"), (":path", "/x/y"), (":scheme", "https"),
              (":authority", "svc.local"), ("x-custom", "v1"),
              ("cookie", "secret=1")]
        first = enc.encode(hs)
        assert dec.decode(first) == hs
        second = enc.encode(hs)
        assert len(second) < len(first)
        assert dec.decode(second) == hs

    def test_huffman_all_bytes(self):
        data = bytes(range(256))
        assert hpack.huffman_decode(hpack.huffman_encode(data)) == data

    def test_huffman_encoding_shrinks_ascii(self):
        raw = b"www.example.com"
        assert len(hpack.huffman_encode(raw)) < len(raw)
        # RFC 7541 C.4.1 canonical vector
        assert hpack.huffman_encode(raw) == bytes.fromhex(
            "f1e3c2e5f23a6ba0ab90f4ff")

    def test_rfc_c_3_request_vectors(self):
        # RFC 7541 C.3: three requests without huffman on one connection
        dec = hpack.Decoder()
        r1 = bytes.fromhex("828684410f7777772e6578616d706c652e636f6d")
        assert dec.decode(r1) == [
            (":method", "GET"), (":scheme", "http"), (":path", "/"),
            (":authority", "www.example.com")]
        r2 = bytes.fromhex("828684be58086e6f2d6361636865")
        assert dec.decode(r2) == [
            (":method", "GET"), (":scheme", "http"), (":path", "/"),
            (":authority", "www.example.com"), ("cache-control", "no-cache")]
        r3 = bytes.fromhex(
            "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565")
        assert dec.decode(r3) == [
            (":method", "GET"), (":scheme", "https"), (":path", "/index.html"),
            (":authority", "www.example.com"), ("custom-key", "custom-value")]

    def test_table_size_update_over_settings_rejected(self):
        dec = hpack.Decoder(max_table_size=100)
        with pytest.raises(hpack.HpackError):
            dec.decode(bytes([0x3F, 0xE1, 0x1F]))  # update to 4096 > 100


class TestStreamModel:
    def test_read_all_and_release(self):
        released = []
        s = H2Stream()
        s.offer(DataFrame(b"abc", release=released.append))
        s.offer(DataFrame(b"def", eos=True, release=released.append))

        async def go():
            body, trailers = await s.read_all()
            assert body == b"abcdef"
            assert trailers is None
            assert released == [3, 3]

        run(go())

    def test_trailers(self):
        s = stream_of(b"payload", trailers=[("grpc-status", "0")])

        async def go():
            body, trailers = await s.read_all()
            assert body == b"payload"
            assert trailers.headers == [("grpc-status", "0")]

        run(go())

    def test_reset_propagates(self):
        s = H2Stream()
        s.reset(0x8, "cancelled")

        async def go():
            with pytest.raises(StreamReset):
                await s.read()

        run(go())

    def test_buffered_stream_fork_and_overflow(self):
        async def go():
            src = H2Stream()
            buf = BufferedStream(src, capacity=10)
            f1 = buf.fork()
            src.offer(DataFrame(b"12345", eos=False))
            src.offer(DataFrame(b"678", eos=True))
            b1, _ = await f1.read_all()
            assert b1 == b"12345678"
            # replay from buffer
            f2 = buf.fork()
            b2, _ = await f2.read_all()
            assert b2 == b"12345678"
            await buf.close()

            # overflow: capacity 4 < 8 bytes
            src2 = H2Stream()
            buf2 = BufferedStream(src2, capacity=4)
            g1 = buf2.fork()
            src2.offer(DataFrame(b"12345", eos=False))
            src2.offer(DataFrame(b"678", eos=True))
            bb, _ = await g1.read_all()
            assert bb == b"12345678"
            assert buf2.overflowed
            with pytest.raises(RuntimeError):
                buf2.fork()
            await buf2.close()

        run(go())


def echo_service():
    async def handler(req: H2Request) -> H2Response:
        body, _ = await req.stream.read_all()
        rsp = H2Response(status=200, body=b"echo:" + body)
        rsp.headers.set("x-method", req.method)
        rsp.headers.set("x-path", req.path)
        return rsp

    return FnService(handler)


class TestH2EndToEnd:
    def test_get_and_post_roundtrip(self):
        async def go():
            server = await serve_h2(echo_service())
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                rsp = await client(H2Request(
                    method="GET", path="/hello", authority="test"))
                body, _ = await rsp.stream.read_all()
                assert rsp.status == 200
                assert body == b"echo:"
                assert rsp.headers.get("x-path") == "/hello"

                rsp2 = await client(H2Request(
                    method="POST", path="/p", authority="test",
                    body=b"payload"))
                body2, _ = await rsp2.stream.read_all()
                assert body2 == b"echo:payload"
            finally:
                await client.close()
                await server.close()

        run(go())

    def test_concurrent_streams_multiplex(self):
        # ref: ConcurrentStreamsEndToEndTest
        async def go():
            server = await serve_h2(echo_service())
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                async def one(i: int):
                    rsp = await client(H2Request(
                        method="POST", path=f"/{i}", authority="t",
                        body=f"msg-{i}".encode()))
                    body, _ = await rsp.stream.read_all()
                    return body

                results = await asyncio.gather(*(one(i) for i in range(20)))
                assert results == [f"echo:msg-{i}".encode()
                                   for i in range(20)]
                # all multiplexed over ONE connection
                assert client._conn is not None
            finally:
                await client.close()
                await server.close()

        run(go())

    def test_large_stream_flow_control(self):
        # ref: LargeStreamEndToEndTest / FlowControlEndToEndTest: a body
        # far larger than the 64KB default window must flow once the
        # consumer releases frames.
        big = bytes(1024) * 2048  # 2MB

        async def go():
            server = await serve_h2(echo_service())
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                rsp = await client(H2Request(
                    method="POST", path="/big", authority="t", body=big))
                body, _ = await rsp.stream.read_all()
                assert body == b"echo:" + big
            finally:
                await client.close()
                await server.close()

        run(go())

    def test_streaming_response_with_trailers(self):
        async def handler(req: H2Request) -> H2Response:
            out = H2Stream()
            rsp = H2Response(status=200, stream=out)

            async def produce():
                for i in range(5):
                    out.offer(DataFrame(f"chunk{i};".encode()))
                    await asyncio.sleep(0)
                out.offer(Trailers([("grpc-status", "0")]))

            asyncio.get_running_loop().create_task(produce())
            return rsp

        async def go():
            server = await serve_h2(FnService(handler))
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                rsp = await client(H2Request(path="/s", authority="t"))
                body, trailers = await rsp.stream.read_all()
                assert body == b"chunk0;chunk1;chunk2;chunk3;chunk4;"
                assert trailers.headers == [("grpc-status", "0")]
            finally:
                await client.close()
                await server.close()

        run(go())

    def test_handler_exception_maps_to_502(self):
        async def boom(req):
            raise RuntimeError("kaboom")

        async def go():
            server = await serve_h2(FnService(boom))
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                rsp = await client(H2Request(path="/x", authority="t"))
                assert rsp.status == 502
            finally:
                await client.close()
                await server.close()

        run(go())


@pytest.mark.skipif(shutil.which("curl") is None, reason="curl not available")
class TestCurlInterop:
    """nghttp2 (curl) speaks to our server — huffman-encoded HPACK,
    real-world settings, h2c prior knowledge."""

    def test_curl_http2_prior_knowledge(self):
        async def go():
            server = await serve_h2(echo_service())
            port = server.bound_port
            try:
                proc = await asyncio.create_subprocess_exec(
                    "curl", "-sS", "--http2-prior-knowledge",
                    "-d", "hello-from-curl",
                    f"http://127.0.0.1:{port}/post-path",
                    "-w", "\n%{http_code} %{http_version}",
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.PIPE)
                out, err = await proc.communicate()
                assert proc.returncode == 0, err.decode()
                text = out.decode()
                assert "echo:hello-from-curl" in text
                assert "200 2" in text
            finally:
                await server.close()

        run(go())

    def test_curl_sequential_fresh_connections(self):
        # NB: curl 7.88 on this image returns error 16 when REUSING an h2
        # connection across URLs even against grpcio's reference server
        # (verified), so connection-reuse interop is covered by our own
        # client's multiplexing test; here each request is a fresh conn.
        async def go():
            server = await serve_h2(echo_service())
            port = server.bound_port
            try:
                for i in range(3):
                    proc = await asyncio.create_subprocess_exec(
                        "curl", "-sS", "--http2-prior-knowledge",
                        f"http://127.0.0.1:{port}/r{i}",
                        stdout=asyncio.subprocess.PIPE,
                        stderr=asyncio.subprocess.PIPE)
                    out, err = await proc.communicate()
                    assert proc.returncode == 0, err.decode()
                    assert out.decode() == f"echo:"
            finally:
                await server.close()

        run(go())


class TestHpackCacheCorrectness:
    def test_random_roundtrip_with_table_churn(self):
        """Property check for the steady-state block caches: random
        header lists (repeats, new entries, evictions, resizes) must
        round-trip encoder->decoder identically to a cache-free pair."""
        import random as _random

        from linkerd_tpu.protocol.h2 import hpack

        rng = _random.Random(42)
        enc = hpack.Encoder()
        dec = hpack.Decoder()
        names = [f"x-h{i}" for i in range(40)] + [":path", ":authority"]
        values = [f"v{i}" * rng.randint(1, 30) for i in range(60)]
        seen_lists = []
        for step in range(600):
            if seen_lists and rng.random() < 0.5:
                headers = rng.choice(seen_lists)  # repeat: cache hits
            else:
                headers = [(rng.choice(names), rng.choice(values))
                           for _ in range(rng.randint(1, 8))]
                seen_lists.append(headers)
            if rng.random() < 0.02:
                size = rng.choice([512, 1024, 4096])
                dec.set_max_table_size(size)
                enc.set_max_table_size(size)
            block = enc.encode(headers)
            got = dec.decode(block)
            want = [(n.lower(), v) for n, v in headers]
            assert got == want, (step, headers, got)

    def test_decoder_cache_bounded(self):
        from linkerd_tpu.protocol.h2 import hpack

        dec = hpack.Decoder()
        enc = hpack.Encoder()
        # only literal-never-indexed fields -> non-mutating blocks
        for i in range(hpack._CACHE_CAP + 50):
            block = enc.encode([("authorization", f"token-{i}")])
            dec.decode(block)
        assert len(dec._cache) <= hpack._CACHE_CAP
        assert dec._cache_bytes <= hpack._CACHE_MAX_BYTES


class TestClientReconnect:
    def test_reconnects_after_server_goaway(self):
        """The singleton-pool client must transparently re-establish after
        the server GOAWAYs its connection (ref: H2.scala SingletonPool
        re-establishment)."""
        async def go():
            server = await serve_h2(echo_service())
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                r1 = await client(H2Request(
                    method="POST", path="/a", authority="t", body=b"one"))
                b1, _ = await r1.stream.read_all()
                assert b1.endswith(b"one")

                # server closes every live connection (GOAWAY + FIN)
                first_conn = client._conn
                for conn in list(server._conns):
                    await conn.close()
                for _ in range(100):
                    if first_conn.is_closed:
                        break
                    await asyncio.sleep(0.01)

                r2 = await client(H2Request(
                    method="POST", path="/b", authority="t", body=b"two"))
                b2, _ = await r2.stream.read_all()
                assert b2.endswith(b"two")
                assert client._conn is not first_conn  # fresh connection
            finally:
                await client.close()
                await server.close()

        run(go())


class TestH1ToH2cUpgrade:
    """RFC 7540 §3.2 server-side upgrade: an HTTP/1.1 client sending
    ``Upgrade: h2c`` + HTTP2-Settings on the h2 port gets 101 and its
    request served as h2 stream 1 (ref ServerUpgradeHandler.scala:1-70)."""

    @staticmethod
    async def _h1_upgrade_exchange(port: int, host_hdr: str):
        """Raw curl-style client: upgrade, then read the h2 response for
        stream 1. -> (status, body, trailers_or_None)."""
        from linkerd_tpu.protocol.h2 import frames

        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            import base64
            settings = base64.urlsafe_b64encode(
                b"").decode()  # empty SETTINGS payload is legal
            writer.write(
                (f"GET /up HTTP/1.1\r\nHost: {host_hdr}\r\n"
                 f"Connection: Upgrade, HTTP2-Settings\r\n"
                 f"Upgrade: h2c\r\nHTTP2-Settings: {settings}\r\n"
                 f"\r\n").encode())
            await writer.drain()
            status_line = await reader.readline()
            assert b"101" in status_line, status_line
            while (await reader.readline()) not in (b"\r\n", b""):
                pass
            # now h2: client preface + SETTINGS
            writer.write(frames.CONNECTION_PREFACE)
            writer.write(frames.pack_settings([]))
            await writer.drain()

            dec = hpack.Decoder()
            status = None
            body = b""
            trailers = None
            while True:
                head = await reader.readexactly(9)
                fh = frames.unpack_header(head)
                payload = (await reader.readexactly(fh.length)
                           if fh.length else b"")
                if fh.type == frames.SETTINGS:
                    if not (fh.flags & frames.FLAG_ACK):
                        writer.write(frames.pack_settings([], ack=True))
                        await writer.drain()
                elif fh.type == frames.HEADERS:
                    hdrs = dec.decode(frames.strip_padding(fh.flags,
                                                           payload))
                    if status is None:
                        status = int(next(v for n, v in hdrs
                                          if n == ":status"))
                    else:
                        trailers = hdrs
                    if fh.flags & frames.FLAG_END_STREAM:
                        return status, body, trailers
                elif fh.type == frames.DATA:
                    body += frames.strip_padding(fh.flags, payload)
                    if fh.flags & frames.FLAG_END_STREAM:
                        return status, body, trailers
                elif fh.type == frames.GOAWAY:
                    raise AssertionError(f"goaway: {payload!r}")
        finally:
            writer.close()

    def test_upgrade_direct_server(self):
        async def go():
            async def handler(req: H2Request) -> H2Response:
                body, _ = await req.stream.read_all()
                return H2Response(
                    status=200,
                    body=f"{req.method} {req.path} a={req.authority}"
                         .encode())

            server = await serve_h2(FnService(handler))
            try:
                status, body, _ = await self._h1_upgrade_exchange(
                    server.bound_port, "up.test")
                assert status == 200
                assert body == b"GET /up a=up.test"
            finally:
                await server.close()

        run(go())

    def test_upgrade_with_coalesced_preface_and_body(self):
        """An eager client coalesces the upgrade request (WITH a body)
        and its h2 preface+SETTINGS into one write before reading the
        101 — the server must split body / preface / frames correctly."""
        from linkerd_tpu.protocol.h2 import frames

        async def go():
            async def handler(req: H2Request) -> H2Response:
                body, _ = await req.stream.read_all()
                return H2Response(status=200, body=b"got:" + body)

            server = await serve_h2(FnService(handler))
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.bound_port)
            try:
                body = b"PAYLOAD"
                writer.write(
                    (f"POST /up HTTP/1.1\r\nHost: t\r\n"
                     f"Connection: Upgrade, HTTP2-Settings\r\n"
                     f"Upgrade: h2c\r\nHTTP2-Settings: \r\n"
                     f"Content-Length: {len(body)}\r\n\r\n").encode()
                    + body
                    + frames.CONNECTION_PREFACE
                    + frames.pack_settings([]))
                await writer.drain()
                status_line = await reader.readline()
                assert b"101" in status_line
                while (await reader.readline()) not in (b"\r\n", b""):
                    pass
                dec = hpack.Decoder()
                status = rsp_body = None
                got_body = b""
                while True:
                    head = await asyncio.wait_for(reader.readexactly(9), 5)
                    fh = frames.unpack_header(head)
                    payload = (await reader.readexactly(fh.length)
                               if fh.length else b"")
                    if fh.type == frames.SETTINGS and not (
                            fh.flags & frames.FLAG_ACK):
                        writer.write(frames.pack_settings([], ack=True))
                        await writer.drain()
                    elif fh.type == frames.HEADERS:
                        hdrs = dec.decode(frames.strip_padding(
                            fh.flags, payload))
                        status = next(v for n, v in hdrs
                                      if n == ":status")
                    elif fh.type == frames.DATA:
                        got_body += frames.strip_padding(fh.flags, payload)
                        if fh.flags & frames.FLAG_END_STREAM:
                            break
                    elif fh.type == frames.GOAWAY:
                        raise AssertionError(f"goaway: {payload!r}")
                assert status == "200"
                assert got_body == b"got:PAYLOAD"
            finally:
                writer.close()
                await server.close()

        run(go())

    def test_non_upgrade_h1_gets_426(self):
        async def go():
            async def handler(req: H2Request) -> H2Response:
                return H2Response(status=200)

            server = await serve_h2(FnService(handler))
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", server.bound_port)
                writer.write(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
                await writer.drain()
                line = await reader.readline()
                assert b"426" in line
                writer.close()
            finally:
                await server.close()

        run(go())

    def test_upgrade_routed_through_linker(self, tmp_path):
        """curl-style h1 client upgrades on the h2 ROUTER port and its
        request routes through identify->bind->dispatch to an h2
        backend."""
        from linkerd_tpu.linker import load_linker

        async def go():
            async def handler(req: H2Request) -> H2Response:
                body, _ = await req.stream.read_all()
                return H2Response(status=200, body=b"routed-upgrade")

            backend = await serve_h2(FnService(handler))
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "upsvc").write_text(
                f"127.0.0.1 {backend.bound_port}\n")
            cfg = f"""
routers:
- protocol: h2
  label: h2up
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            try:
                status, body, _ = await self._h1_upgrade_exchange(
                    linker.routers[0].server_ports[0], "upsvc")
                assert (status, body) == (200, b"routed-upgrade")
            finally:
                await linker.close()
                await backend.close()

        run(go())
