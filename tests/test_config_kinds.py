"""Every registered config kind parses strictly from a literal config.

This is the coverage half of the l5dlint ``config-registry`` rule: each
kind below is instantiated through the strict parser from a minimal
mapping (defaults exercised), re-instantiated with its optional fields
set, and rejected when handed an unknown field. Factories (``mk``) run
for the pure-construction categories (classifiers, identifiers,
failure accrual, transformers, loggers) — anything that would open
sockets stays config-only.
"""

import dataclasses

import pytest

import linkerd_tpu.linker  # noqa: F401 — loads plugin registrations
import linkerd_tpu.namerd.config  # noqa: F401 — dtabStore + iface kinds
from linkerd_tpu.config import ConfigError, instantiate, kinds
from linkerd_tpu.config.registry import CATEGORIES, _REGISTRY

# (category, kind, overrides, safe_to_mk)
KINDS = [
    ("namer", "io.l5d.k8s.ns", {"namespace": "prod"}, False),
    ("namer", "io.l5d.k8s.external", {"port": 8001}, False),
    ("transformer", "io.l5d.localhost", {}, True),
    ("transformer", "io.l5d.specificHost", {"host": "10.0.0.9"}, True),
    ("transformer", "io.l5d.replace", {"addrs": ["127.0.0.1 9990"]}, True),
    ("transformer", "io.l5d.k8s.daemonset", {
        "namespace": "kube-system", "service": "l5d", "port": "incoming",
    }, False),
    ("dtabStore", "io.l5d.inMemory", {}, True),
    ("dtabStore", "io.l5d.etcd", {"pathPrefix": "/namerd/dtabs"}, False),
    ("h2classifier", "io.l5d.h2.nonRetryable5XX", {}, True),
    ("h2classifier", "io.l5d.h2.retryableIdempotent5XX", {}, True),
    ("h2classifier", "io.l5d.h2.grpc.alwaysRetryable", {}, True),
    ("h2classifier", "io.l5d.h2.grpc.neverRetryable", {}, True),
    ("h2classifier", "io.l5d.h2.grpc.retryableStatusCodes",
     {"retryableStatusCodes": [4, 14]}, True),
    # identifier factories take (prefix, base_dtab): config-only here
    ("h2identifier", "io.l5d.header.token", {"header": "l5d-name"}, False),
    ("h2identifier", "io.l5d.header.path", {"segments": 2}, False),
    ("identifier", "io.l5d.header.token", {"header": "l5d-name"}, False),
    ("identifier", "io.l5d.path", {"segments": 2}, False),
    ("identifier", "io.l5d.header", {"header": "my-header"}, False),
    ("logger", "io.l5d.http.debug", {"level": "INFO"}, True),
    ("classifier", "io.l5d.http.nonRetryable5XX", {}, True),
    ("classifier", "io.l5d.http.retryableRead5XX", {}, True),
    ("classifier", "io.l5d.http.allSuccessful", {}, True),
    ("classifier", "io.l5d.http.headerRetryable", {}, True),
    ("failureAccrual", "io.l5d.consecutiveFailures", {"failures": 3}, True),
    ("failureAccrual", "io.l5d.successRate",
     {"successRate": 0.9, "requests": 20}, True),
    ("failureAccrual", "io.l5d.successRateWindowed",
     {"successRate": 0.9, "window": 10}, True),
    ("telemeter", "io.l5d.influxdb", {}, False),
    ("telemeter", "io.l5d.statsd", {"prefix": "l5d"}, False),
    ("telemeter", "io.l5d.tracelog", {"sampleRate": 0.5}, False),
]


@pytest.mark.parametrize("category,kind,overrides,safe_mk", KINDS,
                         ids=[f"{c}:{k}" for c, k, _, _ in KINDS])
def test_kind_parses_strictly(category, kind, overrides, safe_mk):
    # minimal: defaults only
    cfg = instantiate(category, {"kind": kind})
    assert dataclasses.is_dataclass(cfg)
    assert cfg.kind == kind
    # with overrides: the documented fields round-trip
    cfg = instantiate(category, {"kind": kind, **overrides})
    for key, val in overrides.items():
        got = getattr(cfg, key)
        got = got if not hasattr(got, "value") else got.value  # Port et al
        assert got == val or str(got) == str(val)
    # strictness: unknown fields are rejected with the offending name
    with pytest.raises(ConfigError, match="bogusField"):
        instantiate(category, {"kind": kind, "bogusField": 1})
    if safe_mk:
        mk = getattr(cfg, "mk", None)
        if mk is not None:
            assert mk() is not None


def test_registered_categories_are_declared():
    """Every category that actually registered kinds appears in
    CATEGORIES (the inventory l5dlint cross-checks registrations
    against), and every declared category is non-empty."""
    live = {c for c, reg in _REGISTRY.items() if reg}
    # "interpreter" carries a default registration; the rest must match
    assert live <= set(CATEGORIES), live - set(CATEGORIES)
    for cat in CATEGORIES:
        assert kinds(cat), f"declared category {cat!r} has no kinds"
