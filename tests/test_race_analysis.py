"""l5drace self-tests + deterministic-interleaving regression tests.

Three layers, mirroring tests/test_static_analysis.py:

1. every race rule fires on a positive fixture and stays quiet on the
   matching negative (tiny synthetic repos under tmp_path);
2. the real tree is clean — zero unsuppressed findings over the race
   scope, every suppression justified (the tier-1 gate);
3. every race the analyzer found and we FIXED has a deterministic
   interleaving test here: the schedule that breaks the pre-fix code is
   replayed against the fixed code (linkerd_tpu/testing/schedules), so
   a regression turns the exact race back into a red test, not a flake.
"""

import asyncio
import os
import textwrap

import pytest

from linkerd_tpu.testing.schedules import (
    DeterministicScheduler, ScheduleDeadlock, access_log, clear_log,
    explore, lost_updates, track,
)
from tools.analysis import race_rule_ids
from tools.analysis.race import DEFAULT_SCOPE, run_race_analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_repo(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


def findings_of(tmp_path, files, rule):
    root = mk_repo(tmp_path, files)
    out = run_race_analysis(["linkerd_tpu"], repo_root=root, rules=[rule])
    return [f for f in out if f.rule == rule]


# ---------------------------------------------------------------------------
# 1. rule fixtures
# ---------------------------------------------------------------------------


class TestAwaitAtomicity:
    def test_torn_rmw_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                class Gauge:
                    def __init__(self):
                        self.count = 0
                    async def bump(self, svc):
                        v = self.count
                        await svc()
                        self.count = v + 1
                    def reset(self):
                        self.count = 0
            """}, "await-atomicity")
        assert len(got) == 1 and "self.count" in got[0].message
        assert "straddle" in got[0].message

    def test_lock_spanning_window_is_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                class Gauge:
                    def __init__(self):
                        self.count = 0
                        self._lock = asyncio.Lock()
                    async def bump(self, svc):
                        async with self._lock:
                            v = self.count
                            await svc()
                            self.count = v + 1
                    async def read(self):
                        async with self._lock:
                            return self.count
            """}, "await-atomicity")
        assert got == []

    def test_atomic_augassign_counters_are_clean(self, tmp_path):
        # the admission-filter idiom: each += / -= is atomic in asyncio
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                class F(Filter):
                    def __init__(self):
                        self.pending = 0
                    async def apply(self, req, service):
                        self.pending += 1
                        try:
                            return await service(req)
                        finally:
                            self.pending -= 1
            """}, "await-atomicity")
        assert got == []

    def test_reread_after_await_is_clean(self, tmp_path):
        # the sanctioned fix idiom the rule message recommends
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                class Gauge:
                    def __init__(self):
                        self.count = 0
                    async def bump(self, svc):
                        v = self.count
                        await svc()
                        v = self.count
                        self.count = v + 1
                    def reset(self):
                        self.count = 0
            """}, "await-atomicity")
        assert got == []

    def test_while_test_read_is_not_stale(self, tmp_path):
        # `while not self.closed:` re-evaluates after every await in the
        # loop — pairing it with a teardown write is a false positive
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/x.py": """
                class Loop:
                    def __init__(self):
                        self.closed = False
                    async def run(self, step):
                        while not self.closed:
                            await step()
                    async def close(self):
                        self.closed = True
            """}, "await-atomicity")
        assert got == []

    def test_stale_entry_guard_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/x.py": """
                class Client(Service):
                    def __init__(self):
                        self.closed = False
                        self.pending = 0
                    async def call(self, req, connect):
                        if self.closed:
                            raise ConnectionError("closed")
                        conn = await connect()
                        self.pending += 1
                        return conn
                    async def close(self):
                        self.closed = True
            """}, "await-atomicity")
        assert len(got) == 1 and "guard on self.closed" in got[0].message
        assert "never re-checked" in got[0].message

    def test_rechecked_guard_is_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/x.py": """
                class Client(Service):
                    def __init__(self):
                        self.closed = False
                        self.pending = 0
                    async def call(self, req, connect):
                        if self.closed:
                            raise ConnectionError("closed")
                        conn = await connect()
                        if self.closed:
                            raise ConnectionError("closed during connect")
                        self.pending += 1
                        return conn
                    async def close(self):
                        self.closed = True
            """}, "await-atomicity")
        assert got == []

    def test_out_of_scope_package_is_ignored(self, tmp_path):
        # control-plane startup code is single-task; not race scope
        got = findings_of(tmp_path, {
            "linkerd_tpu/namerd/x.py": """
                class Gauge:
                    def __init__(self):
                        self.count = 0
                    async def bump(self, svc):
                        v = self.count
                        await svc()
                        self.count = v + 1
                    def reset(self):
                        self.count = 0
            """}, "await-atomicity")
        assert got == []


class TestLockGuard:
    FILES = {
        "linkerd_tpu/protocol/x.py": """
            import asyncio
            class Conn:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self.writer = None
                async def dispatch(self, connect):
                    async with self._lock:
                        if self.writer is None:
                            self.writer = await connect()
                        return self.writer
                async def close(self):
                    self.writer = None
        """}

    def test_unguarded_write_fires(self, tmp_path):
        got = findings_of(tmp_path, self.FILES, "lock-guard")
        assert len(got) == 1
        assert "close" in got[0].message and "_lock" in got[0].message

    def test_write_under_lock_is_clean(self, tmp_path):
        files = {"linkerd_tpu/protocol/x.py":
                 self.FILES["linkerd_tpu/protocol/x.py"].replace(
                     "async def close(self):\n                    "
                     "self.writer = None",
                     "async def close(self):\n                    "
                     "async with self._lock:\n                        "
                     "self.writer = None")}
        got = findings_of(tmp_path, files, "lock-guard")
        assert got == []

    def test_helper_called_only_under_lock_is_inferred_held(self, tmp_path):
        # the _ensure_conn idiom: every call site holds the lock, so the
        # helper's writes are lock-held even without a lexical region
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/x.py": """
                import asyncio
                class Conn:
                    def __init__(self):
                        self._lock = asyncio.Lock()
                        self.writer = None
                    async def _ensure(self, connect):
                        if self.writer is None:
                            self.writer = await connect()
                    async def dispatch(self, connect):
                        async with self._lock:
                            await self._ensure(connect)
                            return self.writer
                    async def ping(self, connect):
                        async with self._lock:
                            await self._ensure(connect)
            """}, "lock-guard")
        assert got == []

    def test_sync_helper_inlined_into_async_caller(self, tmp_path):
        # close() tearing down through a sync helper is still an
        # unguarded write (the ThriftClient._teardown shape)
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/x.py": """
                import asyncio
                class Conn:
                    def __init__(self):
                        self._lock = asyncio.Lock()
                        self.writer = None
                    def _teardown(self):
                        self.writer = None
                    async def dispatch(self, connect):
                        async with self._lock:
                            if self.writer is None:
                                self.writer = await connect()
                            return self.writer
                    async def close(self):
                        self._teardown()
            """}, "lock-guard")
        assert len(got) == 1 and "via _teardown()" in got[0].message


class TestLockOrder:
    def test_ordering_cycle_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                class Pair:
                    def __init__(self):
                        self._alock = asyncio.Lock()
                        self._block = asyncio.Lock()
                    async def ab(self):
                        async with self._alock:
                            async with self._block:
                                return 1
                    async def ba(self):
                        async with self._block:
                            async with self._alock:
                                return 2
            """}, "lock-order")
        assert len(got) == 1 and "deadlock" in got[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                class Pair:
                    def __init__(self):
                        self._alock = asyncio.Lock()
                        self._block = asyncio.Lock()
                    async def ab(self):
                        async with self._alock:
                            async with self._block:
                                return 1
                    async def ab2(self):
                        async with self._alock:
                            async with self._block:
                                return 2
            """}, "lock-order")
        assert got == []


class TestLockRelease:
    def test_acquire_without_release_fires(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                class Q:
                    def __init__(self):
                        self._sem = asyncio.Semaphore(1)
                    async def take(self):
                        await self._sem.acquire()
                        return 1
            """}, "lock-release")
        assert len(got) == 1 and "acquire()" in got[0].message

    def test_finally_release_is_clean(self, tmp_path):
        got = findings_of(tmp_path, {
            "linkerd_tpu/router/x.py": """
                import asyncio
                class Q:
                    def __init__(self):
                        self._sem = asyncio.Semaphore(1)
                    async def take(self, fn):
                        await self._sem.acquire()
                        try:
                            return await fn()
                        finally:
                            self._sem.release()
            """}, "lock-release")
        assert got == []

    def test_cross_method_release_is_trusted(self, tmp_path):
        # the connection-pool checkout/checkin shape
        got = findings_of(tmp_path, {
            "linkerd_tpu/protocol/x.py": """
                import asyncio
                class Pool:
                    def __init__(self):
                        self._sem = asyncio.Semaphore(4)
                    async def checkout(self):
                        await self._sem.acquire()
                        return object()
                    def checkin(self, conn):
                        self._sem.release()
            """}, "lock-release")
        assert got == []


class TestRaceSuppressions:
    RACY = """
        class Gauge:
            def __init__(self):
                self.count = 0
            async def bump(self, svc):
                v = self.count
                await svc()
                self.count = v + 1  {comment}
            def reset(self):
                self.count = 0
    """

    def test_justified_suppression_suppresses(self, tmp_path):
        root = mk_repo(tmp_path, {"linkerd_tpu/router/x.py":
                                  self.RACY.format(
            comment="# l5d: ignore[await-atomicity] — single-task by "
                    "construction here")})
        out = run_race_analysis(["linkerd_tpu"], repo_root=root)
        hits = [f for f in out if f.rule == "await-atomicity"]
        assert len(hits) == 1 and hits[0].suppressed
        assert "single-task" in hits[0].justification

    def test_unjustified_suppression_does_not_suppress(self, tmp_path):
        # ...and the lint suite's meta-rule reports the bare ignore
        from tools.analysis import run_analysis
        root = mk_repo(tmp_path, {"linkerd_tpu/router/x.py":
                                  self.RACY.format(
            comment="# l5d: ignore[await-atomicity]")})
        out = run_race_analysis(["linkerd_tpu"], repo_root=root)
        hits = [f for f in out if f.rule == "await-atomicity"]
        assert len(hits) == 1 and not hits[0].suppressed
        lint = run_analysis(["linkerd_tpu"], repo_root=root)
        sup = [f for f in lint if f.rule == "suppression"]
        assert len(sup) == 1 and "justification" in sup[0].message

    def test_race_rule_names_are_known_to_lint_meta_rule(self, tmp_path):
        # race suppressions live in the same .py files lint scans; their
        # rule ids must not be reported as unknown
        from tools.analysis import run_analysis
        root = mk_repo(tmp_path, {"linkerd_tpu/router/x.py":
                                  self.RACY.format(
            comment="# l5d: ignore[await-atomicity] — justified")})
        lint = run_analysis(["linkerd_tpu"], repo_root=root)
        assert [f for f in lint if f.rule == "suppression"] == []


class TestRaceCLI:
    def test_rule_inventory(self):
        assert race_rule_ids() == [
            "await-atomicity", "lock-guard", "lock-order", "lock-release",
        ]

    def test_cli_clean_tree_exits_zero(self, capsys):
        from tools.analysis.__main__ import main
        assert main(["race"]) == 0
        assert "l5drace" in capsys.readouterr().out

    def test_cli_json_format(self, capsys):
        import json
        from tools.analysis.__main__ import main
        assert main(["race", "--format", "json"]) == 0
        blob = json.loads(capsys.readouterr().out)
        assert blob["mode"] == "race"
        assert blob["unsuppressed"] == []
        assert blob["suppressed_count"] >= 1

    def test_cli_unknown_rule_is_usage_error(self):
        from tools.analysis.__main__ import main
        assert main(["race", "--rule", "no-such-rule"]) == 2


class TestRepoGate:
    """The tier-1 gate: the race suite over the real tree."""

    def test_repo_has_zero_unsuppressed_findings(self):
        out = run_race_analysis(list(DEFAULT_SCOPE), repo_root=REPO)
        unsuppressed = [f for f in out if not f.suppressed]
        assert unsuppressed == [], "\n" + "\n".join(
            f.show() for f in unsuppressed)

    def test_every_race_suppression_is_justified(self):
        out = run_race_analysis(list(DEFAULT_SCOPE), repo_root=REPO)
        suppressed = [f for f in out if f.suppressed]
        assert suppressed, "expected the documented benign findings"
        for f in suppressed:
            assert f.justification.strip(), f.show()


# ---------------------------------------------------------------------------
# 2. the deterministic scheduler + sanitizer themselves
# ---------------------------------------------------------------------------


class Counter:
    def __init__(self):
        self.value = 0


class TestScheduler:
    def test_reproduces_torn_rmw_and_sanitizer_flags_it(self):
        def mk(sched):
            c = Counter()
            clear_log()
            track(c, ["value"])

            async def bump(tag):
                v = c.value
                await sched.point(tag)
                c.value = v + 1
            return c, [bump("a"), bump("b")]

        # every schedule loses one update: both tasks read before either
        # writes (they park between read and write)
        sched = DeterministicScheduler(order=["a", "b"])
        c, coros = mk(sched)
        sched.run_sync(*coros)
        assert c.value == 1  # not 2: the lost update, deterministically
        assert sched.history == ["a", "b"]
        assert lost_updates("value"), "sanitizer missed the torn RMW"

    def test_explicit_order_replays_exactly(self):
        seen = []

        async def step(sched, tag):
            await sched.point(tag)
            seen.append(tag)

        sched = DeterministicScheduler(order=["c", "a", "b"])
        sched.run_sync(step(sched, "a"), step(sched, "b"),
                       step(sched, "c"))
        assert seen == ["c", "a", "b"]

    def test_seeded_runs_are_reproducible(self):
        def run(seed):
            sched = DeterministicScheduler(seed=seed)

            async def step(tag):
                await sched.point(tag)
            sched.run_sync(step("a"), step("b"), step("c"))
            return sched.history

        assert run(7) == run(7)

    def test_deadlock_is_reported_not_hung(self):
        async def wedged():
            await asyncio.get_running_loop().create_future()

        sched = DeterministicScheduler()
        with pytest.raises(ScheduleDeadlock):
            sched.run_sync(wedged(), timeout=0.1)

    def test_atomic_counters_show_no_lost_updates(self):
        # negative control for the sanitizer: += with no await between
        # read and write never tears, under any schedule
        def mk(sched):
            c = Counter()
            clear_log()
            track(c, ["value"])

            async def bump(tag):
                await sched.point(tag)
                c.value += 1
            return [bump("a"), bump("b")]

        def invariant(_results):
            assert lost_updates("value") == []

        assert explore(mk, invariant, seeds=range(8)) is None


# ---------------------------------------------------------------------------
# 3. interleaving regressions for the fixed races
# ---------------------------------------------------------------------------


class FakeTransport:
    def get_write_buffer_size(self):
        return 0


class FakeWriter:
    def __init__(self):
        self.closed = False
        self.transport = FakeTransport()
        self.reader = None        # EOF'd on close, like a real transport
        self.drain_forever = False  # simulate a peer that stopped reading
        self._drain_fut = None

    def is_closing(self):
        return self.closed

    def close(self):
        self.closed = True
        if self.reader is not None and not self.reader.at_eof():
            self.reader.feed_eof()
        if self._drain_fut is not None and not self._drain_fut.done():
            # closing the transport aborts parked drain() waiters
            self._drain_fut.set_exception(
                ConnectionResetError("transport closed"))

    def write(self, data):
        pass

    async def drain(self):
        if self.drain_forever and not self.closed:
            self._drain_fut = asyncio.get_running_loop().create_future()
            await self._drain_fut


class GatedConnect:
    """Monkeypatches asyncio.open_connection with a scheduler-gated fake.
    Closing a writer feeds EOF to its reader (as a real transport
    teardown does), so reads wedged on a dead connection fail over."""

    def __init__(self, sched, reader_bytes=b"", wedge_drain=False):
        self.sched = sched
        self.reader_bytes = reader_bytes
        self.wedge_drain = wedge_drain
        self.writers = []
        self._orig = None

    async def _open(self, host, port, **kw):
        await self.sched.point("connect")
        reader = asyncio.StreamReader()
        if self.reader_bytes:
            reader.feed_data(self.reader_bytes)
        writer = FakeWriter()
        writer.reader = reader
        if self.wedge_drain:
            writer.drain_forever = True
        self.writers.append(writer)
        await self.sched.point("connect-done")
        return reader, writer

    def __enter__(self):
        self._orig = asyncio.open_connection
        asyncio.open_connection = self._open
        return self

    def __exit__(self, *exc):
        asyncio.open_connection = self._orig


class TestHttpClientCloseRace:
    """await-atomicity @ protocol/http/client.py __call__: close() lands
    between the entry guard and the checkout — pre-fix, the request
    dispatched on the closed client and the fresh socket leaked."""

    def test_close_between_guard_and_checkout(self):
        from linkerd_tpu.protocol.http.client import HttpClient
        from linkerd_tpu.protocol.http.message import Request

        async def main():
            sched = DeterministicScheduler(
                order=["connect", "close", "connect-done"])
            with GatedConnect(
                    sched,
                    reader_bytes=b"HTTP/1.1 200 OK\r\n"
                                 b"content-length: 0\r\n\r\n") as gc:
                client = HttpClient("127.0.0.1", 1)

                async def caller():
                    try:
                        await client(Request(method="GET", uri="/"))
                    except ConnectionError:
                        return "refused"
                    return "dispatched"

                async def closer():
                    await sched.point("close")
                    await client.close()

                results = await sched.run(caller(), closer(), timeout=1.0)
                assert results[0] == "refused", (
                    f"request rode a closed client: {results[0]}")
                assert gc.writers and gc.writers[0].closed, (
                    "connection leaked past close()")

        asyncio.run(main())


class TestH2ClientCloseRace:
    """await-atomicity @ protocol/h2/client.py _get_conn/__call__: the
    singleton connect finishing after close() cached a live connection
    (read loop and all) on a dead client — pre-fix it leaked forever."""

    def test_close_during_handshake(self):
        from linkerd_tpu.protocol.h2.client import H2Client
        from linkerd_tpu.protocol.h2.messages import H2Request

        async def main():
            sched = DeterministicScheduler(
                order=["connect", "close", "connect-done"])
            with GatedConnect(sched) as gc:
                client = H2Client("127.0.0.1", 1)

                async def caller():
                    try:
                        await client(H2Request(method="GET", path="/",
                                               authority="t"))
                    except ConnectionError:
                        return "refused"
                    return "dispatched"

                async def closer():
                    await sched.point("close")
                    await client.close()

                results = await sched.run(caller(), closer(), timeout=1.0)
                assert results[0] == "refused", (
                    f"request rode a closed h2 client: {results[0]}")
                assert client._conn is None, "dead client cached a conn"
                assert gc.writers and gc.writers[0].closed, (
                    "h2 connection (and its read loop) leaked past close()")

        asyncio.run(main())


class TestMuxClientCloseRace:
    """lock-guard @ protocol/mux/client.py close(): teardown ran outside
    _lock, so a dispatch parked in _ensure_conn reconnected AFTER the
    teardown — a leaked socket + read loop on a closed client."""

    def test_close_during_connect(self):
        from linkerd_tpu.protocol.mux.client import MuxClient

        async def main():
            sched = DeterministicScheduler(
                order=["close", "connect", "connect-done"])
            with GatedConnect(sched) as gc:
                client = MuxClient("127.0.0.1", 1)

                async def caller():
                    try:
                        await client.ping()
                    except ConnectionError:
                        return "refused"
                    return "ok"

                async def closer():
                    await sched.point("close")
                    await client.close()

                results = await sched.run(caller(), closer(), timeout=1.0)
                assert isinstance(results[0], str), results[0]
                assert client._writer is None, (
                    "reconnect leaked a writer past close()")
                assert all(w.closed for w in gc.writers), (
                    "mux socket leaked past close()")

        asyncio.run(main())


class TestThriftClientCloseRace:
    """lock-guard @ protocol/thrift/client.py close(): same shape as mux
    — teardown outside the exchange lock let a queued exchange
    reconnect after close()."""

    def test_close_during_connect(self):
        from linkerd_tpu.protocol.thrift.client import ThriftClient
        from linkerd_tpu.protocol.thrift.codec import ONEWAY, ThriftCall

        async def main():
            sched = DeterministicScheduler(
                order=["close", "connect", "connect-done"])
            with GatedConnect(sched) as gc:
                client = ThriftClient("127.0.0.1", 1)
                call = ThriftCall(payload=b"x", name="m", seqid=1,
                                  type=ONEWAY)

                async def caller():
                    try:
                        await client(call)
                    except ConnectionError:
                        return "refused"
                    return "ok"

                async def closer():
                    await sched.point("close")
                    await client.close()

                results = await sched.run(caller(), closer(), timeout=1.0)
                assert isinstance(results[0], str), results[0]
                assert client._writer is None, (
                    "reconnect leaked a writer past close()")
                assert all(w.closed for w in gc.writers), (
                    "thrift socket leaked past close()")
                # and once closed, no silent reconnect ever again
                with pytest.raises(ConnectionError):
                    await client(call)

        asyncio.run(main())


class TestCloseNeverHangs:
    """The lock-based close fixes must not trade the reconnect race for
    a close-that-hangs: a wedged in-flight exchange (blackholed reply,
    peer that stopped reading) holds the exchange lock indefinitely, so
    close() pokes the transport BEFORE waiting for the lock."""

    def test_thrift_close_breaks_a_blackholed_exchange(self):
        from linkerd_tpu.protocol.thrift.client import ThriftClient
        from linkerd_tpu.protocol.thrift.codec import CALL, ThriftCall

        async def main():
            sched = DeterministicScheduler(
                order=["connect", "connect-done", "close"])
            with GatedConnect(sched) as gc:  # reply never arrives
                client = ThriftClient("127.0.0.1", 1)
                call = ThriftCall(payload=b"x", name="m", seqid=1,
                                  type=CALL)

                async def caller():
                    try:
                        await client(call)
                    except ConnectionError:
                        return "failed-fast"
                    return "ok"

                async def closer():
                    await sched.point("close")
                    await client.close()
                    return "closed"

                results = await sched.run(caller(), closer(), timeout=1.0)
                assert results[1] == "closed", (
                    f"close() hung behind the wedged exchange: "
                    f"{results[1]!r}")
                assert results[0] == "failed-fast", results[0]
                assert all(w.closed for w in gc.writers)

        asyncio.run(main())

    def test_mux_close_breaks_a_wedged_drain(self):
        from linkerd_tpu.protocol.mux.client import MuxClient

        async def main():
            sched = DeterministicScheduler(
                order=["connect", "connect-done", "close"])
            with GatedConnect(sched, wedge_drain=True) as gc:
                client = MuxClient("127.0.0.1", 1)

                async def caller():
                    try:
                        await client.ping()
                    except (ConnectionError, ConnectionResetError):
                        return "failed-fast"
                    return "ok"

                async def closer():
                    await sched.point("close")
                    await client.close()
                    return "closed"

                results = await sched.run(caller(), closer(), timeout=1.0)
                assert results[1] == "closed", (
                    f"close() hung behind the wedged drain: "
                    f"{results[1]!r}")
                assert results[0] == "failed-fast", results[0]
                assert all(w.closed for w in gc.writers)

        asyncio.run(main())


    def test_thrift_close_mid_connect_never_wedges(self):
        # close lands BETWEEN connect start and finish: the exchange
        # must abandon its fresh socket instead of dispatching on the
        # closed client (which would wedge close() behind the lock)
        from linkerd_tpu.protocol.thrift.client import ThriftClient
        from linkerd_tpu.protocol.thrift.codec import CALL, ThriftCall

        async def main():
            sched = DeterministicScheduler(
                order=["connect", "close", "connect-done"])
            with GatedConnect(sched) as gc:  # reply would never arrive
                client = ThriftClient("127.0.0.1", 1)
                call = ThriftCall(payload=b"x", name="m", seqid=1,
                                  type=CALL)

                async def caller():
                    try:
                        await client(call)
                    except ConnectionError:
                        return "refused"
                    return "ok"

                async def closer():
                    await sched.point("close")
                    await client.close()
                    return "closed"

                results = await sched.run(caller(), closer(), timeout=1.0)
                assert results == ["refused", "closed"], results
                assert all(w.closed for w in gc.writers)
                assert client._writer is None

        asyncio.run(main())

    def test_mux_close_mid_connect_never_wedges(self):
        from linkerd_tpu.protocol.mux.client import MuxClient

        async def main():
            sched = DeterministicScheduler(
                order=["connect", "close", "connect-done"])
            with GatedConnect(sched, wedge_drain=True) as gc:
                client = MuxClient("127.0.0.1", 1)

                async def caller():
                    try:
                        await client.ping()
                    except (ConnectionError, ConnectionResetError):
                        return "refused"
                    return "ok"

                async def closer():
                    await sched.point("close")
                    await client.close()
                    return "closed"

                results = await sched.run(caller(), closer(), timeout=1.0)
                assert results == ["refused", "closed"], results
                assert all(w.closed for w in gc.writers)
                assert client._writer is None

        asyncio.run(main())


class TestLifecycleLockRaces:
    """lock-guard @ lifecycle/promote.py bootstrap()/checkpoint(): both
    ran outside the cycle lock. Pre-fix, a checkpoint taken while a
    bootstrap restore was in flight recorded the STALE serving version
    as its parent — corrupted lineage in the store."""

    @staticmethod
    def _mk_snap(step):
        import numpy as np
        from linkerd_tpu.lifecycle.store import ModelSnapshot
        from linkerd_tpu.models.anomaly import AnomalyModelConfig
        return ModelSnapshot(
            params={"w": np.zeros((2, 2), np.float32)},
            opt_leaves=[np.zeros(2, np.float32)],
            mu=np.zeros(4, np.float32), var=np.ones(4, np.float32),
            norm_initialized=False, step=step,
            cfg=AnomalyModelConfig())

    def test_checkpoint_parent_is_never_stale(self, tmp_path):
        from linkerd_tpu.lifecycle.promote import (
            ModelLifecycleManager, PromotionGate, ReplayWindow,
        )
        from linkerd_tpu.lifecycle.store import CheckpointStore

        mk_snap = self._mk_snap
        import itertools
        store_ids = itertools.count()  # id(sched) is reusable after GC

        def mk(sched):
            store = CheckpointStore(str(tmp_path / f"s{next(store_ids)}"))
            v1 = store.save(mk_snap(1), status="promoted")
            mgr = ModelLifecycleManager(store, PromotionGate(),
                                        ReplayWindow())
            assert mgr.serving_version == v1
            # a peer promotes v2 out from under this manager (the
            # fleet-distribution path): latest_good moves past serving
            v2 = store.save(mk_snap(2), status="promoted", parent=v1)

            class GatedScorer:
                async def snapshot(self):
                    await sched.point("snapshot")
                    return mk_snap(7)

                async def restore(self, snap):
                    await sched.point("restore")
                    self.restored = snap.step

            scorer = GatedScorer()

            async def check_invariant():
                await sched.run(mgr.bootstrap(scorer),
                                mgr.checkpoint(scorer))
                assert mgr.serving_version == v2
                cand = [e for e in store.versions()
                        if e.status == "candidate"]
                assert len(cand) == 1
                assert cand[0].parent == v2, (
                    f"stale lineage: candidate parent {cand[0].parent} "
                    f"but serving was {v2} at save time")
            return [check_invariant()]

        def invariant(results):
            for r in results:
                if isinstance(r, BaseException):
                    raise AssertionError(repr(r))

        hit = explore(mk, invariant, seeds=range(12))
        assert hit is None, f"lineage race reproduced: {hit}"


class TestReplayWindowInterleaving:
    """Regression pin: ReplayWindow.sample() snapshots stay internally
    consistent (equal column lengths, row accounting exact) while
    add_batch churns between awaits — under every schedule."""

    def test_append_vs_snapshot(self):
        import numpy as np
        from linkerd_tpu.lifecycle.promote import ReplayWindow

        def mk(sched):
            win = ReplayWindow(capacity_rows=64)
            win.add_batch(np.zeros((4, 3), np.float32),
                          np.zeros(4), np.zeros(4))

            async def writer(tag):
                for i in range(4):
                    await sched.point(f"{tag}-{i}")
                    win.add_batch(np.full((8, 3), i, np.float32),
                                  np.zeros(8), np.ones(8))

            async def sampler():
                views = []
                for i in range(3):
                    await sched.point(f"sample-{i}")
                    x, labels, mask = win.sample()
                    views.append((len(x), len(labels), len(mask)))
                return views

            async def check():
                results = await sched.run(writer("w1"), writer("w2"),
                                          sampler())
                for r in results:
                    if isinstance(r, BaseException):
                        raise r
                for nx, nl, nm in results[2]:
                    assert nx == nl == nm, "torn sample"
                total = sum(len(b[0]) for b in win._batches)
                assert len(win) == total, "row accounting drifted"
                assert len(win) <= win.capacity_rows + 8
            return [check()]

        def invariant(results):
            for r in results:
                if isinstance(r, BaseException):
                    raise AssertionError(repr(r))

        assert explore(mk, invariant, seeds=range(10)) is None


class TestAdmissionInterleaving:
    """Regression pin: the admission pending/inflight counters stay
    exact under concurrent shed/admit — each RMW is awaitless (atomic),
    which is exactly why l5drace does NOT flag them. The sanitizer
    confirms: no lost updates on either counter, any schedule."""

    def test_counters_under_concurrent_shed_admit(self):
        from linkerd_tpu.router.admission import (
            AdmissionControlFilter, OverloadShed,
        )

        def mk(sched):
            f = AdmissionControlFilter(max_concurrency=2, max_pending=1)
            clear_log()
            track(f, ["_pending", "_inflight"])
            peak = {"inflight": 0, "pending": 0}

            async def service(req):
                peak["inflight"] = max(peak["inflight"], f._inflight)
                await sched.point(f"svc-{req}")
                return "ok"

            async def caller(i):
                try:
                    return await f.apply(i, service)
                except OverloadShed:
                    return "shed"

            async def check():
                results = await sched.run(*[caller(i) for i in range(5)])
                outcomes = sorted(str(r) for r in results)
                # 2 dispatch + 1 queued admit + 2 sheds, every schedule
                assert outcomes == ["ok", "ok", "ok", "shed", "shed"], (
                    outcomes)
                assert f._pending == 0 and f._inflight == 0
                assert peak["inflight"] <= 2, "concurrency bound broken"
                assert lost_updates("_pending") == []
                assert lost_updates("_inflight") == []
            return [check()]

        def invariant(results):
            for r in results:
                if isinstance(r, BaseException):
                    raise AssertionError(repr(r))

        assert explore(mk, invariant, seeds=range(10)) is None
