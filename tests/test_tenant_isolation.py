"""Tenant isolation under fire.

One abusive tenant — retry storm, slowloris, connection churn — must
degrade alone. Covered here:

- tenant extraction parity (C vs Python: bit-identical FNV-1a hash,
  header + pathSegment extraction through the native engines);
- quota shrink/recover hysteresis (no flapping) through the
  TenantAdmission governor;
- LRU cardinality bounds under hostile tenant-id churn (Python board
  AND the engines' native tables);
- retry-safety of per-tenant sheds (http 503 + l5d-retryable, h2
  RST_STREAM REFUSED_STREAM);
- the h2 rapid-reset cap (CVE-2023-44487-shaped floods die with
  ENHANCE_YOUR_CALM) + native slowloris/churn defenses;
- the chaos-matrix e2e: with the attacker tenant active, the victim
  tenant's success rate stays >= 0.99 and its p99 within bounds while
  the attacker is shed — including concurrently with a native weight
  hot-swap.
"""

import asyncio
import contextlib

import pytest

from linkerd_tpu import native
from linkerd_tpu.control.admission import TenantAdmission
from linkerd_tpu.control.state import HysteresisGovernor
from linkerd_tpu.router.admission import (
    AdmissionControlFilter, OverloadShed,
)
from linkerd_tpu.router.tenancy import (
    TenantBoard, TenantIdentifierSpec, TenantTagFilter, tenant_feature,
    tenant_hash,
)
from linkerd_tpu.router.service import FnService
from linkerd_tpu.testing.faults import (
    ConnectionChurnAttack, PacedTenantClient, SlowlorisAttack,
    TenantRetryStorm,
)

native_only = pytest.mark.skipif(
    not native.ensure_built(), reason="native toolchain unavailable")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


# ---------------------------------------------------------------- hashing


class TestTenantHash:
    def test_fnv1a_reference_values(self):
        # FNV-1a 32-bit test vectors (the empty string is not a tenant,
        # but the offset basis pins the algorithm)
        assert tenant_hash("a") == 0xE40C292C
        assert tenant_hash("foobar") == 0xBF9CF968

    def test_zero_folds_to_one(self):
        # 0 means "no tenant"; any real id must never hash to it
        for s in ("a", "b", "tenant", "x" * 64):
            assert tenant_hash(s) != 0

    def test_feature_fold_is_f32_exact(self):
        import numpy as np
        for s in ("alice", "bob", "t-999"):
            f = tenant_feature(tenant_hash(s))
            assert f == float(np.float32(f))
            assert 0 <= f < 2 ** 24

    @native_only
    def test_native_parity_bit_identical(self):
        ids = ["alice", "bob", "tenant-123", "UPPER", "with space",
               "ümlaut", "日本語", "x" * 200] + [f"t-{i}" for i in range(64)]
        for s in ids:
            assert tenant_hash(s) == native.tenant_hash_native(
                s.encode("utf-8")), s


class TestTenantIdentifierSpec:
    def test_header_extraction_http_and_h2(self):
        from linkerd_tpu.protocol.h2.messages import H2Request, Headers
        from linkerd_tpu.protocol.http.message import Request
        spec = TenantIdentifierSpec(kind="header", header="l5d-tenant")
        req = Request(uri="/x")
        req.headers.set("l5d-tenant", "alice")
        assert spec.extract(req) == "alice"
        h2req = H2Request(path="/x",
                          headers=Headers([("l5d-tenant", "bob")]))
        assert spec.extract(h2req) == "bob"

    def test_path_segment_extraction(self):
        from linkerd_tpu.protocol.http.message import Request
        spec = TenantIdentifierSpec(kind="pathSegment", segment=0)
        assert spec.extract(Request(uri="/acme/api/v1?q=1")) == "acme"
        assert spec.extract(Request(uri="/")) is None
        spec2 = TenantIdentifierSpec(kind="pathSegment", segment=1)
        assert spec2.extract(Request(uri="/acme/api")) == "api"

    def test_validation(self):
        with pytest.raises(ValueError):
            TenantIdentifierSpec(kind="nope").validate()
        with pytest.raises(ValueError):
            TenantIdentifierSpec(kind="header", header="").validate()
        with pytest.raises(ValueError):
            TenantIdentifierSpec(kind="pathSegment",
                                 segment=-1).validate()


# ---------------------------------------------------------------- board


class TestTenantBoard:
    def test_error_ewma_drives_level(self):
        b = TenantBoard(alpha=0.3)
        for _ in range(20):
            b.observe("bad", error=True, now=1.0)
            b.observe("good", error=False, now=1.0)
        assert b.level("bad") > 0.9
        assert b.level("good") == 0.0
        assert b.level("unknown") == 0.0

    def test_score_ewma_feeds_level(self):
        b = TenantBoard()
        b.ingest_native(0x1234, requests=100, errors=0, sheds=0,
                        score_ewma=0.8, scored=100, now=1.0)
        assert b.level("#00001234") == pytest.approx(0.8)

    def test_dominance_flags_retry_storm_shape(self):
        b = TenantBoard(window_s=1.0, fair_share_burst=2.0)
        # window 1: attacker sends 97%, victim 3%
        for _ in range(970):
            b.observe("atk", error=False, now=0.5)
        for _ in range(30):
            b.observe("vic", error=False, now=0.5)
        # rotate the window, then observe once more to land in window 2
        b.observe("atk", error=False, now=2.0)
        b.observe("vic", error=False, now=2.0)
        assert b.level("atk") > 0.0
        assert b.level("vic") == 0.0

    def test_lru_bound_under_id_churn(self):
        b = TenantBoard(max_tenants=64)
        for i in range(10_000):
            b.observe(f"churn-{i}", error=False, now=float(i))
        assert len(b.active_tenants()) <= 64
        assert b.evicted > 0

    def test_snapshot_shape(self):
        b = TenantBoard()
        b.observe("t1", error=True, now=1.0)
        b.observe_shed("t1", now=1.0)
        snap = b.snapshot()
        assert snap["t1"]["requests"] == 1
        assert snap["t1"]["sheds"] == 1
        assert snap["t1"]["errors"] == 1
        assert snap["t1"]["hash"] == tenant_hash("t1")


# ------------------------------------------------------------- governor


class _StubEngineQuotas:
    def __init__(self):
        self.quotas = {}

    def set_tenant_quota(self, thash, limit):
        if limit is None:
            self.quotas.pop(thash, None)
        else:
            self.quotas[thash] = limit


class TestTenantAdmission:
    def _mk(self, floor=0.125, quorum=3, dwell=1.0):
        board = TenantBoard()
        ta = TenantAdmission(
            board,
            governor=HysteresisGovernor(enter=0.6, exit=0.2,
                                        quorum=quorum, dwell_s=dwell),
            floor=floor, engine_base=64)
        return board, ta

    def test_quota_shrinks_then_recovers(self):
        board, ta = self._mk(dwell=0.0)
        filt = AdmissionControlFilter(32)
        eng = _StubEngineQuotas()
        ta.register(filt)
        ta.register_engine(eng)
        th = tenant_hash("atk")
        now = 100.0
        # sustained high level -> SICK after quorum steps
        for i in range(5):
            for _ in range(3):
                board.observe("atk", error=True, now=now)
            ta.step(now)
            now += 1.0
        assert filt.tenant_limit_of(th) == max(1, round(0.125 * 32))
        assert eng.quotas[th] == max(1, round(0.125 * 64))
        assert ta.transitions == 1
        # recovery: healthy traffic drains the EWMA, quota clears
        for i in range(60):
            board.observe("atk", error=False, now=now)
            ta.step(now)
            now += 1.0
        assert filt.tenant_limit_of(th) is None
        assert th not in eng.quotas
        assert ta.transitions == 2

    def test_no_flapping_on_oscillating_level(self):
        """A level oscillating between the enter and exit thresholds
        must cause at most the initial transition — the split
        thresholds + quorum + dwell absorb it."""
        board, ta = self._mk(quorum=3, dwell=5.0)
        filt = AdmissionControlFilter(32)
        ta.register(filt)
        now = 0.0
        # drive to SICK
        for _ in range(10):
            for _ in range(4):
                board.observe("osc", error=True, now=now)
            ta.step(now)
            now += 1.0
        assert ta.transitions == 1
        # now oscillate: bursts of successes and errors that keep the
        # EWMA wandering between exit (0.2) and enter (0.6)
        import itertools
        flip = itertools.cycle([True, False])
        for _ in range(100):
            board.observe("osc", error=next(flip), now=now)
            ta.step(now)
            now += 0.05
        assert ta.transitions == 1, "quota flapped"

    def test_governor_keys_bounded_under_id_churn(self):
        """The governor forgets tenants the board's LRU evicted (sick
        ones excepted) — hostile id churn must not grow its key store
        past the board bound."""
        board = TenantBoard(max_tenants=16)
        ta = TenantAdmission(
            board,
            governor=HysteresisGovernor(enter=0.6, exit=0.2, quorum=2,
                                        dwell_s=0.0),
            floor=0.125, engine_base=64)
        now = 0.0
        for i in range(2000):
            board.observe(f"churn-{i}", error=False, now=now)
            if i % 10 == 0:
                ta.step(now)
            now += 0.01
        ta.step(now)
        assert len(ta.governor.keys()) <= 16

    def test_untracked_tenants_untouched(self):
        board, ta = self._mk(dwell=0.0)
        filt = AdmissionControlFilter(32)
        ta.register(filt)
        now = 0.0
        for _ in range(5):
            for _ in range(3):
                board.observe("atk", error=True, now=now)
            board.observe("vic", error=False, now=now)
            ta.step(now)
            now += 1.0
        assert filt.tenant_limit_of(tenant_hash("atk")) is not None
        assert filt.tenant_limit_of(tenant_hash("vic")) is None


# ------------------------------------------- per-tenant admission limits


class TestAdmissionTenantLimits:
    def test_tenant_sublimit_sheds_without_touching_others(self):
        async def go():
            gate = asyncio.Event()

            async def slow(req):
                await gate.wait()
                return "ok"

            filt = AdmissionControlFilter(16)
            filt.set_tenant_limit(tenant_hash("atk"), 1)
            svc = FnService(slow)

            class Req:
                def __init__(self, tenant):
                    self.ctx = {"tenant_hash": tenant_hash(tenant)}

            t1 = asyncio.ensure_future(filt.apply(Req("atk"), svc))
            await asyncio.sleep(0.01)
            # second attacker request: over the sub-limit -> shed
            with pytest.raises(OverloadShed):
                await filt.apply(Req("atk"), svc)
            # the victim is untouched (global limit 16 has room)
            t2 = asyncio.ensure_future(filt.apply(Req("vic"), svc))
            await asyncio.sleep(0.01)
            gate.set()
            assert await t1 == "ok"
            assert await t2 == "ok"
            # slot released: attacker admits again
            assert await filt.apply(Req("atk"), svc) == "ok"

        run(go())

    def test_queued_same_tenant_counts_toward_sublimit(self):
        """The tenant slot is taken before the global queue wait, so
        a tenant cannot exceed its sub-limit via queued arrivals."""
        async def go():
            gate = asyncio.Event()

            async def slow(req):
                await gate.wait()
                return "ok"

            # global limit 1 + queue: the second atk request queues
            # globally but already holds a tenant slot
            filt = AdmissionControlFilter(1, max_pending=4)
            filt.set_tenant_limit(tenant_hash("atk"), 2)
            svc = FnService(slow)

            class Req:
                def __init__(self):
                    self.ctx = {"tenant_hash": tenant_hash("atk")}

            t1 = asyncio.ensure_future(filt.apply(Req(), svc))
            await asyncio.sleep(0.01)
            t2 = asyncio.ensure_future(filt.apply(Req(), svc))
            await asyncio.sleep(0.01)
            with pytest.raises(OverloadShed):
                await filt.apply(Req(), svc)
            gate.set()
            assert await t1 == "ok"
            assert await t2 == "ok"

        run(go())


# -------------------------------------------------- retry-safety of sheds


class TestShedRetrySafety:
    def test_http_tenant_shed_is_retryable_503(self, tmp_path):
        """Through a real linker: a tenant at its sub-limit gets 503 +
        l5d-retryable (the same contract as the global gate)."""
        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.http import Request
        from linkerd_tpu.protocol.http.client import HttpClient
        from linkerd_tpu.protocol.http.server import serve

        gate = asyncio.Event()

        async def waiting(req):
            await gate.wait()
            from linkerd_tpu.protocol.http import Response
            return Response(200, body=b"ok")

        async def go():
            backend = await serve(FnService(waiting))
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text(
                f"127.0.0.1 {backend.bound_port}\n")
            linker = load_linker(f"""
routers:
- protocol: http
  label: tshed
  admissionControl: {{maxConcurrency: 8, maxPending: 0}}
  tenantIdentifier: {{kind: header, header: l5d-tenant}}
  tenants: {{floor: 0.125}}
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
""")
            await linker.start()
            port = linker.routers[0].server_ports[0]
            # install the sub-limit directly (the governor path is
            # covered elsewhere; here we pin the SHED SIGNAL)
            _, board, adm = linker.tenant_views[0]
            admission = adm._filters[0]
            admission.set_tenant_limit(tenant_hash("atk"), 1)
            c1, c2 = (HttpClient("127.0.0.1", port) for _ in range(2))
            try:
                req1 = Request(uri="/1")
                req1.headers.set("Host", "web")
                req1.headers.set("l5d-tenant", "atk")
                t1 = asyncio.ensure_future(c1(req1))
                await asyncio.sleep(0.05)
                req2 = Request(uri="/2")
                req2.headers.set("Host", "web")
                req2.headers.set("l5d-tenant", "atk")
                rsp = await c2(req2)
                assert rsp.status == 503
                assert rsp.headers.get("l5d-retryable") == "true"
                gate.set()
                assert (await t1).status == 200
                flat = linker.metrics.flatten()
                assert flat["rt/tshed/server/admission/"
                            "tenant_shed_total"] >= 1
            finally:
                await c1.close()
                await c2.close()
                await linker.close()
                await backend.close()

        run(go())

    def test_h2_refused_is_retryable_in_classifiers(self):
        """REFUSED_STREAM (the h2 tenant-shed signal, native and
        Python) reads as retryable in every h2 status classifier —
        even the nonRetryable5XX one: RFC 7540 §8.1.4 blesses the
        retry because the stream was never processed."""
        from linkerd_tpu.protocol.h2.classifiers import (
            H2NonRetryable5XX, H2RetryableIdempotent5XX,
            H2RetryableRead5XX,
        )
        from linkerd_tpu.protocol.h2.messages import H2Request
        from linkerd_tpu.protocol.h2.stream import (
            RST_REFUSED_STREAM, StreamReset,
        )
        from linkerd_tpu.router.classifiers import ResponseClass
        refused = StreamReset(error_code=RST_REFUSED_STREAM)
        req = H2Request(method="POST", path="/")
        for cfg in (H2NonRetryable5XX(), H2RetryableRead5XX(),
                    H2RetryableIdempotent5XX()):
            rc = cfg.mk().classify(req, None, None, refused)
            assert rc is ResponseClass.RETRYABLE_FAILURE, cfg


# --------------------------------------------------- native: extraction


@native_only
class TestNativeTenantExtraction:
    async def _serve_ok(self):
        async def handle(reader, writer):
            while True:
                try:
                    await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError,
                        ConnectionResetError):
                    break
                writer.write(b"HTTP/1.1 200 OK\r\n"
                             b"Content-Length: 2\r\n\r\nok")
                await writer.drain()
            writer.close()

        return await asyncio.start_server(handle, "127.0.0.1", 0)

    async def _h1_get(self, port, host, uri="/", headers=()):
        r, w = await asyncio.open_connection("127.0.0.1", port)
        try:
            head = f"GET {uri} HTTP/1.1\r\nHost: {host}\r\n"
            for k, v in headers:
                head += f"{k}: {v}\r\n"
            w.write(head.encode() + b"\r\n")
            await w.drain()
            line = await asyncio.wait_for(r.readline(), 10)
            status = int(line.split()[1])
            hdrs = {}
            while True:
                ln = await r.readline()
                if ln in (b"\r\n", b""):
                    break
                k, _, v = ln.decode().partition(":")
                hdrs[k.strip().lower()] = v.strip()
            n = int(hdrs.get("content-length", 0))
            if n:
                await r.readexactly(n)
            return status, hdrs
        finally:
            w.close()

    def test_header_extraction_parity_and_feature_row(self):
        async def go():
            srv = await self._serve_ok()
            bport = srv.sockets[0].getsockname()[1]
            eng = native.FastPathEngine()
            eng.set_tenant("header", "l5d-tenant")
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("svc", [("127.0.0.1", bport)])
            try:
                for tid in ("alice", "bob", "T-42"):
                    st, _ = await self._h1_get(
                        port, "svc", headers=[("l5d-tenant", tid)])
                    assert st == 200
                await asyncio.sleep(0.05)
                rows = eng.drain_features()
                assert rows.shape[1] == 12
                got = set(float(x) for x in rows[:, 8])
                want = {tenant_feature(tenant_hash(t))
                        for t in ("alice", "bob", "T-42")}
                assert got == want
                by = eng.stats()["tenants"]["by_tenant"]
                assert set(int(k) for k in by) == {
                    tenant_hash(t) for t in ("alice", "bob", "T-42")}
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_path_segment_extraction_parity(self):
        async def go():
            srv = await self._serve_ok()
            bport = srv.sockets[0].getsockname()[1]
            eng = native.FastPathEngine()
            eng.set_tenant("pathSegment", segment=0)
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("svc", [("127.0.0.1", bport)])
            try:
                st, _ = await self._h1_get(port, "svc",
                                           uri="/acme/api?q=1")
                assert st == 200
                await asyncio.sleep(0.05)
                rows = eng.drain_features()
                spec = TenantIdentifierSpec(kind="pathSegment",
                                            segment=0)
                from linkerd_tpu.protocol.http.message import Request
                pyside = spec.extract(Request(uri="/acme/api?q=1"))
                assert pyside == "acme"
                assert float(rows[0, 8]) == tenant_feature(
                    tenant_hash(pyside))
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_native_lru_bound_under_id_churn(self):
        async def go():
            srv = await self._serve_ok()
            bport = srv.sockets[0].getsockname()[1]
            eng = native.FastPathEngine()
            eng.set_tenant("header", "l5d-tenant")
            eng.set_guard(tenant_cap=16)
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("svc", [("127.0.0.1", bport)])
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                for i in range(200):
                    w.write(f"GET / HTTP/1.1\r\nHost: svc\r\n"
                            f"l5d-tenant: churn-{i}\r\n\r\n".encode())
                    await w.drain()
                    line = await asyncio.wait_for(r.readline(), 10)
                    assert int(line.split()[1]) == 200
                    while True:
                        ln = await r.readline()
                        if ln == b"\r\n":
                            break
                    await r.readexactly(2)
                w.close()
                tn = eng.stats()["tenants"]
                assert tn["count"] <= 16
                assert tn["evicted"] >= 200 - 16 - 16  # amortized sweeps
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_native_quota_shed_is_retryable_503(self):
        async def go():
            srv = await self._serve_ok()
            bport = srv.sockets[0].getsockname()[1]
            eng = native.FastPathEngine()
            eng.set_tenant("header", "l5d-tenant")
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("svc", [("127.0.0.1", bport)])
            try:
                eng.set_tenant_quota(tenant_hash("atk"), 0)
                st, hdrs = await self._h1_get(
                    port, "svc", headers=[("l5d-tenant", "atk")])
                assert st == 503
                assert hdrs.get("l5d-retryable") == "true"
                # the victim rides through untouched
                st, _ = await self._h1_get(
                    port, "svc", headers=[("l5d-tenant", "vic")])
                assert st == 200
                eng.set_tenant_quota(tenant_hash("atk"), None)
                st, _ = await self._h1_get(
                    port, "svc", headers=[("l5d-tenant", "atk")])
                assert st == 200
                assert eng.stats()["guard"]["tenant_shed"] == 1
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_no_route_responses_release_the_tenant_slot(self):
        """Regression: synthesized error responses (no-route 400) end
        the request without finish_exchange — the per-tenant inflight
        slot must still be released, or a quota'd tenant whose
        requests miss routes accrues phantom inflight and is shed
        forever (and its pinned table entry defeats LRU eviction)."""
        async def go():
            srv = await self._serve_ok()
            bport = srv.sockets[0].getsockname()[1]
            eng = native.FastPathEngine()
            eng.set_tenant("header", "l5d-tenant")
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("svc", [("127.0.0.1", bport)])
            eng.set_route("dead", [])  # installed, zero endpoints: 400
            try:
                eng.set_tenant_quota(tenant_hash("t"), 2)
                r, w = await asyncio.open_connection("127.0.0.1", port)
                # 5 keep-alive requests that all 400 (no endpoints) —
                # each would leak one inflight slot pre-fix
                for _ in range(5):
                    w.write(b"GET / HTTP/1.1\r\nHost: dead\r\n"
                            b"l5d-tenant: t\r\n\r\n")
                    await w.drain()
                    line = await asyncio.wait_for(r.readline(), 10)
                    assert int(line.split()[1]) == 400
                    clen = 0
                    while True:
                        ln = await r.readline()
                        if ln in (b"\r\n", b""):
                            break
                        if ln.lower().startswith(b"content-length:"):
                            clen = int(ln.split(b":")[1])
                    if clen:
                        await r.readexactly(clen)
                w.close()
                # the tenant is idle now: a good request MUST pass
                st, _ = await self._h1_get(
                    port, "svc", headers=[("l5d-tenant", "t")])
                assert st == 200, "phantom inflight shed an idle tenant"
                by = eng.stats()["tenants"]["by_tenant"]
                assert by[str(tenant_hash("t"))]["inflight"] == 0
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_h2_native_quota_shed_is_refused_stream(self):
        from linkerd_tpu.protocol.h2.client import H2Client
        from linkerd_tpu.protocol.h2.messages import (
            H2Request, H2Response, Headers,
        )
        from linkerd_tpu.protocol.h2.server import H2Server
        from linkerd_tpu.protocol.h2.stream import StreamReset

        async def go():
            async def handler(req):
                return H2Response(status=200, body=b"ok")

            backend = await H2Server(FnService(handler)).start()
            eng = native.H2FastPathEngine()
            eng.set_tenant("header", "l5d-tenant")
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("echo",
                          [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            try:
                eng.set_tenant_quota(tenant_hash("atk"), 0)

                async def get(tenant):
                    req = H2Request(
                        method="GET", path="/", authority="echo",
                        headers=Headers([("l5d-tenant", tenant)]))
                    rsp = await h2c(req)
                    await rsp.stream.read_all()
                    return rsp.status

                with pytest.raises(StreamReset) as ei:
                    await get("atk")
                assert ei.value.error_code == 0x7  # REFUSED_STREAM
                assert await get("vic") == 200
                eng.set_tenant_quota(tenant_hash("atk"), None)
                assert await get("atk") == 200
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())


# ---------------------------------------------- native: conn-plane guard


@native_only
class TestNativeConnectionGuard:
    def test_h1_slowloris_closed_within_budget(self):
        async def go():
            eng = native.FastPathEngine()
            eng.set_guard(header_budget_ms=600)
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            try:
                loris = SlowlorisAttack(port, conns=8,
                                        drip_s=10.0).start()
                t0 = asyncio.get_event_loop().time()
                while (eng.stats()["guard"]["slowloris_closed"] < 8
                       and asyncio.get_event_loop().time() - t0 < 10):
                    await asyncio.sleep(0.2)
                await loris.stop()
                assert eng.stats()["guard"]["slowloris_closed"] >= 8
            finally:
                eng.close()

        run(go())

    def test_h1_body_stall_closed(self):
        async def go():
            async def handle(reader, writer):
                with contextlib.suppress(Exception):
                    await reader.readuntil(b"\r\n\r\n")
                await asyncio.sleep(30)
                writer.close()

            srv = await asyncio.start_server(handle, "127.0.0.1", 0)
            bport = srv.sockets[0].getsockname()[1]
            eng = native.FastPathEngine()
            eng.set_guard(header_budget_ms=30_000, body_stall_ms=600)
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("svc", [("127.0.0.1", bport)])
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                # declared 1000-byte body, send 3 bytes, stall
                w.write(b"POST / HTTP/1.1\r\nHost: svc\r\n"
                        b"Content-Length: 1000\r\n\r\nabc")
                await w.drain()
                data = await asyncio.wait_for(r.read(4096), 15)
                assert data == b""  # closed, no response
                assert eng.stats()["guard"]["body_stall_closed"] >= 1
                w.close()
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_accept_throttle_engages_under_churn(self):
        async def go():
            eng = native.FastPathEngine()
            eng.set_guard(accept_burst=20, accept_window_ms=1000)
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            try:
                churn = ConnectionChurnAttack(
                    port, rate_per_s=2000, workers=8).start()
                t0 = asyncio.get_event_loop().time()
                while (eng.stats()["guard"]["accept_throttled"] == 0
                       and asyncio.get_event_loop().time() - t0 < 10):
                    await asyncio.sleep(0.1)
                await churn.stop()
                assert eng.stats()["guard"]["accept_throttled"] > 0
            finally:
                eng.close()

        run(go())

    def test_h2_rapid_reset_cap(self):
        from linkerd_tpu.protocol.h2.hpack import Encoder

        async def go():
            eng = native.H2FastPathEngine()
            eng.set_flood_guard(rst_burst=20, window_ms=5000)
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            try:
                enc = Encoder()
                r, w = await asyncio.open_connection("127.0.0.1", port)
                with contextlib.suppress(ConnectionError):
                    w.write(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
                    w.write(b"\x00\x00\x00\x04\x00" + b"\x00" * 4)
                    for i in range(40):
                        sid = 1 + 2 * i
                        block = enc.encode(
                            [(":method", "GET"), (":scheme", "http"),
                             (":path", "/"), (":authority", "boom")])
                        ln = len(block)
                        w.write(bytes([(ln >> 16) & 0xFF,
                                       (ln >> 8) & 0xFF, ln & 0xFF,
                                       0x01, 0x05])
                                + sid.to_bytes(4, "big") + block)
                        w.write(b"\x00\x00\x04\x03\x00"
                                + sid.to_bytes(4, "big")
                                + (8).to_bytes(4, "big"))
                        await w.drain()
                with contextlib.suppress(ConnectionError,
                                         asyncio.TimeoutError):
                    while await asyncio.wait_for(r.read(65536), 5):
                        pass
                w.close()
                assert eng.stats()["guard"]["rapid_reset_closed"] >= 1
            finally:
                eng.close()

        run(go())

    def test_h2_preface_stall_closed(self):
        async def go():
            eng = native.H2FastPathEngine()
            eng.set_guard(header_budget_ms=600)
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(b"PRI * HTTP/2.0\r\n")  # half a preface
                await w.drain()
                data = b"x"
                with contextlib.suppress(ConnectionError):
                    while data:
                        data = await asyncio.wait_for(r.read(65536), 10)
                assert eng.stats()["guard"]["slowloris_closed"] >= 1
                w.close()
            finally:
                eng.close()

        run(go())


# ------------------------------------------------- fastpath control loop


@native_only
class TestFastpathTenantControlPlane:
    def test_stats_loop_feeds_board_and_pushes_quota(self):
        """The FastPathController's stats tick folds engine per-tenant
        deltas into the TenantBoard and steps the governor — a tenant
        whose engine-side error rate spikes gets its quota pushed INTO
        the engine within a few ticks."""

        class StubEngine:
            def __init__(self):
                self.quotas = {}
                self.tenants = {}

            def stats(self):
                return {"routes": {}, "tenants": {
                    "count": len(self.tenants), "evicted": 0,
                    "by_tenant": dict(self.tenants)}, "guard": {}}

            def set_tenant_quota(self, thash, limit):
                if limit is None:
                    self.quotas.pop(thash, None)
                else:
                    self.quotas[thash] = limit

        from linkerd_tpu.router.fastpath import FastPathController
        from linkerd_tpu.telemetry.metrics import MetricsTree

        async def go():
            eng = StubEngine()
            board = TenantBoard()
            ta = TenantAdmission(
                board,
                governor=HysteresisGovernor(enter=0.6, exit=0.2,
                                            quorum=2, dwell_s=0.0),
                floor=0.125, engine_base=64)
            ta.register_engine(eng)
            ctl = FastPathController.__new__(FastPathController)
            ctl.engine = eng
            ctl._scope = MetricsTree().scope("rt", "t", "fastpath")
            ctl.tenant_board = board
            ctl.tenant_admission = ta
            ctl._last_tenants = {}
            ctl._last_guard = {}
            ctl._tenant_metric_keys = set()
            ctl._tenant_metric_cap = 256
            th = tenant_hash("atk")
            reqs = 0
            # the per-tick error-rate EWMA (alpha 0.1) needs ~10 all-
            # error ticks to cross enter=0.6, plus the quorum
            for tick in range(16):
                reqs += 50
                eng.tenants[str(th)] = {
                    "requests": reqs, "shed": 0, "errors": reqs,
                    "scored": 0, "score_ewma": 0.0, "inflight": 0,
                    "quota": -1}
                ctl._export_tenants(eng.stats())
            assert eng.quotas.get(th) == max(1, round(0.125 * 64))

        run(go())


# ----------------------------------------------------- the chaos matrix


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(0.99 * len(xs)))] if xs else 0.0


class TestChaosMatrixPythonPath:
    def test_retry_storm_tenant_degrades_alone(self, tmp_path):
        """The full e2e on the Python data plane: an attacker tenant
        retry-storms a failing route; its error EWMA trips the quota
        governor; its floor quota sheds the storm retryably; the
        victim tenant's success rate and p99 hold. Zero quota flaps."""
        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.http import Response
        from linkerd_tpu.protocol.http.server import serve

        async def ok_handler(req):
            await asyncio.sleep(0.002)
            return Response(200, body=b"ok")

        async def boom_handler(req):
            return Response(500, body=b"boom")

        async def go():
            ok_srv = await serve(FnService(ok_handler))
            boom_srv = await serve(FnService(boom_handler))
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "good").write_text(
                f"127.0.0.1 {ok_srv.bound_port}\n")
            (disco / "boom").write_text(
                f"127.0.0.1 {boom_srv.bound_port}\n")
            linker = load_linker(f"""
routers:
- protocol: http
  label: chaos
  admissionControl: {{maxConcurrency: 8, maxPending: 8}}
  tenantIdentifier: {{kind: header, header: l5d-tenant}}
  tenants:
    floor: 0.125
    enterThreshold: 0.5
    exitThreshold: 0.2
    quorum: 3
    cooldownS: 0.2
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
""")
            await linker.start()
            port = linker.routers[0].server_ports[0]
            try:
                # -- baseline: victim alone
                vic0 = PacedTenantClient(port, "good", "victim",
                                         rate_per_s=100)
                await vic0.run(80)
                assert vic0.success_rate == 1.0
                base_p99 = vic0.p99_ms()

                # -- attack: retry storm against the failing route.
                # A light victim trickle runs through the detection
                # window (its errors-before-quota are the governor's
                # cost, not the isolation bound's).
                storm = TenantRetryStorm(port, "boom", "attacker",
                                         concurrency=8,
                                         retry_delay_s=0.005).start()
                warm = PacedTenantClient(port, "good", "victim",
                                         rate_per_s=50)
                warm_task = asyncio.ensure_future(warm.run(500))
                # wait for the governor to trip the attacker
                _, board, adm = linker.tenant_views[0]
                t0 = asyncio.get_event_loop().time()
                while (not adm.status()["sick"]
                       and asyncio.get_event_loop().time() - t0 < 15):
                    await asyncio.sleep(0.05)
                assert adm.status()["sick"] == ["attacker"], \
                    adm.status()
                warm_task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await warm_task
                # steady state under quota ("while the attacker is
                # shed"): the victim's bound and the attacker's shed
                # fraction are measured HERE
                ok0, shed0 = storm.ok, storm.shed
                vic = PacedTenantClient(port, "good", "victim",
                                        rate_per_s=100)
                await vic.run(200)
                ok1, shed1 = storm.ok, storm.shed
                await storm.stop()

                # the victim held. The p99 bound is 2x its no-attack
                # baseline, widened by a fixed 50 ms jitter allowance:
                # everything here — router, both downstreams, attacker
                # AND victim — shares one event loop, so tens of ms of
                # scheduling jitter is harness noise, not mesh queueing
                # (pre-quota collapse is hundreds of ms of queue waits
                # + sheds). For real (>50 ms) latencies the bound
                # degenerates to the plain 2x criterion.
                assert vic.success_rate >= 0.99, vic.success_rate
                bound = max(2 * base_p99, base_p99 + 50.0)
                assert vic.p99_ms() <= bound, (vic.p99_ms(), base_p99)
                # the attacker was shed at rate
                post = (ok1 - ok0) + (shed1 - shed0)
                assert post > 0
                assert (shed1 - shed0) / post >= 0.9, \
                    (shed1 - shed0, post)
                # zero flaps: exactly one transition (to SICK)
                assert adm.transitions == 1
                # admin surface agrees
                snap = board.snapshot()
                assert snap["attacker"]["level"] > 0.5
                assert snap["victim"]["level"] < 0.2
            finally:
                await linker.close()
                await ok_srv.close()
                await boom_srv.close()

        run(go())


@native_only
class TestChaosMatrixNative:
    @pytest.mark.parametrize("workers", [1, 2])
    def test_isolation_holds_during_weight_hot_swap(self, workers):
        """Native leg: attacker quota-shed in the ENGINE while weight
        blobs hot-swap concurrently — the victim's success rate and
        the engine's scoring pipeline both hold. Runs at workers=1
        (today's single engine) AND workers=2 (the SO_REUSEPORT shard
        group: per-core tenant tables, the N-way quota split, and the
        shared weight slab must not break the isolation loop)."""

        async def go():
            async def handle(reader, writer):
                while True:
                    try:
                        await reader.readuntil(b"\r\n\r\n")
                    except (asyncio.IncompleteReadError,
                            ConnectionResetError):
                        break
                    writer.write(b"HTTP/1.1 200 OK\r\n"
                                 b"Content-Length: 2\r\n\r\nok")
                    await writer.drain()
                writer.close()

            srv = await asyncio.start_server(handle, "127.0.0.1", 0)
            bport = srv.sockets[0].getsockname()[1]
            eng = native.FastPathEngine(workers=workers)
            eng.set_tenant("header", "l5d-tenant")
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("svc", [("127.0.0.1", bport)])
            eng.set_route_feature("svc", 14, 1.0)
            # workers=2 splits this floor-division: 1 // 2 = 0 per
            # worker — the attacker is shed entirely, the victim
            # (quota-less) must still sail through on every core
            eng.set_tenant_quota(tenant_hash("attacker"), 1)

            swaps = 0
            stop = asyncio.Event()

            async def swapper():
                nonlocal swaps
                v = 1
                while not stop.is_set():
                    blob = native.score_test_blob(version=v,
                                                  quant="f32", seed=v)
                    eng.publish_weights(blob)
                    swaps += 1
                    v += 1
                    await asyncio.sleep(0.01)

            try:
                storm = TenantRetryStorm(port, "svc", "attacker",
                                         concurrency=8).start()
                swap_task = asyncio.ensure_future(swapper())
                vic = PacedTenantClient(port, "svc", "victim",
                                        rate_per_s=100)
                await vic.run(200)
                stop.set()
                await swap_task
                await storm.stop()
                assert vic.success_rate >= 0.99, vic.success_rate
                assert storm.shed_fraction >= 0.5, storm.shed_fraction
                assert swaps > 10
                st = eng.stats()
                assert st["guard"]["tenant_shed"] > 0
                # the scoring pipeline kept running through the swaps
                assert st["native_scorer"]["scored"] > 0
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())
