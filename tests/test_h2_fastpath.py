"""Native h2 fastpath data plane: engine semantics + linker integration.

The h2/gRPC hot loop runs in C++ (native/h2_fastpath.cpp); these tests
drive it through real sockets and assert parity with the Python h2
router path: route-by-:authority, 400 on unbound, live re-route on
fs-namer change, both flow-control levels across an 8MB proxied body
(ref: router/h2 LargeStreamEndToEndTest + FlowControlEndToEndTest),
GOAWAY reconnect with request replay (ref: H2.scala SingletonPool
re-establishment + BufferedStream retry-buffer), trailer-borne
grpc-status passthrough, and feature/stat export for the anomaly
telemeter.
"""

import asyncio

import pytest

from linkerd_tpu import native
from linkerd_tpu.grpc import (
    ClientDispatcher, Field, ProtoMessage, Rpc, ServerDispatcher,
    ServiceDef,
)
from linkerd_tpu.linker import load_linker
from linkerd_tpu.protocol.h2.client import H2Client
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
from linkerd_tpu.protocol.h2.server import H2Server
from linkerd_tpu.router.service import FnService

pytestmark = pytest.mark.skipif(
    not native.ensure_built(), reason="native toolchain unavailable")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60))


class Echo(ProtoMessage):
    FIELDS = {"payload": Field(1, "bytes")}


ECHO_SVC = ServiceDef("fp.Echo", [Rpc("Echo", Echo, Echo)])


def echo_dispatcher() -> ServerDispatcher:
    disp = ServerDispatcher()

    async def echo(req: Echo) -> Echo:
        return Echo(payload=req.payload)

    disp.register_all(ECHO_SVC, {"Echo": echo})
    return disp


def mk_cfg(disco) -> str:
    return f"""
routers:
- protocol: h2
  label: h2fp
  fastPath: true
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""


@pytest.fixture
def disco(tmp_path):
    d = tmp_path / "disco"
    d.mkdir()
    return d


class TestH2FastPathEngine:
    def test_routes_grpc_and_exports_features(self):
        async def go():
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            backend = await H2Server(echo_dispatcher()).start()
            eng.set_route("echo", [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            client = ClientDispatcher(h2c, authority="echo")
            try:
                out = await client.unary(ECHO_SVC, "Echo",
                                         Echo(payload=b"ping"))
                assert out.payload == b"ping"
                outs = await asyncio.gather(*[
                    client.unary(ECHO_SVC, "Echo",
                                 Echo(payload=b"x%d" % i))
                    for i in range(32)])
                assert all(o.payload == b"x%d" % i
                           for i, o in enumerate(outs))
                stats = eng.stats()["routes"]["echo"]
                assert stats["requests"] == 33
                assert stats["success"] == 33
                rows = eng.drain_features()
                assert rows.shape == (33, 12)
                assert (rows[:, 2] == 200).all()  # status column
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())

    def test_route_miss_parks_then_unparks(self):
        """A request for an unknown authority parks until the control
        plane installs the route (ref: fastpath.cpp WAIT_ROUTE dance)."""
        async def go():
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            backend = await H2Server(echo_dispatcher()).start()
            h2c = H2Client("127.0.0.1", port)
            client = ClientDispatcher(h2c, authority="late")
            try:
                fut = asyncio.ensure_future(
                    client.unary(ECHO_SVC, "Echo", Echo(payload=b"wait")))
                # the engine surfaces the miss; play controller
                for _ in range(200):
                    misses = eng.drain_misses()
                    if "late" in misses:
                        break
                    await asyncio.sleep(0.01)
                else:
                    raise AssertionError("miss never surfaced")
                eng.set_route("late", [("127.0.0.1", backend.bound_port)])
                out = await fut
                assert out.payload == b"wait"
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())

    def test_unknown_route_times_out_400(self):
        async def go():
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            h2c = H2Client("127.0.0.1", port)
            try:
                rsp = await h2c(H2Request(method="POST", path="/x",
                                          authority="ghost", body=b""))
                assert rsp.status == 400
                assert rsp.headers.get("l5d-err") is not None
            finally:
                await h2c.close()
                eng.close()

        run(go())

    def test_8mb_body_through_native_proxy(self):
        """An 8MB request+response must recycle BOTH flow-control levels
        across both hops of the native proxy."""
        big = bytes(1024) * (8 * 1024)  # 8MB

        async def echo_len(req: H2Request) -> H2Response:
            body, _ = await req.stream.read_all(max_bytes=1 << 27)
            return H2Response(status=200, body=body)

        async def go():
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            backend = await H2Server(FnService(echo_len)).start()
            eng.set_route("big", [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            try:
                rsp = await h2c(H2Request(method="POST", path="/up",
                                          authority="big", body=big))
                body, _ = await rsp.stream.read_all(max_bytes=1 << 27)
                assert body == big
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())

    def test_goaway_reconnect_replays_on_fresh_conn(self):
        """After the backend GOAWAYs the proxy's multiplexed upstream
        conn, the next request must flow on a fresh connection."""
        async def go():
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            backend = await H2Server(echo_dispatcher()).start()
            eng.set_route("echo", [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            client = ClientDispatcher(h2c, authority="echo")
            try:
                out = await client.unary(ECHO_SVC, "Echo",
                                         Echo(payload=b"one"))
                assert out.payload == b"one"
                # backend sends GOAWAY + FIN on every live conn
                for conn in list(backend._conns):
                    await conn.close()
                await asyncio.sleep(0.05)
                out = await client.unary(ECHO_SVC, "Echo",
                                         Echo(payload=b"two"))
                assert out.payload == b"two"
                stats = eng.stats()["routes"]["echo"]
                assert stats["success"] == 2
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())

    def test_upstream_max_concurrent_streams_queueing(self):
        """A backend advertising MAX_CONCURRENT_STREAMS=1 forces the
        engine to queue dispatches on its multiplexed upstream conn;
        all requests must still complete (ref: pend_dispatch in
        h2_fastpath.cpp, finagle's slot waiting)."""
        disp = ServerDispatcher()

        async def slow_echo(req: Echo) -> Echo:
            await asyncio.sleep(0.02)
            return Echo(payload=req.payload)

        disp.register_all(ECHO_SVC, {"Echo": slow_echo})

        async def go():
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            backend = await H2Server(
                disp, h2_settings={"max_concurrent_streams": 1}).start()
            eng.set_route("echo", [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            client = ClientDispatcher(h2c, authority="echo")
            try:
                outs = await asyncio.wait_for(asyncio.gather(*[
                    client.unary(ECHO_SVC, "Echo", Echo(payload=b"q%d" % i))
                    for i in range(8)]), 30)
                assert all(o.payload == b"q%d" % i
                           for i, o in enumerate(outs))
                stats = eng.stats()["routes"]["echo"]
                assert stats["success"] == 8
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())

    def test_grpc_error_status_trailer_passthrough(self):
        """grpc-status trailers (the gRPC error channel) must survive the
        proxy hop byte-for-byte (ref: GrpcClassifier.scala reads them)."""
        from linkerd_tpu.grpc import GrpcError

        disp = ServerDispatcher()

        async def boom(req: Echo) -> Echo:
            raise GrpcError.of(14, "try again later")  # UNAVAILABLE

        disp.register_all(ECHO_SVC, {"Echo": boom})

        async def go():
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            backend = await H2Server(disp).start()
            eng.set_route("echo", [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            client = ClientDispatcher(h2c, authority="echo")
            try:
                with pytest.raises(GrpcError) as ei:
                    await client.unary(ECHO_SVC, "Echo",
                                       Echo(payload=b"x"))
                assert ei.value.status.code == 14
                assert "try again" in ei.value.status.message
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())


class TestH2FastPathLinker:
    def test_linker_grpc_e2e_and_reroute(self, disco):
        """Full linker assembly: fastPath h2 router + fs namer; gRPC
        round-trips and a disco-file edit re-routes live (ref:
        HttpEndToEndTest + WatchingNamer)."""
        async def go():
            d_a = await H2Server(echo_dispatcher()).start()

            disp_b = ServerDispatcher()

            async def tagged(req: Echo) -> Echo:
                return Echo(payload=b"B:" + req.payload)

            disp_b.register_all(ECHO_SVC, {"Echo": tagged})
            d_b = await H2Server(disp_b).start()

            (disco / "echo").write_text(f"127.0.0.1 {d_a.bound_port}\n")
            linker = load_linker(mk_cfg(disco))
            await linker.start()
            port = linker.routers[0].server_ports[0]
            h2c = H2Client("127.0.0.1", port)
            client = ClientDispatcher(h2c, authority="echo")
            try:
                out = await client.unary(ECHO_SVC, "Echo",
                                         Echo(payload=b"hi"))
                assert out.payload == b"hi"

                # live re-route: fs edit flips the replica set
                (disco / "echo").write_text(
                    f"127.0.0.1 {d_b.bound_port}\n")
                for _ in range(300):
                    out = await client.unary(ECHO_SVC, "Echo",
                                             Echo(payload=b"hi"))
                    if out.payload == b"B:hi":
                        break
                    await asyncio.sleep(0.02)
                assert out.payload == b"B:hi"

                # engine stats surface in the MetricsTree under the
                # standard fastpath scope
                await asyncio.sleep(1.2)  # one stats poll interval
                flat = linker.metrics.flatten()
                key = "rt/h2fp/fastpath/route/echo/requests"
                assert flat.get(key, 0) >= 1
            finally:
                await h2c.close()
                await linker.close()
                await d_a.close()
                await d_b.close()

        run(go())


class TestFastPathConfigRefusals:
    def test_unsupported_knobs_fail_load(self, disco):
        """fastPath must refuse config the native engine cannot honor
        rather than silently dropping it (TLS dials, service policy,
        h2 SETTINGS)."""
        from linkerd_tpu.config import ConfigError

        base = f"""
routers:
- protocol: h2
  label: bad
  fastPath: true
  {{extra}}
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{{{port: 0}}}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
        for extra, msg in [
            ("maxFrameBytes: 65536", "maxFrameBytes"),
            ("client: {tls: {commonName: x}}", "client.tls"),
            ("service: {totalTimeoutMs: 100}", "service policy"),
        ]:
            with pytest.raises(ConfigError, match=msg):
                load_linker(base.format(extra=extra))


class TestGrpcioInterop:
    def test_grpcio_client_through_native_proxy(self):
        """grpcio's nghttp2 stack (Huffman HPACK, its own SETTINGS) must
        interop with the native proxy."""
        grpc = pytest.importorskip("grpc")
        import threading

        loop = asyncio.new_event_loop()
        server_box = {}

        async def setup():
            backend = await H2Server(echo_dispatcher()).start()
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("127.0.0.1", [("127.0.0.1", backend.bound_port)])
            server_box.update(backend=backend, eng=eng, port=port)

        loop.run_until_complete(setup())
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        try:
            ch = grpc.insecure_channel(f"127.0.0.1:{server_box['port']}")
            call = ch.unary_unary(
                "/fp.Echo/Echo",
                request_serializer=lambda m: m.encode(),
                response_deserializer=Echo.decode)
            rsp = call(Echo(payload=b"\x01\x02interop"), timeout=10)
            assert rsp.payload == b"\x01\x02interop"
            ch.close()
        finally:
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=5)
            server_box["eng"].close()
            loop.run_until_complete(server_box["backend"].close())
            loop.close()


class TestResponseStartTimeout:
    def test_hung_backend_gets_504(self):
        """A dispatched stream whose backend never starts its response
        times out with 504 (the h1 engine's exchange-timeout analog);
        the upstream side is reset."""
        from linkerd_tpu.protocol.h2.messages import H2Request

        async def go():
            hung = asyncio.Event()

            async def never(req):
                await hung.wait()  # never set

            backend = await H2Server(FnService(never)).start()
            eng = native.H2FastPathEngine()
            port = eng.listen("127.0.0.1", 0)
            eng.set_response_timeout_ms(300)
            eng.start()
            eng.set_route("hang", [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            try:
                rsp = await asyncio.wait_for(
                    h2c(H2Request(method="GET", path="/x",
                                  authority="hang")), 10)
                assert rsp.status == 504
                assert rsp.headers.get("l5d-err") is not None
                stats = eng.stats()["routes"]["hang"]
                assert stats["f5xx"] == 1
            finally:
                hung.set()
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())
