"""North-star pipeline tests: feature recorder -> micro-batch -> scorer ->
scoreboard -> policy feedback, plus the labeled fault-injection AUC
evaluation (BASELINE.md: AUC >= 0.9 on injected-fault traces)."""

import asyncio

import numpy as np
import pytest

from linkerd_tpu.linker import load_linker
from linkerd_tpu.models.features import FEATURE_DIM
from linkerd_tpu.protocol.http import Request, Response
from linkerd_tpu.protocol.http.client import HttpClient
from linkerd_tpu.protocol.http.server import serve
from linkerd_tpu.router.service import FnService
from linkerd_tpu.telemetry.anomaly import (
    AnomalyFailureAccrualPolicy, InProcessScorer, JaxAnomalyConfig,
    ScoreBoard,
)
from linkerd_tpu.telemetry.metrics import MetricsTree
from linkerd_tpu.testing.faults import FaultInjector, FaultSpec, auc


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


class TestAuc:
    def test_auc_helper(self):
        assert auc([0, 0, 1, 1], [0.1, 0.2, 0.8, 0.9]) == 1.0
        assert auc([1, 1, 0, 0], [0.1, 0.2, 0.8, 0.9]) == 0.0
        assert abs(auc([0, 1, 0, 1], [0.5, 0.5, 0.5, 0.5]) - 0.5) < 1e-9


class TestScoreBoard:
    def test_ewma_and_observability(self):
        b = ScoreBoard(alpha=0.5)
        b.update_batch(["/svc/a", "/svc/a", "/svc/b"],
                       np.array([0.8, 0.6, 0.1]))
        assert 0.6 <= b.score_of("/svc/a") <= 0.8
        assert b.score_of("/svc/b") == pytest.approx(0.1)
        b.update_batch(["/svc/b"], np.array([0.9]))
        assert b.score_of("/svc/b") == pytest.approx(0.5)  # ewma moved


class TestAnomalyPolicy:
    def test_threshold_tightens_accrual(self):
        board = ScoreBoard()
        p = AnomalyFailureAccrualPolicy(
            board, failures=5, anomalous_failures=2, threshold=0.5,
            backoffs=iter([1.0, 1.0, 1.0]))
        # calm mesh: needs 5 consecutive failures
        for _ in range(4):
            assert p.record_failure() is None
        p.record_success()
        # anomalous mesh: needs only 2
        board.update_batch(["/svc/web"], np.array([0.9]))
        assert p.record_failure() is None
        assert p.record_failure() == 1.0


class TestTelemeterPipeline:
    def test_end_to_end_scoring_and_auc(self, tmp_path):
        """Full linker with the jaxAnomaly telemeter: normal traffic, then
        injected faults; anomaly scores must separate labeled traffic with
        AUC >= 0.9 and raise the per-dst score."""
        disco = tmp_path / "disco"
        disco.mkdir()

        injector = FaultInjector(FaultSpec(error_rate=0.9, latency_ms=40.0))

        async def backend(req: Request) -> Response:
            return Response(200, body=b"x" * 200)

        async def go():
            d = await serve(injector.and_then(FnService(backend)))
            (disco / "web").write_text(f"127.0.0.1 {d.bound_port}\n")
            cfg = f"""
routers:
- protocol: http
  label: rt
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
  client:
    failureAccrual: {{kind: none}}
telemetry:
- kind: io.l5d.jaxAnomaly
  maxBatch: 512
  trainEveryBatches: 1
  reconWeight: 1.0
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            tele = linker.telemeters[0]
            proxy = HttpClient("127.0.0.1", linker.routers[0].server_ports[0])
            try:
                async def send(n):
                    for _ in range(n):
                        req = Request(method="GET", uri="/")
                        req.headers.set("Host", "web")
                        await proxy(req)

                # Phase A: normal traffic; train the autoencoder on it.
                await send(120)
                ring_copy = list(tele.ring)  # snapshot once: each epoch
                for _ in range(6):           # re-trains on the same batch
                    await tele.drain_once()
                    for item in ring_copy:  # refill so training sees more
                        tele.ring.append(item)
                    await tele.drain_once()
                baseline = tele.board.score_of("/svc/web")

                # Phase B: mixed window — alternating fault bursts and
                # normal traffic, all labeled.
                for _ in range(4):
                    injector.active = True
                    await send(30)
                    injector.active = False
                    await send(30)
                # score the labeled window WITHOUT training on it
                tele.cfg.trainEveryBatches = 0
                items = list(tele.ring)
                await tele.drain_once()
                anomalous = tele.board.score_of("/svc/web")
                assert anomalous > baseline  # score rose under faults

                # AUC over the individually labeled window (ring items
                # are (fv, label, trace, enqueued_at) since the scorer
                # spans landed)
                from linkerd_tpu.models.features import featurize_batch
                fvs = [it[0] for it in items]
                labels = [it[1] for it in items]
                x = featurize_batch(fvs)
                scorer = tele._ensure_scorer()
                scores = await scorer.score(x)
                mask = [(l, s) for l, s in zip(labels, scores)
                        if l is not None]
                got_auc = auc([l for l, _ in mask], [s for _, s in mask])
                assert got_auc >= 0.9, f"AUC {got_auc}"
            finally:
                await proxy.close()
                await linker.close()
                await d.close()

        run(go())

    def test_scorer_metrics_and_admin_handler(self, tmp_path):
        async def go():
            mt = MetricsTree()
            cfg = JaxAnomalyConfig(maxBatch=64, trainEveryBatches=0,
                                   reconWeight=1.0)
            tele = cfg.mk(mt)
            rec = tele.recorder()

            async def ok(req):
                return Response(200)

            svc = rec.and_then(FnService(ok))
            for _ in range(10):
                req = Request()
                req.ctx["dst"] = type("D", (), {"path": None})
                req.ctx["dst"].path = __import__(
                    "linkerd_tpu.core.path", fromlist=["Path"]).Path.read("/svc/x")
                await svc(req)
            n = await tele.drain_once()
            assert n == 10
            flat = mt.flatten()
            assert flat["anomaly/scored_total"] == 10
            assert flat["anomaly/batches"] == 1
            assert "anomaly/dst/svc.x" in flat

            handlers = tele.admin_handlers()
            assert handlers[0][0] == "/anomaly.json"
            rsp = await handlers[0][1](Request())
            assert rsp.status == 200
            tele.close()

        run(go())


class TestGrpcSidecar:
    def test_score_and_fit_over_grpc(self):
        from linkerd_tpu.telemetry.sidecar import (
            GrpcScorerClient, ScorerSidecar, decode_fit, encode_fit,
            decode_matrix, encode_matrix,
        )

        # codec roundtrip
        x = np.random.default_rng(0).standard_normal((5, FEATURE_DIM)).astype(np.float32)
        assert (decode_matrix(encode_matrix(x)) == x).all()
        labels = np.ones(5, np.float32)
        mask = np.zeros(5, np.float32)
        x2, l2, m2 = decode_fit(encode_fit(x, labels, mask))
        assert (x2 == x).all() and (l2 == labels).all() and (m2 == mask).all()

        async def go():
            sidecar = await ScorerSidecar(warmup_rows=4).start()
            # warmup must pre-compile without contaminating scorer state
            assert sidecar.scorer._norm_initialized is False
            client = GrpcScorerClient(f"127.0.0.1:{sidecar.port}")
            try:
                scores = await client.score(x)
                assert scores.shape == (5,)
                assert np.isfinite(scores).all()
                loss = await client.fit(x, labels, np.ones(5, np.float32))
                assert np.isfinite(loss)
                # fit actually trains: loss decreases over steps
                losses = [await client.fit(x, np.zeros(5, np.float32),
                                           np.zeros(5, np.float32))
                          for _ in range(10)]
                assert losses[-1] < losses[0]
            finally:
                client.close()
                await sidecar.close()

        run(go())


class TestSidecarCodec:
    """Length-prefixed codec edge cases: zero-row and non-contiguous
    (sliced) arrays round-trip; truncated payloads raise ValueError
    instead of np.frombuffer silently misreading."""

    def test_zero_row_roundtrip(self):
        from linkerd_tpu.telemetry.sidecar import (
            decode_fit, decode_matrix, encode_fit, encode_matrix,
        )
        empty = np.zeros((0, FEATURE_DIM), np.float32)
        out = decode_matrix(encode_matrix(empty))
        assert out.shape == (0, FEATURE_DIM)
        x, l, m = decode_fit(encode_fit(
            empty, np.zeros(0, np.float32), np.zeros(0, np.float32)))
        assert x.shape == (0, FEATURE_DIM) and len(l) == 0 and len(m) == 0

    def test_non_contiguous_roundtrip(self):
        from linkerd_tpu.telemetry.sidecar import (
            decode_fit, decode_matrix, encode_fit, encode_matrix,
        )
        rng = np.random.default_rng(1)
        base = rng.standard_normal((16, FEATURE_DIM)).astype(np.float32)
        labels = np.arange(16, dtype=np.float32)
        # every-other-row views are not C-contiguous
        x, l, m = base[::2], labels[::2], labels[::2] * 0 + 1
        assert not x.flags["C_CONTIGUOUS"]
        assert (decode_matrix(encode_matrix(x)) == x).all()
        x2, l2, m2 = decode_fit(encode_fit(x, l, m))
        assert (x2 == x).all() and (l2 == l).all() and (m2 == m).all()

    def test_truncated_and_malformed_payloads_raise(self):
        from linkerd_tpu.telemetry.sidecar import (
            decode_fit, decode_matrix, encode_fit, encode_matrix,
        )
        x = np.ones((4, FEATURE_DIM), np.float32)
        good = encode_matrix(x)
        with pytest.raises(ValueError):
            decode_matrix(good[:-8])       # short payload
        with pytest.raises(ValueError):
            decode_matrix(good[:6])        # shorter than the header
        with pytest.raises(ValueError):
            decode_matrix(good + b"\x00" * 4)  # trailing garbage
        fit = encode_fit(x, np.zeros(4, np.float32), np.ones(4, np.float32))
        with pytest.raises(ValueError):
            decode_fit(fit[:-4])           # truncated mask
        with pytest.raises(ValueError):
            decode_fit(fit + b"\x00" * 4)  # trailing garbage
        with pytest.raises(ValueError):
            encode_matrix(np.ones(8, np.float32))  # not [n, d]
        with pytest.raises(ValueError):
            # label/mask row mismatch must not encode shifted payloads
            encode_fit(x, np.zeros(3, np.float32), np.ones(4, np.float32))


class TestDrainBurst:
    def test_backlog_drains_multiple_batches_per_wake(self, tmp_path):
        """Under backlog the telemeter scores several micro-batches per
        wake (capped), not one per interval."""
        from linkerd_tpu.telemetry.anomaly import (
            FeatureVector, JaxAnomalyConfig, JaxAnomalyTelemeter,
        )
        from linkerd_tpu.telemetry.metrics import MetricsTree

        async def go():
            cfg = JaxAnomalyConfig(maxBatch=32, trainEveryBatches=0)
            tele = JaxAnomalyTelemeter(cfg, MetricsTree())
            for i in range(3 * 32 + 5):
                tele.ring.append((FeatureVector(latency_ms=float(i)), None))
            scorer = tele._ensure_scorer()
            drained = await tele._drain_burst(scorer)
            # 3 full batches + the 5-row remainder in ONE burst
            assert drained == 3 * 32 + 5
            assert len(tele.ring) == 0

            # bounded: a deeper backlog stops at max_batches full batches
            for i in range(12 * 32):
                tele.ring.append((FeatureVector(), None))
            drained = await tele._drain_burst(scorer, max_batches=4)
            assert drained == 4 * 32
            assert len(tele.ring) == 8 * 32

        run(go())
