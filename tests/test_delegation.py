"""DelegateTree explanations + admin handlers.

Ref test models: namer/core DelegateTree tests and the admin
DelegateApiHandler JSON shapes.
"""

import asyncio
import json

import pytest

from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.namer.core import ConfiguredDtabNamer
from linkerd_tpu.namer.delegate import (
    DAlt, DDelegate, DLeaf, DNeg, Delegator, delegate_json,
)
from linkerd_tpu.namer.fs import FsNamer


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


@pytest.fixture
def interp(tmp_path):
    d = tmp_path / "disco"
    d.mkdir()
    (d / "web").write_text("127.0.0.1 8080\n")
    namer = FsNamer(str(d))
    namer.refresh()
    return ConfiguredDtabNamer([(Path.read("/io.l5d.fs"), namer)])


class TestDelegator:
    def test_single_rewrite_chain(self, interp):
        dtab = Dtab.read("/svc => /#/io.l5d.fs;")
        tree = Delegator(interp).delegate(dtab, Path.read("/svc/web"))
        # /svc/web -[/svc => /#/io.l5d.fs]-> /#/io.l5d.fs/web -> bound leaf
        assert isinstance(tree, DDelegate)
        assert tree.path.show == "/svc/web"
        assert tree.dentry is not None and tree.dentry.prefix.show == "/svc"
        leaf = tree.child
        assert isinstance(leaf, DLeaf)
        assert leaf.bound is not None
        assert leaf.bound.id_.show == "/#/io.l5d.fs/web"

    def test_neg_when_no_rule(self, interp):
        tree = Delegator(interp).delegate(Dtab.empty(), Path.read("/nope"))
        assert isinstance(tree, DNeg)

    def test_alt_precedence_order(self, interp):
        dtab = Dtab.read(
            "/svc => /#/io.l5d.fs; /svc/web => /#/io.l5d.fs/web;")
        tree = Delegator(interp).delegate(dtab, Path.read("/svc/web"))
        # both dentries match -> Alt with LATER entry first (precedence)
        assert isinstance(tree, DAlt)
        first = tree.children[0]
        assert first.dentry.prefix.show == "/svc/web"
        j = delegate_json(tree)
        assert j["type"] == "alt"
        assert j["alt"][0]["dentry"]["prefix"] == "/svc/web"

    def test_unknown_namer_is_neg(self, interp):
        dtab = Dtab.read("/svc => /#/io.l5d.nothere;")
        tree = Delegator(interp).delegate(dtab, Path.read("/svc/web"))
        assert isinstance(tree, DDelegate)
        assert isinstance(tree.child, DNeg)

    def test_alt_union_branches_keep_originating_dentry(self, interp):
        # regression: nested Alt/Union nodes produced by ONE dentry's dst
        # tree used to drop that dentry — every step must attribute the
        # rule that produced it (delegator UI + l5dcheck terminals)
        from linkerd_tpu.namer.delegate import DUnion

        dtab = Dtab.read(
            "/svc => /#/io.l5d.fs/web | /#/io.l5d.fs/web-v0 ;")
        tree = Delegator(interp).delegate(dtab, Path.read("/svc/x"))
        assert isinstance(tree, DAlt)
        assert tree.dentry is not None
        for child in tree.children:
            assert child.dentry is not None
            assert child.dentry.prefix.show == "/svc"
        j = delegate_json(tree)
        assert all("dentry" in c for c in j["alt"])

        dtab = Dtab.read(
            "/svc => 0.9 * /#/io.l5d.fs/web & 0.1 * /#/io.l5d.fs/web-v0 ;")
        tree = Delegator(interp).delegate(dtab, Path.read("/svc/x"))
        assert isinstance(tree, DUnion)
        for _w, child in tree.weighted:
            assert child.dentry is not None
            assert child.dentry.prefix.show == "/svc"


class TestAdminDelegator:
    def test_delegator_and_bound_names_handlers(self, tmp_path):
        from linkerd_tpu.admin.handlers import linkerd_admin_handlers
        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.http.message import Request

        d = tmp_path / "disco"
        d.mkdir()
        (d / "web").write_text("127.0.0.1 8080\n")
        cfg = f"""
routers:
- protocol: http
  label: out
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {d}
"""

        async def go():
            linker = load_linker(cfg)
            handlers = dict(linkerd_admin_handlers(linker))
            rsp = await handlers["/delegator.json"](
                Request(uri="/delegator.json?router=out&path=/svc/web"))
            data = json.loads(rsp.body)
            assert data["type"] == "delegate"
            assert data["delegate"]["type"] == "leaf"
            assert data["delegate"]["bound"]["id"] == "/#/io.l5d.fs/web"

            rsp = await handlers["/bound-names.json"](
                Request(uri="/bound-names.json"))
            assert json.loads(rsp.body) == {
                "out": {"paths": [], "clients": []}}

            rsp = await handlers["/logging.json"](
                Request(method="POST",
                        uri="/logging.json?logger=test.x&level=DEBUG"))
            assert json.loads(rsp.body)["level"] == "DEBUG"
            import logging
            assert logging.getLogger("test.x").level == logging.DEBUG
            await linker.close()
        run(go())


class TestNamerdDelegateApi:
    def test_api_delegate(self, tmp_path):
        from linkerd_tpu.namer.fs import FsNamer
        from linkerd_tpu.namerd import InMemoryDtabStore, Namerd
        from linkerd_tpu.namerd.http_api import HttpControlService
        from linkerd_tpu.protocol.http.server import HttpServer

        d = tmp_path / "disco"
        d.mkdir()
        (d / "api").write_text("127.0.0.1 9000\n")

        async def go():
            store = InMemoryDtabStore(
                {"default": Dtab.read("/svc => /#/io.l5d.fs;")})
            namer = FsNamer(str(d))
            namer.refresh()
            namerd = Namerd(store, [(Path.read("/io.l5d.fs"), namer)])
            server = await HttpServer(HttpControlService(namerd)).start()

            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.bound_port)
            writer.write(b"GET /api/1/delegate/default?path=/svc/api "
                         b"HTTP/1.1\r\nHost: t\r\nConnection: close\r\n\r\n")
            await writer.drain()
            raw = await reader.read()
            writer.close()
            body = raw.partition(b"\r\n\r\n")[2]
            data = json.loads(body)
            assert data["type"] == "delegate"
            assert data["delegate"]["bound"]["id"] == "/#/io.l5d.fs/api"
            await server.close()
            await namerd.close()
        run(go())
