"""HTTP protocol filters: framing, hop-by-hop, Via, Forwarded, proxy
rewrite, clearContext, l5d-dst headers.

Ref tests: router/http filter suites (FramingFilterTest,
StripHopByHopHeadersFilterTest, AddForwardedHeaderTest etc.).
"""

import asyncio
import json

import pytest

from linkerd_tpu.linker import load_linker
from linkerd_tpu.protocol.http.filters import (
    AddForwardedHeaderFilter, ClearContextFilter, FramingFilter,
    ProxyRewriteFilter, StripHopByHopHeadersFilter, ViaHeaderAppenderFilter,
)
from linkerd_tpu.protocol.http.message import Headers, Request, Response
from linkerd_tpu.protocol.http.server import serve
from linkerd_tpu.router.service import FnService, filters_to_service


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def echo_service(seen):
    async def handler(req: Request) -> Response:
        seen.append(req)
        return Response(status=200, body=b"ok")
    return FnService(handler)


class TestFilters:
    def test_framing_rejects_conflicting_content_length(self):
        async def go():
            svc = filters_to_service([FramingFilter()], echo_service([]))
            req = Request(uri="/")
            req.headers.add("Content-Length", "5")
            req.headers.add("Content-Length", "7")
            rsp = await svc(req)
            assert rsp.status == 400
        run(go())

    def test_strip_hop_by_hop_and_connection_named(self):
        async def go():
            seen = []
            svc = filters_to_service(
                [StripHopByHopHeadersFilter()], echo_service(seen))
            req = Request(uri="/")
            req.headers.set("Connection", "close, X-Custom")
            req.headers.set("X-Custom", "1")
            req.headers.set("Keep-Alive", "timeout=5")
            req.headers.set("X-Keep", "yes")
            await svc(req)
            got = seen[0]
            assert got.headers.get("x-custom") is None
            assert got.headers.get("keep-alive") is None
            assert got.headers.get("connection") is None
            assert got.headers.get("x-keep") == "yes"
        run(go())

    def test_via_appended_both_ways(self):
        async def go():
            seen = []
            svc = filters_to_service(
                [ViaHeaderAppenderFilter()], echo_service(seen))
            req = Request(uri="/")
            req.headers.set("Via", "1.0 upstream")
            rsp = await svc(req)
            assert seen[0].headers.get("via") == "1.0 upstream, 1.1 linkerd"
            assert rsp.headers.get("via") == "1.1 linkerd"
        run(go())

    def test_forwarded_rfc7239(self):
        from linkerd_tpu.protocol.http.filters import mk_forwarded_labeler

        async def go():
            # explicit clear-ip labelers (kind: ip), the pre-round-4 wire
            # format
            seen = []
            svc = filters_to_service(
                [AddForwardedHeaderFilter(
                    by=mk_forwarded_labeler({"kind": "ip"}, "r"),
                    for_=mk_forwarded_labeler({"kind": "ip"}, "r"))],
                echo_service(seen))
            req = Request(uri="/")
            req.ctx["client_addr"] = ("10.0.0.9", 55555)
            req.ctx["server_addr"] = ("10.0.0.1", 4140)
            await svc(req)
            assert seen[0].headers.get("forwarded") == \
                "for=10.0.0.9;by=10.0.0.1"

            # default labelers obfuscate (ref By/For.default =
            # ObfuscatedRandom.PerRequest): a fresh _label per request
            seen2 = []
            svc2 = filters_to_service(
                [AddForwardedHeaderFilter()], echo_service(seen2))
            req2 = Request(uri="/")
            req2.ctx["client_addr"] = ("10.0.0.9", 55555)
            await svc2(req2)
            await svc2(Request(uri="/"))
            h1 = seen2[0].headers.get("forwarded")
            h2 = seen2[1].headers.get("forwarded")
            assert h1.startswith("for=_") and ";by=_" in h1
            assert h1 != h2  # per-request randomness

            # kinds: ip:port quoting, router + static obfuscated labels
            ipport = mk_forwarded_labeler({"kind": "ip:port"}, "r")
            assert ipport(("10.0.0.9", 55555), None) == '"10.0.0.9:55555"'
            router = mk_forwarded_labeler({"kind": "router"}, "myrt")
            assert router(None, None) == "_myrt"
            static = mk_forwarded_labeler(
                {"kind": "static", "label": "dmz"}, "r")
            assert static(None, None) == "_dmz"
            # header-injection labels are refused (RFC 7239 §6.3 syntax)
            import pytest as _pytest
            with _pytest.raises(ValueError):
                mk_forwarded_labeler(
                    {"kind": "static", "label": "dmz; by=evil"}, "r")

            # connectionRandom: keyed on the CONNECTION (so a `by`
            # labeler doesn't collapse on the shared listener addr) —
            # stable per conn_key, distinct across conn_keys
            conn = mk_forwarded_labeler({"kind": "connectionRandom"}, "r")
            listener = ("10.0.0.1", 4140)
            a1 = conn(listener, ("1.1.1.1", 10))
            assert a1 == conn(listener, ("1.1.1.1", 10))
            assert a1 != conn(listener, ("1.1.1.1", 11))
        run(go())

    def test_proxy_rewrite_absolute_uri(self):
        async def go():
            seen = []
            svc = filters_to_service(
                [ProxyRewriteFilter()], echo_service(seen))
            await svc(Request(method="GET",
                              uri="http://web.example.com/a/b?x=1"))
            got = seen[0]
            assert got.uri == "/a/b?x=1"
            assert got.headers.get("host") == "web.example.com"
        run(go())

    def test_clear_context_strips_l5d(self):
        async def go():
            seen = []
            svc = filters_to_service(
                [ClearContextFilter()], echo_service(seen))
            req = Request(uri="/")
            req.headers.set("l5d-ctx-trace", "abc")
            req.headers.set("l5d-dtab", "/a=>/b")
            req.headers.set("X-Ok", "1")
            await svc(req)
            got = seen[0]
            assert got.headers.get("l5d-ctx-trace") is None
            assert got.headers.get("l5d-dtab") is None
            assert got.headers.get("x-ok") == "1"
        run(go())


class TestThroughLinker:
    def test_dst_headers_and_via_end_to_end(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            seen = []
            d = await serve(echo_service(seen))
            (disco / "web").write_text(f"127.0.0.1 {d.bound_port}\n")
            cfg = f"""
routers:
- protocol: http
  label: out
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
    clearContext: true
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            from linkerd_tpu.protocol.http.client import HttpClient
            proxy = HttpClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "web")
                req.headers.set("l5d-dtab", "/svc => /$/fail;")  # cleared
                rsp = await proxy(req)
                assert rsp.status == 200  # injected dtab was stripped
                got = seen[0]
                assert got.headers.get("l5d-dst-service") == "/svc/web"
                assert got.headers.get("l5d-dst-client") == "#.io.l5d.fs.web"
                assert got.headers.get("via") == "1.1 linkerd"
                assert rsp.headers.get("via") == "1.1 linkerd"
            finally:
                await proxy.close()
                await linker.close()
                await d.close()
        run(go())


class TestRequestLoggers:
    def test_file_logger_through_full_linker(self, tmp_path):
        """loggers: [{kind: io.l5d.http.file}] writes one JSON line per
        proxied request from the client-stack position
        (ref: HttpLoggerConfig.scala plugin chain)."""
        import json as _json

        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.http.client import HttpClient
        from linkerd_tpu.protocol.http.server import serve
        from linkerd_tpu.router.service import FnService

        async def go():
            disco = tmp_path / "disco"
            disco.mkdir()
            log_path = tmp_path / "req.log"

            async def handler(req):
                return Response(status=200, body=b"ok")
            backend = await serve(FnService(handler))
            (disco / "web").write_text(f"127.0.0.1 {backend.bound_port}\n")

            linker = load_linker(f"""
routers:
- protocol: http
  label: lg
  dtab: |
    /svc => /#/io.l5d.fs ;
  loggers:
  - kind: io.l5d.http.file
    path: {log_path}
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
""")
            await linker.start()
            proxy = HttpClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            req = Request(uri="/things")
            req.headers.set("Host", "web")
            rsp = await proxy(req)
            assert rsp.status == 200
            await proxy.close()
            await linker.close()
            await backend.close()

            for _ in range(100):
                if log_path.exists() and log_path.read_text().strip():
                    break
                await asyncio.sleep(0.02)
            line = _json.loads(log_path.read_text().strip().splitlines()[0])
            assert line["method"] == "GET"
            assert line["uri"] == "/things"
            assert line["status"] == 200
            assert line["dst"].startswith("/svc/web")
            assert line["latency_ms"] >= 0

        run(go())
