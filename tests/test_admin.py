"""Admin server surface tests."""

import asyncio
import json

from linkerd_tpu.admin.server import AdminServer
from linkerd_tpu.protocol.http import Request
from linkerd_tpu.protocol.http.client import HttpClient
from linkerd_tpu.telemetry.metrics import MetricsTree


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 15))


class TestAdmin:
    def test_endpoints(self):
        async def go():
            mt = MetricsTree()
            mt.counter("rt", "http", "server", "requests").incr(7)
            admin = AdminServer(mt, {"routers": [{"protocol": "http"}]}, port=0)
            await admin.start()
            client = HttpClient("127.0.0.1", admin.bound_port)
            try:
                r = await client(Request(uri="/ping"))
                assert (r.status, r.body) == (200, b"pong")

                r = await client(Request(uri="/config.json"))
                assert json.loads(r.body) == {"routers": [{"protocol": "http"}]}

                r = await client(Request(uri="/admin/metrics.json"))
                flat = json.loads(r.body)
                assert flat["rt/http/server/requests"] == 7

                r = await client(Request(uri="/admin/metrics.json?tree=true"))
                tree = json.loads(r.body)
                assert tree["rt"]["http"]["server"]["requests"]["counter"] == 7

                r = await client(Request(uri="/admin/metrics.json?q=rt/http"))
                assert json.loads(r.body) != {}

                r = await client(Request(uri="/nope"))
                assert r.status == 404
            finally:
                await client.close()
                await admin.close()

        run(go())


def test_identifier_debug_endpoint(tmp_path):
    """/identifier.json runs each http router's identifier on a synthetic
    request (ref: HttpIdentifierHandler.scala:48)."""
    import asyncio
    import json as _json

    from linkerd_tpu.admin.handlers import mk_identifier_handler
    from linkerd_tpu.linker import load_linker
    from linkerd_tpu.protocol.http.message import Request

    disco = tmp_path / "disco"
    disco.mkdir()
    (disco / "web").write_text("127.0.0.1 1\n")

    async def go():
        linker = load_linker(f"""
routers:
- protocol: http
  label: idr
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
""")
        handler = mk_identifier_handler(linker)
        rsp = await handler(Request(
            uri="/identifier.json?method=GET&host=web&path=/x"))
        out = _json.loads(rsp.body)
        assert out["idr"]["path"] == "/svc/web"
        # unidentifiable request reports the per-router error
        rsp2 = await handler(Request(uri="/identifier.json?path=/x"))
        out2 = _json.loads(rsp2.body)
        assert "error" in out2["idr"]
        await linker.close()

    asyncio.run(asyncio.wait_for(go(), 30))
