"""Admin server surface tests."""

import asyncio
import json

from linkerd_tpu.admin.server import AdminServer
from linkerd_tpu.protocol.http import Request
from linkerd_tpu.protocol.http.client import HttpClient
from linkerd_tpu.telemetry.metrics import MetricsTree


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 15))


class TestAdmin:
    def test_endpoints(self):
        async def go():
            mt = MetricsTree()
            mt.counter("rt", "http", "server", "requests").incr(7)
            admin = AdminServer(mt, {"routers": [{"protocol": "http"}]}, port=0)
            await admin.start()
            client = HttpClient("127.0.0.1", admin.bound_port)
            try:
                r = await client(Request(uri="/ping"))
                assert (r.status, r.body) == (200, b"pong")

                r = await client(Request(uri="/config.json"))
                assert json.loads(r.body) == {"routers": [{"protocol": "http"}]}

                r = await client(Request(uri="/admin/metrics.json"))
                flat = json.loads(r.body)
                assert flat["rt/http/server/requests"] == 7

                r = await client(Request(uri="/admin/metrics.json?tree=true"))
                tree = json.loads(r.body)
                assert tree["rt"]["http"]["server"]["requests"]["counter"] == 7

                r = await client(Request(uri="/admin/metrics.json?q=rt/http"))
                assert json.loads(r.body) != {}

                r = await client(Request(uri="/nope"))
                assert r.status == 404
            finally:
                await client.close()
                await admin.close()

        run(go())


def test_identifier_debug_endpoint(tmp_path):
    """/identifier.json runs each http router's identifier on a synthetic
    request (ref: HttpIdentifierHandler.scala:48)."""
    import asyncio
    import json as _json

    from linkerd_tpu.admin.handlers import mk_identifier_handler
    from linkerd_tpu.linker import load_linker
    from linkerd_tpu.protocol.http.message import Request

    disco = tmp_path / "disco"
    disco.mkdir()
    (disco / "web").write_text("127.0.0.1 1\n")

    async def go():
        linker = load_linker(f"""
routers:
- protocol: http
  label: idr
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
""")
        handler = mk_identifier_handler(linker)
        rsp = await handler(Request(
            uri="/identifier.json?method=GET&host=web&path=/x"))
        out = _json.loads(rsp.body)
        assert out["idr"]["path"] == "/svc/web"
        # unidentifiable request reports the per-router error
        rsp2 = await handler(Request(uri="/identifier.json?path=/x"))
        out2 = _json.loads(rsp2.body)
        assert "error" in out2["idr"]
        await linker.close()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_config_check_endpoint(tmp_path):
    """/config-check.json runs l5dcheck over the live linker's own
    config — findings (here: a dentry to an unconfigured namer) come
    back as JSON, clean flips to false."""
    import asyncio
    import json as _json

    from linkerd_tpu.admin.handlers import mk_config_check_handler
    from linkerd_tpu.linker import load_linker
    from linkerd_tpu.protocol.http.message import Request

    disco = tmp_path / "disco"
    disco.mkdir()

    async def go():
        linker = load_linker(f"""
routers:
- protocol: http
  label: checked
  dtab: |
    /svc => /#/io.l5d.fs ;
    /svc/ghost => /#/io.l5d.nothere ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
""")
        handler = mk_config_check_handler(linker)
        out = _json.loads((await handler(
            Request(uri="/config-check.json"))).body)
        assert out["clean"] is False
        rules = {f["rule"] for f in out["findings"]}
        assert "dtab-unbound" in rules
        await linker.close()

        clean = load_linker(f"""
routers:
- protocol: http
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
""")
        out = _json.loads((await mk_config_check_handler(clean)(
            Request(uri="/config-check.json"))).body)
        assert out["clean"] is True and out["findings"] == []
        await clean.close()

    asyncio.run(asyncio.wait_for(go(), 30))


class TestPprofHandlers:
    def test_profile_and_heap_capture(self):
        """/admin/pprof/profile + /heap return text captures of the live
        process (ref: twitter-server's /admin/pprof via Deps.scala:10)."""
        from linkerd_tpu.admin.handlers import (
            pprof_heap_handler, pprof_profile_handler,
        )

        async def go():
            async def busywork():
                # something for the profiler to see
                for _ in range(50):
                    json.dumps({"x": list(range(100))})
                    await asyncio.sleep(0)

            task = asyncio.ensure_future(busywork())
            rsp = await pprof_profile_handler(
                Request(uri="/admin/pprof/profile?seconds=0.2"))
            await task
            assert rsp.status == 200
            text = rsp.body.decode()
            assert "cumulative" in text  # pstats table header
            assert "sleep" in text or "json" in text

            rsp = await pprof_heap_handler(
                Request(uri="/admin/pprof/heap?seconds=0.1"))
            assert rsp.status == 200

            bad = await pprof_profile_handler(
                Request(uri="/admin/pprof/profile?seconds=nope"))
            assert bad.status == 400

        run(go())

    def test_linked_from_admin_surface(self, tmp_path):
        """The handlers are wired into the assembled admin server."""
        from linkerd_tpu.linker import load_linker

        async def go():
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "x").write_text("127.0.0.1 1\n")
            cfg = f"""
admin: {{port: 0}}
routers:
- protocol: http
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            from linkerd_tpu.admin.handlers import linkerd_admin_handlers
            from linkerd_tpu.admin.server import AdminServer

            linker = load_linker(cfg)
            await linker.start()
            # assemble the admin surface the way __main__ does
            admin = AdminServer(linker.metrics, {}, port=0)
            admin.add_handlers(linkerd_admin_handlers(linker))
            await admin.start()
            client = HttpClient("127.0.0.1", admin.bound_port)
            try:
                rsp = await client(Request(
                    uri="/admin/pprof/profile?seconds=0.1"))
                assert rsp.status == 200
                assert b"function calls" in rsp.body
                # dashboard nav links to it
                dash = await client(Request(uri="/"))
                assert b"/admin/pprof/profile" in dash.body
            finally:
                await client.close()
                await admin.close()
                await linker.close()

        run(go())


class TestHttpIdentifierServer:
    def test_standalone_identifier_port(self, tmp_path):
        """admin.httpIdentifierPort serves the identification debugger on
        its own port (ref HttpIdentifierHandler.scala:48 + Main.initAdmin
        wiring)."""
        from linkerd_tpu.admin.handlers import mk_identifier_server
        from linkerd_tpu.linker import load_linker

        async def go():
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text("127.0.0.1 1\n")
            cfg = f"""
admin: {{port: 0, httpIdentifierPort: 0}}
routers:
- protocol: http
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            assert linker.spec.admin.httpIdentifierPort == 0
            await linker.start()
            srv = await mk_identifier_server(
                linker, linker.spec.admin.httpIdentifierPort)
            client = HttpClient("127.0.0.1", srv.bound_port)
            try:
                rsp = await client(Request(
                    uri="/?method=GET&host=web&path=/x"))
                assert rsp.status == 200
                got = json.loads(rsp.body)
                label = linker.routers[0].label
                assert got[label]["path"] == "/svc/web"
            finally:
                await client.close()
                await srv.close()
                await linker.close()

        run(go())
