"""k8s ingress-controller story: IngressCache/identifiers + K8sDtabStore
against scripted fake k8s API servers (the reference's test technique,
EndpointsNamerTest-style watch replay)."""

import asyncio
import json

import pytest

from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.k8s.client import K8sApi
from linkerd_tpu.k8s.ingress import (
    IngressCache, IngressIdentifier, H2IngressIdentifier, parse_ingress,
)
from linkerd_tpu.namerd.store import (
    DtabNamespaceDoesNotExist, DtabVersionMismatch,
)
from linkerd_tpu.namerd.stores import K8sDtabStore
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.router.routing import IdentificationError
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def ingress_obj(name="web-ingress", ns="prod", host="example.com",
                path="/api/.*", svc="api-svc", port="http",
                annotations=None, version="10"):
    return {
        "kind": "Ingress",
        "metadata": {"name": name, "namespace": ns,
                     "resourceVersion": version,
                     "annotations": annotations or {}},
        "spec": {
            "rules": [{
                "host": host,
                "http": {"paths": [{
                    "path": path,
                    "backend": {"serviceName": svc, "servicePort": port},
                }]},
            }],
        },
    }


class FakeIngressApi:
    def __init__(self, items=None):
        self.items = items if items is not None else [ingress_obj()]
        self.events: asyncio.Queue = asyncio.Queue()

    def service(self):
        async def handler(req: Request) -> Response:
            assert "/ingresses" in req.uri
            if "watch=true" not in req.uri:
                return Response(status=200, body=json.dumps({
                    "kind": "IngressList",
                    "metadata": {"resourceVersion": "100"},
                    "items": self.items,
                }).encode())

            async def gen():
                while True:
                    evt = await self.events.get()
                    if evt is None:
                        return
                    yield (json.dumps(evt) + "\n").encode()
            return Response(status=200, body_stream=gen())
        return FnService(handler)


class TestParseIngress:
    def test_both_backend_shapes_and_annotation_filter(self):
        spec = parse_ingress(ingress_obj(), "linkerd")
        assert spec.rules[0].svc == "api-svc"
        assert spec.rules[0].port == "http"

        # networking.k8s.io/v1 shape
        modern = {
            "metadata": {"name": "m", "namespace": "prod"},
            "spec": {
                "defaultBackend": {"service": {
                    "name": "fallback", "port": {"number": 8080}}},
                "rules": [{"http": {"paths": [{
                    "path": "/x",
                    "backend": {"service": {"name": "svc-v1",
                                            "port": {"name": "http"}}},
                }]}}],
            },
        }
        spec2 = parse_ingress(modern, "linkerd")
        assert spec2.rules[0].svc == "svc-v1"
        assert spec2.rules[0].port == "http"
        assert spec2.fallback.svc == "fallback"
        assert spec2.fallback.port == "8080"

        # another controller's ingress is ignored
        other = ingress_obj(
            annotations={"kubernetes.io/ingress.class": "nginx"})
        assert parse_ingress(other, "linkerd") is None
        mine = ingress_obj(
            annotations={"kubernetes.io/ingress.class": "linkerd"})
        assert parse_ingress(mine, "linkerd") is not None


class TestIngressIdentifier:
    def test_identify_watch_update_and_h2(self):
        async def go():
            fake = FakeIngressApi()
            server = await HttpServer(fake.service()).start()
            cfg = IngressIdentifier(host="127.0.0.1",
                                    port=server.bound_port)
            identify = cfg.mk(Path.of("svc"), Dtab.empty())
            try:
                req = Request(method="GET", uri="/api/users",
                              headers=None)
                req.headers = __import__(
                    "linkerd_tpu.protocol.http.message",
                    fromlist=["Headers"]).Headers(
                        [("Host", "example.com")])
                dst = await identify(req)
                # /<prefix>/<namespace>/<port>/<svc> (io.l5d.k8s shape)
                assert dst.path.show == "/svc/prod/http/api-svc"

                # non-matching host -> unidentified
                req2 = Request(method="GET", uri="/api/users")
                req2.headers.set("Host", "other.com")
                with pytest.raises(IdentificationError):
                    await identify(req2)

                # watch event: rule added for other.com -> now identifies
                fake.events.put_nowait({
                    "type": "ADDED",
                    "object": ingress_obj(name="other", host="other.com",
                                          path="/api/.*", svc="other-svc",
                                          port="8080", version="11")})
                for _ in range(100):
                    try:
                        dst2 = await identify(req2)
                        break
                    except IdentificationError:
                        await asyncio.sleep(0.02)
                else:
                    raise AssertionError("watch update not applied")
                assert dst2.path.show == "/svc/prod/8080/other-svc"

                # h2 twin matches on :authority/:path
                h2cfg = H2IngressIdentifier(host="127.0.0.1",
                                            port=server.bound_port)
                h2id = h2cfg.mk(Path.of("svc"), Dtab.empty())
                from linkerd_tpu.protocol.h2.messages import H2Request
                h2req = H2Request(method="GET", path="/api/users",
                                  scheme="http", authority="example.com:80")
                h2dst = await h2id(h2req)
                assert h2dst.path.show == "/svc/prod/http/api-svc"
                h2cfg._cache.stop()
            finally:
                if cfg._cache is not None:
                    cfg._cache.stop()
                await server.close()

        run(go())


class FakeDtabApi:
    """TPR dtab API: list/watch + POST/PUT/DELETE with resourceVersion CAS."""

    def __init__(self):
        self.dtabs = {}  # name -> (dentries, version)
        self.gen = 100
        self.events: asyncio.Queue = asyncio.Queue()

    def _obj(self, name):
        dentries, version = self.dtabs[name]
        return {"apiVersion": "buoyant.io/v1", "kind": "DTab",
                "metadata": {"name": name,
                             "resourceVersion": str(version)},
                "dentries": dentries}

    def service(self):
        async def handler(req: Request) -> Response:
            assert "/apis/buoyant.io/v1/namespaces/default/dtabs" in req.uri
            name = req.uri.split("?")[0].rsplit("/dtabs", 1)[1].lstrip("/")
            if req.method == "GET" and "watch=true" in req.uri:
                async def gen():
                    while True:
                        evt = await self.events.get()
                        if evt is None:
                            return
                        yield (json.dumps(evt) + "\n").encode()
                return Response(status=200, body_stream=gen())
            if req.method == "GET":
                return Response(status=200, body=json.dumps({
                    "kind": "DTabList",
                    "metadata": {"resourceVersion": str(self.gen)},
                    "items": [self._obj(n) for n in self.dtabs],
                }).encode())
            if req.method == "POST":
                obj = json.loads(req.body)
                n = obj["metadata"]["name"]
                if n in self.dtabs:
                    return Response(status=409, body=b"{}")
                self.gen += 1
                self.dtabs[n] = (obj.get("dentries") or [], self.gen)
                self.events.put_nowait(
                    {"type": "ADDED", "object": self._obj(n)})
                return Response(status=201, body=b"{}")
            if req.method == "PUT":
                obj = json.loads(req.body)
                if name not in self.dtabs:
                    return Response(status=404, body=b"{}")
                want = obj["metadata"].get("resourceVersion")
                _, cur = self.dtabs[name]
                if want is not None and want != str(cur):
                    return Response(status=409, body=b"{}")
                self.gen += 1
                self.dtabs[name] = (obj.get("dentries") or [], self.gen)
                self.events.put_nowait(
                    {"type": "MODIFIED", "object": self._obj(name)})
                return Response(status=200, body=b"{}")
            if req.method == "DELETE":
                if name not in self.dtabs:
                    return Response(status=404, body=b"{}")
                obj = self._obj(name)
                del self.dtabs[name]
                self.events.put_nowait({"type": "DELETED", "object": obj})
                return Response(status=200, body=b"{}")
            return Response(status=405)
        return FnService(handler)


async def wait_until(fn, timeout=5.0):
    for _ in range(int(timeout / 0.02)):
        if fn():
            return
        await asyncio.sleep(0.02)
    raise AssertionError("condition not met")


class TestK8sDtabStore:
    def test_crud_cas_and_watch(self):
        async def go():
            from linkerd_tpu.core.activity import Ok

            fake = FakeDtabApi()
            server = await HttpServer(fake.service()).start()
            api = K8sApi("127.0.0.1", server.bound_port, use_tls=False)
            store = K8sDtabStore(api, "default")
            try:
                await store.create("prod", Dtab.read("/svc => /#/io.l5d.fs"))
                act = store.observe("prod")
                await wait_until(
                    lambda: isinstance(act.current, Ok)
                    and act.current.value is not None)
                vd = act.current.value
                assert "io.l5d.fs" in vd.dtab.show

                with pytest.raises(DtabVersionMismatch):
                    await store.update("prod", Dtab.read("/a => /b"),
                                       b"999999")
                await store.update("prod", Dtab.read("/a => /b"), vd.version)
                await wait_until(
                    lambda: isinstance(act.current, Ok)
                    and act.current.value
                    and "/a" in act.current.value.dtab.show)

                names = store.list()
                await wait_until(lambda: "prod" in names.sample())
                await store.put("stage", Dtab.read("/x => /y"))
                await wait_until(lambda: "stage" in names.sample())

                await store.delete("stage")
                await wait_until(lambda: "stage" not in names.sample())
                with pytest.raises(DtabNamespaceDoesNotExist):
                    await store.delete("stage")
            finally:
                store.close()
                await server.close()

        run(go())


class TestIngressEndToEnd:
    def test_linker_routes_by_ingress_rule(self, tmp_path):
        """Full linker: request identified by an Ingress rule from a
        scripted k8s watch stream, bound through the fs namer, proxied to
        a real downstream over sockets."""
        async def go():
            from linkerd_tpu.linker import load_linker
            from linkerd_tpu.protocol.http.client import HttpClient
            from linkerd_tpu.protocol.http.server import serve

            fake = FakeIngressApi()
            k8s_srv = await HttpServer(fake.service()).start()

            async def backend_handler(req: Request) -> Response:
                return Response(status=200, body=b"ingress-backend")
            backend = await serve(FnService(backend_handler))

            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "api-svc").write_text(
                f"127.0.0.1 {backend.bound_port}\n")

            cfg = f"""
routers:
- protocol: http
  label: ingress
  identifier:
    kind: io.l5d.ingress
    host: 127.0.0.1
    port: {k8s_srv.bound_port}
  dtab: |
    /svc/prod/http => /#/io.l5d.fs ;
  servers:
  - port: 0
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/api/users")
                req.headers.set("Host", "example.com")
                rsp = await proxy(req)
                assert (rsp.status, rsp.body) == (200, b"ingress-backend")

                # a request matching no ingress rule is unidentified (400)
                bad = Request(uri="/nope")
                bad.headers.set("Host", "example.com")
                rsp2 = await proxy(bad)
                assert rsp2.status == 400
            finally:
                await proxy.close()
                await linker.close()
                await backend.close()
                await k8s_srv.close()

        run(go())
