"""namerd thrift long-poll interface + io.l5d.namerd interpreter.

Covers the third (and reference-default) control-plane protocol: the
TBinaryProtocol struct DSL, stamped long-poll semantics on the server
(ThriftNamerInterface.scala parity), and the client interpreter's
bind/addr watch loops with live updates on dtab flips and address churn
(ThriftNamerClient.scala parity).
"""

import asyncio

import pytest

from linkerd_tpu.core import Dtab, Path, Var
from linkerd_tpu.core.activity import Ok
from linkerd_tpu.core.addr import Address, Bound
from linkerd_tpu.core.nametree import Leaf
from linkerd_tpu.interpreter.namerd_thrift import ThriftNamerInterpreter
from linkerd_tpu.namer.fs import FsNamer
from linkerd_tpu.namerd import InMemoryDtabStore, Namerd
from linkerd_tpu.namerd import thrift_idl as idl
from linkerd_tpu.namerd.thrift_iface import ThriftNamerIface
from linkerd_tpu.protocol.thrift.binary import (
    decode_struct, encode_struct,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


class TestTreeFromWire:
    def test_weighted_and_alt_wire_trees_convert_and_map(self):
        """Regression: weighted/alt wire nodes must splat into Union/Alt
        varargs — a single-tuple arg crashes NameTree.map downstream."""
        import collections
        interp = ThriftNamerInterpreter.__new__(ThriftNamerInterpreter)
        interp._addrs = collections.OrderedDict()
        interp._tasks = {}
        interp.max_addr_watches = 16
        interp._closed = True  # suppress addr watch loops in unit scope
        leaf = idl.BoundNode(leaf=idl.TBoundName(
            id=[b"#", b"io.l5d.fs", b"web"], residual=[]))
        wire = idl.BoundTree(
            root=idl.BoundNode(alt=[0, 1]),
            nodes={
                0: idl.BoundNode(weighted=[
                    idl.WeightedNodeId(weight=0.75, id=2),
                    idl.WeightedNodeId(weight=0.25, id=3),
                ]),
                1: idl.BoundNode(neg=idl.TVoid()),
                2: leaf,
                3: leaf,
            })
        tree = interp._tree_from_wire(wire)
        mapped = tree.map(lambda b: b)  # must not raise
        union = mapped.trees[0]
        assert [w.weight for w in union.weighted] == [0.75, 0.25]
        for w in union.weighted:
            assert w.tree.value.id_.show == "/#/io.l5d.fs/web"


class TestBinaryProtocol:
    def test_struct_roundtrip(self):
        ref = idl.NameRef(stamp=b"\x00\x01", name=[b"svc", b"web"],
                          ns="default")
        req = idl.BindReq(dtab="/a => /b;", name=ref, clientId=[b"l5d"])
        out = decode_struct(idl.BindReq, encode_struct(req))
        assert out.dtab == "/a => /b;"
        assert out.name.ns == "default"
        assert out.name.name == [b"svc", b"web"]
        assert out.name.stamp == b"\x00\x01"

    def test_union_and_map_roundtrip(self):
        tree = idl.BoundTree(
            root=idl.BoundNode(weighted=[
                idl.WeightedNodeId(weight=0.5, id=0),
                idl.WeightedNodeId(weight=0.5, id=1),
            ]),
            nodes={
                0: idl.BoundNode(leaf=idl.TBoundName(
                    id=[b"#", b"io.l5d.fs", b"a"], residual=[])),
                1: idl.BoundNode(neg=idl.TVoid()),
            })
        out = decode_struct(idl.BoundTree, encode_struct(tree))
        assert out.root.union_field() == "weighted"
        assert len(out.root.weighted) == 2
        assert out.nodes[0].union_field() == "leaf"
        assert out.nodes[0].leaf.id == [b"#", b"io.l5d.fs", b"a"]
        assert out.nodes[1].union_field() == "neg"

    def test_unknown_fields_skipped(self):
        # decoding BindReq bytes as NameRef-only reader must not crash:
        # unknown/mistyped fields are skipped for forward compat
        req = idl.BindReq(dtab="/a => /b;",
                          name=idl.NameRef(ns="x"), clientId=[b"c"])
        out = decode_struct(idl.DtabReq, encode_struct(req))
        assert out is not None


def mk_world(tmp_path, dtab="/svc => /#/io.l5d.fs ;"):
    disco = tmp_path / "disco"
    disco.mkdir(exist_ok=True)
    namer = FsNamer(str(disco))
    store = InMemoryDtabStore()
    # namer prefixes register WITHOUT /#/ — the configured-namer prefix
    # is applied during dtab lookup (namer/core.py CONFIGURED_PREFIX)
    namerd = Namerd(store, [(Path.read("/io.l5d.fs"), namer)])
    return disco, namer, store, namerd, dtab


class TestThriftIfaceEndToEnd:
    def test_bind_addr_and_live_updates(self, tmp_path):
        disco, namer, store, namerd, dtab = mk_world(tmp_path)

        async def go():
            (disco / "web").write_text("127.0.0.1 8080\n")
            namer.refresh()
            await store.create("default", Dtab.read(dtab))
            iface = await ThriftNamerIface(namerd).start()
            interp = ThriftNamerInterpreter(
                "127.0.0.1", iface.bound_port, namespace="default")
            try:
                act = interp.bind(Dtab.empty(), Path.read("/svc/web"))
                for _ in range(100):
                    st = act.current
                    if isinstance(st, Ok):
                        break
                    await asyncio.sleep(0.05)
                tree = act.sample().simplified
                assert isinstance(tree, Leaf)
                assert "io.l5d.fs" in tree.value.id_.show

                # addresses stream through the addr op
                leaf = tree.value
                for _ in range(100):
                    addr = leaf.addr.sample()
                    if isinstance(addr, Bound) and addr.addresses:
                        break
                    await asyncio.sleep(0.05)
                addr = leaf.addr.sample()
                assert Address("127.0.0.1", 8080) in addr.addresses

                # live addr churn: fs file edit -> addr long-poll pushes
                (disco / "web").write_text("127.0.0.1 9090\n")
                namer.refresh()
                for _ in range(100):
                    addr = leaf.addr.sample()
                    if (isinstance(addr, Bound) and
                            Address("127.0.0.1", 9090) in addr.addresses):
                        break
                    await asyncio.sleep(0.05)
                assert Address("127.0.0.1", 9090) in leaf.addr.sample().addresses

                # live dtab flip: store update -> bind long-poll re-binds
                (disco / "web2").write_text("127.0.0.1 7070\n")
                vd = await store.observe("default").to_future()
                await store.update(
                    "default", Dtab.read("/svc/web => /#/io.l5d.fs/web2;"),
                    vd.version)
                for _ in range(100):
                    st = act.current
                    if (isinstance(st, Ok) and
                            isinstance(st.value.simplified, Leaf) and
                            st.value.simplified.value.id_.show.endswith(
                                "web2")):
                        break
                    await asyncio.sleep(0.05)
                tree2 = act.sample().simplified
                assert tree2.value.id_.show.endswith("web2")
            finally:
                interp.close()
                await iface.close()
                await namerd.close()

        run(go())

    def test_unbound_host_is_neg(self, tmp_path):
        disco, namer, store, namerd, dtab = mk_world(tmp_path)

        async def go():
            await store.create("default", Dtab.read(dtab))
            iface = await ThriftNamerIface(namerd).start()
            interp = ThriftNamerInterpreter(
                "127.0.0.1", iface.bound_port, namespace="default")
            try:
                act = interp.bind(Dtab.empty(), Path.read("/svc/ghost"))
                from linkerd_tpu.core.nametree import Neg
                for _ in range(100):
                    st = act.current
                    if isinstance(st, Ok):
                        break
                    await asyncio.sleep(0.05)
                assert isinstance(act.sample().simplified, Neg)
            finally:
                interp.close()
                await iface.close()
                await namerd.close()

        run(go())

    def test_dtab_op_long_poll(self, tmp_path):
        disco, namer, store, namerd, dtab = mk_world(tmp_path)

        async def go():
            await store.create("default", Dtab.read(dtab))
            iface = await ThriftNamerIface(namerd).start()
            from linkerd_tpu.interpreter.namerd_thrift import _encode_call, _decode_reply
            from linkerd_tpu.protocol.thrift.client import ThriftClient
            from linkerd_tpu.protocol.thrift.codec import CALL, ThriftCall
            client = ThriftClient("127.0.0.1", iface.bound_port)
            try:
                payload = _encode_call("dtab", 1, idl.DtabReq(
                    stamp=b"", ns="default", clientId=[b"t"]))
                reply = await client(ThriftCall(
                    payload=payload, name="dtab", seqid=1, type=CALL))
                ref = _decode_reply(reply, idl.DtabRef, idl.DtabFailure)
                assert "/svc" in ref.dtab
                stamp1 = ref.stamp

                # same stamp parks until the store changes
                async def poll_again():
                    p2 = _encode_call("dtab", 2, idl.DtabReq(
                        stamp=stamp1, ns="default", clientId=[b"t"]))
                    r2 = await client(ThriftCall(
                        payload=p2, name="dtab", seqid=2, type=CALL))
                    return _decode_reply(r2, idl.DtabRef, idl.DtabFailure)

                task = asyncio.create_task(poll_again())
                await asyncio.sleep(0.2)
                assert not task.done()  # parked
                vd = await store.observe("default").to_future()
                await store.update(
                    "default", Dtab.read("/svc => /#/changed;"), vd.version)
                ref2 = await asyncio.wait_for(task, 5)
                assert "/#/changed" in ref2.dtab
                assert ref2.stamp != stamp1
            finally:
                await client.close()
                await iface.close()
                await namerd.close()

        run(go())

    def test_delegate_op(self, tmp_path):
        disco, namer, store, namerd, dtab = mk_world(tmp_path)

        async def go():
            (disco / "web").write_text("127.0.0.1 8080\n")
            await store.create("default", Dtab.read(dtab))
            iface = await ThriftNamerIface(namerd).start()
            from linkerd_tpu.interpreter.namerd_thrift import _encode_call, _decode_reply
            from linkerd_tpu.protocol.thrift.client import ThriftClient
            from linkerd_tpu.protocol.thrift.codec import CALL, ThriftCall
            client = ThriftClient("127.0.0.1", iface.bound_port)
            try:
                req = idl.DelegateReq(
                    dtab="",
                    delegation=idl.Delegation(
                        ns="default",
                        tree=idl.TDelegateTree(root=idl.DelegateNode(
                            path=[b"svc", b"web"], dentry=""))),
                    clientId=[b"t"])
                payload = _encode_call("delegate", 1, req)
                reply = await client(ThriftCall(
                    payload=payload, name="delegate", seqid=1, type=CALL))
                d = _decode_reply(reply, idl.Delegation, idl.DelegationFailure)
                # root delegates through the dtab down to a bound leaf
                assert d.tree is not None
                found_leaf = []

                def walk(node):
                    kind = node.contents.union_field()
                    if kind == "boundLeaf":
                        found_leaf.append(node.contents.boundLeaf)
                    elif kind == "delegate":
                        walk(d.tree.nodes[node.contents.delegate])
                    elif kind == "alt":
                        for i in node.contents.alt:
                            walk(d.tree.nodes[i])
                    elif kind == "weighted":
                        for w in node.contents.weighted:
                            walk(d.tree.nodes[w.id])

                walk(d.tree.root)
                assert found_leaf, "no bound leaf in delegation"
                assert b"io.l5d.fs" in found_leaf[0].id
            finally:
                await client.close()
                await iface.close()
                await namerd.close()

        run(go())
