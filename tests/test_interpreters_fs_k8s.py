"""fs and k8s-configMap interpreters: the base dtab followed live from a
watched file / ConfigMap key (ref: FsInterpreterConfig.scala:35 and the
configmap interpreter in interpreter/k8s)."""

import asyncio
import json

from linkerd_tpu.config import instantiate
from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.core.activity import Ok
from linkerd_tpu.core.nametree import Leaf, Neg
from linkerd_tpu.namer.fs import FsNamer
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.router.service import FnService
import linkerd_tpu.interpreter.configs  # noqa: F401 — registers kinds


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


async def wait_until(fn, timeout=5.0):
    for _ in range(int(timeout / 0.02)):
        v = fn()
        if v:
            return v
        await asyncio.sleep(0.02)
    raise AssertionError("condition not met")


class TestFsInterpreter:
    def test_dtab_follows_file(self, tmp_path):
        async def go():
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text("127.0.0.1 9999\n")
            (disco / "api").write_text("127.0.0.1 8888\n")
            dtab_file = tmp_path / "dtab"
            dtab_file.write_text("/svc => /#/io.l5d.fs/web ;\n")

            cfg = instantiate("interpreter", {
                "kind": "io.l5d.fs", "dtabFile": str(dtab_file)})
            namer = FsNamer(str(disco))
            interp = cfg.mk([(Path.read("/io.l5d.fs"), namer)])

            act = interp.bind(Dtab.empty(), Path.read("/svc"))
            state = await wait_until(
                lambda: act.current if isinstance(act.current, Ok) else None)
            assert isinstance(state.value, Leaf)
            bound = state.value.value
            assert bound.id_.show == "/#/io.l5d.fs/web"
            assert {a.port for a in bound.addr.sample().addresses} == {9999}

            # editing the dtab file re-binds live
            dtab_file.write_text("/svc => /#/io.l5d.fs/api ;\n")
            interp._file_dtab.refresh()  # deterministic poll
            act2 = interp.bind(Dtab.empty(), Path.read("/svc"))
            state2 = await wait_until(
                lambda: (act2.current
                         if isinstance(act2.current, Ok)
                         and isinstance(act2.current.value, Leaf)
                         and act2.current.value.value.id_.show.endswith("api")
                         else None))
            assert state2.value.value.id_.show == "/#/io.l5d.fs/api"
            interp._file_dtab.close()
            namer.close()

        run(go())


class FakeConfigMapApi:
    def __init__(self, dtab_text):
        self.data = {"dtab": dtab_text}
        self.version = 5
        self.events: asyncio.Queue = asyncio.Queue()

    def _obj(self):
        return {"kind": "ConfigMap",
                "metadata": {"name": "l5d-dtab", "namespace": "default",
                             "resourceVersion": str(self.version)},
                "data": dict(self.data)}

    def service(self):
        async def handler(req: Request) -> Response:
            assert "/api/v1/namespaces/default/configmaps/l5d-dtab" in req.uri
            if "watch=true" not in req.uri:
                return Response(status=200,
                                body=json.dumps(self._obj()).encode())

            async def gen():
                while True:
                    evt = await self.events.get()
                    if evt is None:
                        return
                    yield (json.dumps(evt) + "\n").encode()
            return Response(status=200, body_stream=gen())
        return FnService(handler)

    def update(self, dtab_text):
        self.data["dtab"] = dtab_text
        self.version += 1
        self.events.put_nowait({"type": "MODIFIED", "object": self._obj()})


class TestConfigMapInterpreter:
    def test_dtab_follows_configmap(self, tmp_path):
        async def go():
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text("127.0.0.1 9999\n")
            (disco / "api").write_text("127.0.0.1 8888\n")

            fake = FakeConfigMapApi("/svc => /#/io.l5d.fs/web ;")
            server = await HttpServer(fake.service()).start()

            cfg = instantiate("interpreter", {
                "kind": "io.l5d.k8s.configMap", "name": "l5d-dtab",
                "host": "127.0.0.1", "port": server.bound_port})
            namer = FsNamer(str(disco))
            interp = cfg.mk([(Path.read("/io.l5d.fs"), namer)])

            act = interp.bind(Dtab.empty(), Path.read("/svc"))
            state = await wait_until(
                lambda: (act.current
                         if isinstance(act.current, Ok)
                         and isinstance(act.current.value, Leaf)
                         else None))
            assert state.value.value.id_.show == "/#/io.l5d.fs/web"

            # configmap edit re-binds live through the watch stream
            fake.update("/svc => /#/io.l5d.fs/api ;")
            act2 = interp.bind(Dtab.empty(), Path.read("/svc"))
            await wait_until(
                lambda: (isinstance(act2.current, Ok)
                         and isinstance(act2.current.value, Leaf)
                         and act2.current.value.value.id_.show.endswith(
                             "api")))
            interp._configmap.close()
            namer.close()
            await server.close()

        run(go())
