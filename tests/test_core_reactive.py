"""Tests for Var / Activity reactive cells.

Modeled on the reference's Events.takeStates-style assertions over state
sequences (/root/reference/test-util/.../Events.scala — SURVEY.md §4).
"""

import asyncio

import pytest

from linkerd_tpu.core import Var, Activity
from linkerd_tpu.core.activity import Ok, Failed, Pending, PENDING


class TestVar:
    def test_sample_update(self):
        v = Var(1)
        assert v.sample() == 1
        assert v.update(2)
        assert v.sample() == 2

    def test_dedup(self):
        v = Var(1)
        seen = []
        v.observe(seen.append)
        assert seen == [1]
        assert not v.update(1)  # dedup
        v.update(2)
        v.update(2)
        assert seen == [1, 2]
        assert v.version == 1

    def test_observe_close_detaches(self):
        v = Var(1)
        seen = []
        h = v.observe(seen.append)
        h.close()
        v.update(2)
        assert seen == [1]
        assert v.observer_count == 0

    def test_map(self):
        v = Var(1)
        m = v.map(lambda x: x * 10)
        assert m.sample() == 10
        v.update(3)
        assert m.sample() == 30

    def test_derived_close_detaches(self):
        v = Var(1)
        m = v.map(lambda x: x * 10)
        v.update(3)
        assert m.sample() == 30
        assert v.observer_count == 1
        m.close()
        assert v.observer_count == 0
        v.update(5)
        assert m.sample() == 30  # frozen after close

    def test_collect_close_detaches(self):
        a, b = Var(1), Var(2)
        c = Var.collect([a, b])
        c.close()
        assert a.observer_count == 0 and b.observer_count == 0

    def test_observer_exception_isolated(self):
        v = Var(1)
        seen = []

        def bad(_):
            raise RuntimeError("boom")

        v.observe(bad, run_now=False)
        v.observe(seen.append, run_now=False)
        v.update(2)  # must not raise, and must reach the second observer
        assert seen == [2]

    def test_collect(self):
        a, b = Var(1), Var(2)
        c = Var.collect([a, b])
        assert c.sample() == (1, 2)
        a.update(5)
        assert c.sample() == (5, 2)

    def test_changes_stream(self):
        async def run():
            v = Var(0)
            out = []

            async def consume():
                async for x in v.changes():
                    out.append(x)
                    if x >= 2:
                        break

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.01)
            v.update(1)
            await asyncio.sleep(0.01)
            v.update(2)
            await asyncio.wait_for(task, 2)
            return out

        assert asyncio.run(run()) == [0, 1, 2]

    def test_changes_conflates(self):
        """Burst updates between polls conflate to the latest state."""
        async def run():
            v = Var(0)
            out = []

            async def consume():
                async for x in v.changes():
                    out.append(x)
                    if x == 99:
                        break

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.01)
            for i in range(1, 100):
                v.update(i)
            await asyncio.wait_for(task, 2)
            return out

        out = asyncio.run(run())
        assert out[0] == 0
        assert out[-1] == 99
        assert len(out) < 100  # conflated


class TestActivity:
    def test_states(self):
        a = Activity.pending()
        assert isinstance(a.current, Pending)
        with pytest.raises(RuntimeError):
            a.sample()
        a.set_value(42)
        assert a.sample() == 42
        a.set_exception(ValueError("boom"))
        with pytest.raises(ValueError):
            a.sample()

    def test_failed_dedup(self):
        a = Activity.pending()
        seen = []
        a.states.observe(seen.append)
        a.set_exception(ValueError("x"))
        a.set_exception(ValueError("x"))  # same type+args: dedup
        assert len(seen) == 2

    def test_map(self):
        a = Activity.value(2)
        m = a.map(lambda x: x + 1)
        assert m.sample() == 3
        a.update(Ok(10))
        assert m.sample() == 11

    def test_map_failure_becomes_failed(self):
        a = Activity.value(0)
        m = a.map(lambda x: 1 // x)
        assert isinstance(m.current, Failed)

    def test_flat_map_switches_inner(self):
        inner1 = Activity.value("one")
        inner2 = Activity.value("two")
        table = {1: inner1, 2: inner2}
        a = Activity.value(1)
        fm = a.flat_map(lambda k: table[k])
        assert fm.sample() == "one"
        a.set_value(2)
        assert fm.sample() == "two"
        # updates to the now-detached inner1 don't leak through
        inner1.set_value("stale")
        assert fm.sample() == "two"
        # updates to the live inner propagate
        inner2.set_value("two!")
        assert fm.sample() == "two!"

    def test_flat_map_pending_upstream(self):
        a = Activity.pending()
        fm = a.flat_map(lambda _: Activity.value(1))
        assert isinstance(fm.current, Pending)
        a.set_value(0)
        assert fm.sample() == 1

    def test_collect(self):
        a, b = Activity.value(1), Activity.pending()
        c = Activity.collect([a, b])
        assert isinstance(c.current, Pending)
        b.set_value(2)
        assert c.sample() == (1, 2)
        b.set_exception(RuntimeError("down"))
        assert isinstance(c.current, Failed)

    def test_collect_close_detaches_inputs(self):
        a, b = Activity.value(1), Activity.value(2)
        c = Activity.collect([a, b])
        assert a.states.observer_count == 1
        c.close()
        assert a.states.observer_count == 0
        assert b.states.observer_count == 0

    def test_changes_with_array_values(self):
        """Vars of numpy arrays must not crash the watch stream on
        ambiguous array __eq__ (version-based change detection)."""
        import numpy as np

        async def run():
            v = Var(np.zeros(4))
            out = []

            async def consume():
                async for x in v.changes():
                    out.append(x.sum())
                    if x.sum() >= 4:
                        break

            task = asyncio.create_task(consume())
            await asyncio.sleep(0.01)
            v.update(np.ones(4))
            await asyncio.wait_for(task, 2)
            return out

        assert asyncio.run(run()) == [0.0, 4.0]

    def test_to_future(self):
        async def run():
            a = Activity.pending()

            async def later():
                await asyncio.sleep(0.01)
                a.set_value("done")

            asyncio.create_task(later())
            return await asyncio.wait_for(a.to_future(), 2)

        assert asyncio.run(run()) == "done"
