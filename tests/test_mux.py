"""mux / thriftmux: codec, multiplexed client/server, routing.

Ref: router/mux + router/thriftmux e2e; finagle mux framing semantics
(tag-matched concurrent exchanges, Tping, Rerr).
"""

import asyncio
import struct

import pytest

from linkerd_tpu.linker import load_linker
from linkerd_tpu.protocol.mux.client import MuxApplicationError, MuxClient
from linkerd_tpu.protocol.mux.codec import (
    Tdispatch, decode_tdispatch, encode_tdispatch, MuxMessage,
)
from linkerd_tpu.protocol.mux.server import MuxServer
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def test_tdispatch_roundtrip():
    mtype, tag, body = encode_tdispatch(
        7, [(b"ctx", b"v")], "/svc/users", [("/a", "/b")], b"PAYLOAD")
    td = decode_tdispatch(MuxMessage(mtype, tag, body))
    assert td.tag == 7
    assert td.contexts == [(b"ctx", b"v")]
    assert td.dest == "/svc/users"
    assert td.dtab == [("/a", "/b")]
    assert td.payload == b"PAYLOAD"


class TestMuxClientServer:
    def test_concurrent_tag_matched_exchanges(self):
        async def go():
            async def handler(td: Tdispatch) -> bytes:
                # reply after a delay proportional to the payload so
                # replies come back OUT of request order
                delay = int(td.payload) / 100
                await asyncio.sleep(delay)
                return b"r" + td.payload

            server = await MuxServer(FnService(handler)).start()
            client = MuxClient("127.0.0.1", server.bound_port)
            results = await asyncio.gather(*(
                client(Tdispatch(0, [], "/svc", [], str(n).encode()))
                for n in (3, 1, 2)))
            assert results == [b"r3", b"r1", b"r2"]
            await client.ping()  # Tping round-trip
            await client.close()
            await server.close()
        run(go())

    def test_handler_error_becomes_rerr(self):
        async def go():
            async def boom(td):
                raise RuntimeError("kapow")
            server = await MuxServer(FnService(boom)).start()
            client = MuxClient("127.0.0.1", server.bound_port)
            with pytest.raises(MuxApplicationError):
                await client(Tdispatch(0, [], "/svc", [], b""))
            await client.close()
            await server.close()
        run(go())


class TestMuxRouter:
    def test_routes_by_dest_with_inline_dtab(self, tmp_path):
        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            seen_dests = []

            async def backend(td: Tdispatch) -> bytes:
                seen_dests.append((td.dest, list(td.dtab)))
                return b"be:" + td.payload
            be = await MuxServer(FnService(backend)).start()
            (disco / "users").write_text(f"127.0.0.1 {be.bound_port}\n")

            cfg = f"""
routers:
- protocol: mux
  label: mx
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            client = MuxClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            # dest "/users" + dstPrefix "/svc" -> /svc/users through dtab
            # (ref: Mux.scala:36 prefix ++ destination)
            rsp = await client(Tdispatch(0, [], "/users", [], b"hi"))
            assert rsp == b"be:hi"
            # the downstream Tdispatch dest is the bound RESIDUAL path,
            # not the logical dest, and the local dtab is consumed (ref:
            # MuxEncodeResidual.scala:1-18). /svc/users binds fully ->
            # empty residual -> "/".
            assert seen_dests[-1] == ("/", [])

            # a deeper dest leaves /extra unbound past the fs file
            rsp = await client(Tdispatch(0, [], "/users/extra", [], b"r"))
            assert rsp == b"be:r"
            assert seen_dests[-1] == ("/extra", [])

            # per-request dtab override (mux carries dtabs natively)
            (disco / "other").write_text(f"127.0.0.1 {be.bound_port}\n")
            rsp = await client(Tdispatch(
                0, [], "/nothere",
                [("/svc/nothere", "/#/io.l5d.fs/other")], b"x"))
            assert rsp == b"be:x"
            assert seen_dests[-1] == ("/", [])

            flat = linker.metrics.flatten()
            assert flat["rt/mx/server/requests"] == 3
            await client.close()
            await linker.close()
            await be.close()
        run(go())


class TestThriftMuxRouter:
    def test_thrift_over_mux(self, tmp_path):
        from linkerd_tpu.protocol.thrift.codec import (
            CALL, REPLY, VERSION_1, parse_message_header,
        )

        def mk_call(name, seqid):
            nb = name.encode()
            return (struct.pack(">I", (VERSION_1 | CALL) & 0xFFFFFFFF)
                    + struct.pack(">I", len(nb)) + nb
                    + struct.pack(">i", seqid) + b"\x00")

        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            async def backend(td: Tdispatch) -> bytes:
                name, seqid, _ = parse_message_header(td.payload)
                nb = name.encode()
                return (struct.pack(">I", (VERSION_1 | REPLY) & 0xFFFFFFFF)
                        + struct.pack(">I", len(nb)) + nb
                        + struct.pack(">i", seqid) + b"\x00")
            be = await MuxServer(FnService(backend)).start()
            (disco / "thriftmux").write_text(f"127.0.0.1 {be.bound_port}\n")

            cfg = f"""
routers:
- protocol: thriftmux
  label: tmx
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
            linker = load_linker(cfg)
            await linker.start()
            client = MuxClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            rsp = await client(Tdispatch(0, [], "", [], mk_call("ping", 3)))
            name, seqid, mtype = parse_message_header(rsp)
            assert (name, seqid, mtype) == ("ping", 3, REPLY)
            await client.close()
            await linker.close()
            await be.close()
        run(go())
