"""Tests for the polymorphic config system (parser + registry + scalars)."""

import dataclasses
from dataclasses import dataclass, field
from typing import List, Optional

import pytest

from linkerd_tpu.config import (
    ConfigError, register, lookup, kinds, clear_category,
    parse_config, instantiate, instantiate_list, Port, HostAndPort,
)
from linkerd_tpu.config.parser import instantiate_as


@pytest.fixture(autouse=True)
def _clean_registry():
    clear_category("testcat")
    yield
    clear_category("testcat")


@dataclass
class Inner:
    name: str
    weight: float = 1.0


def _register_sample():
    @register("testcat", "io.l5d.sample")
    @dataclass
    class SampleConfig:
        host: str
        port: Port
        inners: Optional[List[Inner]] = None
        note: Optional[str] = None

    return SampleConfig


class TestParse:
    def test_yaml_and_json_sniffing(self):
        assert parse_config("a: 1\nb: [1, 2]\n") == {"a": 1, "b": [1, 2]}
        assert parse_config('{"a": 1}') == {"a": 1}

    def test_duplicate_keys_rejected(self):
        with pytest.raises(ConfigError, match="duplicate key"):
            parse_config("a: 1\na: 2\n")

    def test_parse_error(self):
        with pytest.raises(ConfigError):
            parse_config("a: [unclosed\n- x:")


class TestRegistry:
    def test_register_lookup(self):
        cls = _register_sample()
        assert lookup("testcat", "io.l5d.sample") is cls
        assert kinds("testcat") == ("io.l5d.sample",)

    def test_duplicate_kind_rejected(self):
        _register_sample()
        with pytest.raises(ConfigError, match="duplicate kind"):
            @register("testcat", "io.l5d.sample")
            @dataclass
            class Other:
                pass

    def test_unknown_kind(self):
        with pytest.raises(ConfigError, match="unknown testcat kind"):
            lookup("testcat", "io.l5d.nope")


class TestInstantiate:
    def test_full(self):
        _register_sample()
        cfg = instantiate("testcat", {
            "kind": "io.l5d.sample",
            "host": "web",
            "port": 8080,
            "inners": [{"name": "a"}, {"name": "b", "weight": 0.5}],
        })
        assert cfg.kind == "io.l5d.sample"
        assert cfg.host == "web"
        assert int(cfg.port) == 8080
        assert cfg.inners[1].weight == 0.5
        assert cfg.note is None

    def test_unknown_field_rejected(self):
        _register_sample()
        with pytest.raises(ConfigError, match="unknown field 'prot'"):
            instantiate("testcat", {"kind": "io.l5d.sample", "host": "h",
                                    "port": 1, "prot": "x"})

    def test_missing_required(self):
        _register_sample()
        with pytest.raises(ConfigError, match="missing required fields"):
            instantiate("testcat", {"kind": "io.l5d.sample", "host": "h"})

    def test_missing_kind(self):
        with pytest.raises(ConfigError, match="missing 'kind'"):
            instantiate("testcat", {"host": "h"})

    def test_port_range(self):
        _register_sample()
        with pytest.raises(ConfigError, match="port out of range"):
            instantiate("testcat", {"kind": "io.l5d.sample", "host": "h",
                                    "port": 70000})

    def test_list(self):
        _register_sample()
        out = instantiate_list("testcat", [
            {"kind": "io.l5d.sample", "host": "a", "port": 1},
            {"kind": "io.l5d.sample", "host": "b", "port": 2},
        ])
        assert [c.host for c in out] == ["a", "b"]
        assert instantiate_list("testcat", None) == []

    def test_type_mismatch_paths(self):
        _register_sample()
        with pytest.raises(ConfigError, match=r"\.inners"):
            instantiate("testcat", {"kind": "io.l5d.sample", "host": "h",
                                    "port": 1, "inners": "zzz"})

    def test_hostandport(self):
        assert HostAndPort.read("1.2.3.4:80") == HostAndPort("1.2.3.4", Port(80))
        with pytest.raises(ConfigError):
            HostAndPort.read("nohost")

    def test_instantiate_as_plain(self):
        inner = instantiate_as(Inner, {"name": "x", "weight": 2.0})
        assert inner == Inner("x", 2.0)

    def test_kind_field_preserved_on_plain_specs(self):
        """Specs with a real `kind` dataclass field (e.g. loadBalancer)
        must keep the configured value — regression for the silent
        kind-drop bug."""
        from linkerd_tpu.linker import BalancerSpec, ClientSpec

        c = instantiate_as(ClientSpec, {"loadBalancer": {"kind": "ewma"}})
        assert c.loadBalancer == BalancerSpec(kind="ewma")


class TestMetrics:
    def test_counter_gauge_stat(self):
        from linkerd_tpu.telemetry import MetricsTree

        mt = MetricsTree()
        c = mt.counter("rt", "http", "server", "requests")
        c.incr()
        c.incr(4)
        g = mt.gauge("rt", "http", "open_connections")
        g.set(3)
        s = mt.stat("rt", "http", "latency_ms")
        for v in [1, 2, 3, 4, 100]:
            s.add(v)
        flat = mt.flatten()
        assert flat["rt/http/server/requests"] == 5
        assert flat["rt/http/open_connections"] == 3.0
        assert flat["rt/http/latency_ms/count"] == 5
        assert flat["rt/http/latency_ms/max"] == 100
        assert flat["rt/http/latency_ms/p50"] >= 2

    def test_same_leaf_shared(self):
        from linkerd_tpu.telemetry import MetricsTree

        mt = MetricsTree()
        assert mt.counter("a", "b") is mt.counter("a", "b")
        with pytest.raises(ValueError, match="type conflict"):
            mt.stat("a", "b")

    def test_prune(self):
        from linkerd_tpu.telemetry import MetricsTree

        mt = MetricsTree()
        mt.counter("rt", "client", "x", "requests").incr()
        mt.counter("rt", "client", "y", "requests").incr()
        mt.prune("rt", "client", "x")
        flat = mt.flatten()
        assert "rt/client/x/requests" not in flat
        assert flat["rt/client/y/requests"] == 1

    def test_gauge_fn(self):
        from linkerd_tpu.telemetry import MetricsTree

        mt = MetricsTree()
        items = [1, 2, 3]
        mt.gauge("queue", "depth", fn=lambda: len(items))
        assert mt.flatten()["queue/depth"] == 3
        items.append(4)
        assert mt.flatten()["queue/depth"] == 4

    def test_percentiles_monotone(self):
        from linkerd_tpu.telemetry import Stat

        s = Stat()
        for v in range(1000):
            s.add(float(v))
        snap = s.snapshot()
        assert snap["p50"] <= snap["p90"] <= snap["p99"] <= snap["p999"]
        assert 400 <= snap["p50"] <= 600
