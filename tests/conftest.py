"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE jax is imported anywhere,
so multi-chip sharding paths (dp/tp) are exercised without TPU hardware.
Bench (`bench.py`) and the driver's entry checks run outside pytest and see
the real device topology.
"""

import os
import sys

# Force-override: the ambient environment pins JAX onto the real TPU tunnel
# (axon, registered by a sitecustomize that overrides JAX_PLATFORMS); tests
# must run on the virtual 8-device CPU mesh. Backends initialize lazily, so
# setting jax.config before the first device use is sufficient even though
# jax was already imported at interpreter start.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
