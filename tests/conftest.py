"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE jax is imported anywhere,
so multi-chip sharding paths (dp/tp) are exercised without TPU hardware.
Bench (`bench.py`) and the driver's entry checks run outside pytest and see
the real device topology.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
