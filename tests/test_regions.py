"""Hierarchical region tier tests (linkerd_tpu/fleet/regions.py + the
FleetExchange digest roll-up + MeshReactor partition tolerance).

- RegionDigest hostile inputs: malformed / oversized / duplicate-region
  dentries raise ONE error type (ValueError) on decode and cost exactly
  one vote — never a poisoned publish round (mirrors the FleetDoc
  hardening contract);
- RegionView: (generation, seq) fencing, receiver-monotonic WAN
  staleness, the bounded region table against hostile id churn, the
  zombie-leader latch;
- digest exchange in-process: leader-only roll-up gated on live quorum,
  CAS generation takeover, peer regions ingesting digests through the
  shared fleet namespace, the region fence clearing only on legitimate
  re-publish;
- partition -> local-actuate -> heal -> reconcile ordering on the
  reactor, including DeterministicScheduler-pinned interleavings: the
  booked override publishes exactly once on heal (adopt-if-present
  absorbs a successor racing the same dentry), and a healed zombie
  region drops its book without a single store write — it can never
  revert a successor's override.
- end to end on the REAL binaries: 2 regions x 3 linkerds + namerd with
  east's WAN riding a cuttable proxy — cross-region failover publishes
  exactly once and reverts exactly; a WAN partition books a LOCAL
  override on region-local quorum with zero store writes; heal
  reconciles the book with exactly one publish; zero flaps end to end
  (testing/fleet.py RegionFleetHarness).
"""

import asyncio
import json
import time

import pytest

from linkerd_tpu.control.reactor import LocalOverrideBook, LocalStoreClient, MeshReactor
from linkerd_tpu.control.state import HysteresisGovernor
from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.fleet.doc import FleetDoc
from linkerd_tpu.fleet.exchange import FleetConfig
from linkerd_tpu.fleet.regions import (
    DIGEST_FIELDS, MAX_REGIONS, RegionDigest, RegionView,
)
from linkerd_tpu.namerd import InMemoryDtabStore
from linkerd_tpu.telemetry.metrics import MetricsTree


def run(coro, timeout: float = 60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


BASE_DTAB = "/svc => /#/io.l5d.fs ;"
PREFIXES = [Path.read("/io.l5d.fs")]


class _Board:
    degraded = False

    def __init__(self):
        self.levels = {}

    def effective_scores(self):
        return dict(self.levels)


def _digest(region="west", leader="w0", gen=1, seq=1, level=0.1,
            cluster="/svc/web", overrides=()):
    return RegionDigest(region=region, leader=leader, generation=gen,
                        seq=seq,
                        clusters={cluster: {"level": level, "n": 1.0}},
                        overrides=list(overrides), ts=0.0)


def _doc(inst="e1", gen=1, seq=1, level=0.9, cluster="/svc/web",
         region="east"):
    return FleetDoc(instance=inst, generation=gen, seq=seq,
                    clusters={cluster: {"level": level}},
                    overrides=[], ts=0.0, region=region)


def _exchange(store, inst, gen=1, quorum=1, region="east",
              metrics=None, **kw):
    cfg = FleetConfig(instance=inst, generation=gen, quorum=quorum,
                      region=region, wanTtlS=5.0, digestIntervalS=0.5,
                      **kw)
    node = (metrics.scope("control", "fleet")
            if metrics is not None else None)
    return cfg.mk(LocalStoreClient(store) if store is not None else None,
                  metrics_node=node)


class _CuttableClient(LocalStoreClient):
    """LocalStoreClient with a WAN switch: while ``cut``, every store
    op raises OSError (connectivity loss, not store corruption)."""

    def __init__(self, store):
        super().__init__(store)
        self.cut = False
        self.writes = []

    async def fetch(self, ns):
        if self.cut:
            raise OSError("wan down")
        return await super().fetch(ns)

    async def cas(self, ns, dtab, version):
        if self.cut:
            raise OSError("wan down")
        self.writes.append(dtab.show)
        await super().cas(ns, dtab, version)


def _region_reactor(store, board, exchange, metrics=None,
                    client=None, book=None):
    node = (metrics or MetricsTree()).scope("control", "reactor")
    return MeshReactor(
        board, client or LocalStoreClient(store), "default",
        {"/svc/web": "/svc/web-b"},
        governor=HysteresisGovernor(enter=0.6, exit=0.2, quorum=1,
                                    dwell_s=0.0),
        metrics_node=node, namer_prefixes=PREFIXES, fleet=exchange,
        region_failover={"/svc/web": {"west": "/svc/web-west"}},
        local_book=book, heal_probe_interval_s=0.0)


# ---- RegionDigest hostile inputs -------------------------------------------


class TestRegionDigestHostileInputs:
    def test_json_roundtrip(self):
        d = _digest(overrides=["/svc/web"])
        got = RegionDigest.from_json(d.to_json())
        assert got == d

    def test_dentry_rides_a_real_dtab(self):
        d = _digest(region="east", leader="e0")
        prefix, dst = d.to_dentry_parts()
        dtab = Dtab.read(BASE_DTAB + f" {prefix} => {dst} ;")
        found = [RegionDigest.from_dentry_parts(e.prefix.show,
                                                e.dst.show)
                 for e in dtab]
        assert found == [None, d]

    def test_instance_docs_and_digests_never_cross_decode(self):
        # the two tiers share the fleet namespace: each decoder must
        # return None for the other's dentries, never mis-parse
        doc = _doc()
        dp, dd = doc.to_dentry_parts()
        assert RegionDigest.from_dentry_parts(dp, dd) is None
        dig = _digest()
        gp, gd = dig.to_dentry_parts()
        assert FleetDoc.from_dentry_parts(gp, gd) is None

    def test_digest_must_live_under_its_own_region_prefix(self):
        d = _digest(region="west")
        _, dst = d.to_dentry_parts()
        assert RegionDigest.from_dentry_parts("/region/east", dst) is None

    def test_garbage_payload_is_not_a_digest(self):
        assert RegionDigest.from_dentry_parts("/region/east",
                                              "/d/zzzz") is None
        assert RegionDigest.from_dentry_parts("/region/east",
                                              "/d/00ff") is None

    @pytest.mark.parametrize("payload", [
        "[]",                                     # not an object
        '{"r": "East", "l": "e0"}',               # region grammar
        '{"r": "east", "l": "no/slash"}',         # leader grammar
        '{"r": "east", "l": "e0", "c": []}',      # clusters not a map
        '{"r": "east", "l": "e0", "c": {"/svc/web": 3}}',
        '{"r": "east", "l": "e0", "o": {}}',      # overrides not a list
        '{"r": "east", "l": "e0", "g": []}',      # list-valued numeric
        '{"r": "east", "l": "e0", "t": []}',
        '{"r": "east", "l": "e0", '
        '"c": {"/svc/web": {"level": []}}}',
    ])
    def test_malformed_digests_raise_one_error_type(self, payload):
        # the single-error-type contract: peer input failures are
        # ValueError, never TypeError/KeyError leaking out of decode
        with pytest.raises(ValueError):
            RegionDigest.from_json(payload)

    def test_oversized_digest_bounded_on_decode(self):
        from linkerd_tpu.fleet.doc import MAX_CLUSTERS
        d = RegionDigest(
            region="east", leader="e0", generation=1, seq=1,
            clusters={f"/svc/c{i}": {"level": 0.1, "n": 1.0}
                      for i in range(MAX_CLUSTERS * 3)},
            overrides=[f"/svc/c{i}" for i in range(MAX_CLUSTERS * 3)])
        got = RegionDigest.from_json(d.to_json())
        assert len(got.clusters) == MAX_CLUSTERS
        assert len(got.overrides) == MAX_CLUSTERS

    def test_unknown_aggregate_fields_dropped(self):
        got = RegionDigest.from_json(
            '{"r": "east", "l": "e0", "g": 1, "s": 1, '
            '"c": {"/svc/web": {"level": 0.5, "evil": 9e99}}}')
        assert set(got.clusters["/svc/web"]) == set(DIGEST_FIELDS)

    def test_poison_digest_dentry_never_breaks_publish_round(self):
        # a hostile/corrupt digest dentry in the namespace costs
        # exactly itself: the leader's publish round still succeeds
        async def go():
            store = InMemoryDtabStore(
                {"fleet": Dtab.read("/region/east => /d/zzzz ;")})
            ex = _exchange(store, "e0")
            assert await ex.publish_digest_once()
            vd = store.observe("fleet").current.value
            shown = vd.dtab.show
            assert "/region/east => /d/zzzz" in shown  # left alone
            decoded = [RegionDigest.from_dentry_parts(d.prefix.show,
                                                      d.dst.show)
                       for d in vd.dtab]
            good = [d for d in decoded if d is not None]
            assert [d.leader for d in good] == ["e0"]

        run(go())


# ---- RegionView ------------------------------------------------------------


class TestRegionView:
    def test_region_grammar_enforced(self):
        with pytest.raises(ValueError):
            RegionView("East")
        with pytest.raises(ValueError):
            RegionView("east", wan_ttl_s=0.0)

    def test_ordering_fences_stale_digests(self):
        v = RegionView("east", wan_ttl_s=10.0)
        assert v.ingest(_digest(gen=2, seq=5), now=0.0)
        assert not v.ingest(_digest(gen=2, seq=4), now=1.0)  # older seq
        assert not v.ingest(_digest(gen=1, seq=99), now=1.0)  # older gen
        assert v.fenced == 2
        assert v.get("west").seq == 5
        assert v.ingest(_digest(gen=3, seq=1), now=1.0)  # new incarnation

    def test_duplicate_region_dentries_cost_one_vote(self):
        # two dentries for one region in a single ingest pass: the
        # newest ordering wins, the duplicate is fenced — one region,
        # one vote, never two
        v = RegionView("east", wan_ttl_s=10.0)
        v.ingest(_digest(gen=1, seq=2, level=0.1), now=0.0)
        v.ingest(_digest(gen=1, seq=1, level=0.9), now=0.0)
        assert len(v.fresh(now=0.0)) == 1
        assert v.region_level("west", "/svc/web", now=0.0) == 0.1

    def test_wan_staleness_is_receiver_monotonic(self):
        v = RegionView("east", wan_ttl_s=5.0)
        # a sender-side ts from the far future buys nothing: freshness
        # is the RECEIVER's ingest instant
        d = _digest()
        d.ts = 9e12
        v.ingest(d, now=0.0)
        assert v.region_level("west", "/svc/web", now=4.9) == 0.1
        assert v.region_level("west", "/svc/web", now=5.1) is None
        assert v.fresh_peer_regions(now=5.1) == []

    def test_unknown_region_is_unknown_never_healthy(self):
        v = RegionView("east")
        assert v.region_level("west", "/svc/web", now=0.0) is None
        assert v.healthy_regions("/svc/web", below=0.5, now=0.0) == []

    def test_bounded_table_against_hostile_region_churn(self):
        v = RegionView("east", wan_ttl_s=5.0)
        for i in range(MAX_REGIONS):
            assert v.ingest(_digest(region=f"r{i}", leader="w0"),
                            now=0.0)
        # table full of FRESH regions: a minted newcomer is rejected
        assert not v.ingest(_digest(region="minted"), now=1.0)
        assert v.rejected == 1
        # once an entry goes stale the newcomer buys its slot
        assert v.ingest(_digest(region="minted"), now=6.0)
        assert len(v._regions) == MAX_REGIONS

    def test_zombie_leader_latch(self):
        v = RegionView("east")
        v.ingest(_digest(region="east", leader="successor", gen=9),
                 now=0.0)
        v.observe_supersede("e0", was_leader=False)
        assert not v.superseded_leader  # never led: cannot be a zombie
        v.observe_supersede("e0", was_leader=True)
        assert v.superseded_leader

    def test_healthy_regions_sorted_healthiest_first(self):
        v = RegionView("east")
        v.ingest(_digest(region="west", level=0.3), now=0.0)
        v.ingest(_digest(region="apac", leader="a0", level=0.1),
                 now=0.0)
        v.ingest(_digest(region="emea", leader="m0", level=0.9),
                 now=0.0)
        v.ingest(_digest(region="east", leader="e0", level=0.0),
                 now=0.0)  # own region: never a cross-region target
        assert v.healthy_regions("/svc/web", below=0.5,
                                 now=0.0) == ["apac", "west"]


# ---- digest exchange in-process --------------------------------------------


class TestRegionExchange:
    def test_leader_rolls_up_and_peer_region_ingests(self):
        async def go():
            store = InMemoryDtabStore({})
            e0 = _exchange(store, "e0", quorum=2)
            e0.set_source(lambda: {"/svc/web": 0.2},
                          warmed_fn=lambda: True)
            # no fresh same-region peer yet: live quorum unmet, no
            # digest — an isolated instance mints no cross-region
            # evidence
            assert e0.build_region_digest() is None
            e0.view.ingest(_doc(inst="e1", level=0.8))
            assert e0.is_region_leader  # e0 < e1
            assert await e0.publish_digest_once()

            w0 = _exchange(store, "w0", region="west")
            assert await w0.publish_once()  # ingests digests en route
            assert w0.regions.get("east") is not None
            # east's rolled-up level for web = 2nd-highest of
            # {e0: 0.2, e1: 0.8} = 0.2 -> east is a healthy target
            assert w0.region_level("east", "/svc/web") == \
                pytest.approx(0.2)
            assert w0.healthy_peer_regions("/svc/web",
                                           below=0.5) == ["east"]

        run(go())

    def test_follower_never_publishes_digest(self):
        async def go():
            store = InMemoryDtabStore({})
            e1 = _exchange(store, "e1", quorum=2)
            e1.view.ingest(_doc(inst="e0"))  # e0 < e1: e0 leads
            assert not e1.is_region_leader
            assert not await e1.publish_digest_once()
            assert await LocalStoreClient(store).fetch("fleet") is None

        run(go())

    def test_cas_takeover_bumps_generation_past_stored_digest(self):
        async def go():
            store = InMemoryDtabStore({})
            prefix, dst = _digest(region="east", leader="e9", gen=50,
                                  seq=3).to_dentry_parts()
            from linkerd_tpu.control.reactor import cas_modify
            client = LocalStoreClient(store)
            await cas_modify(
                client, "fleet",
                lambda d: Dtab.read(f"{prefix} => {dst} ;"),
                create_if_missing=Dtab.empty())
            e0 = _exchange(store, "e0", gen=1)
            assert await e0.publish_digest_once()
            got = e0.regions.get("east")
            assert got.leader == "e0"
            assert got.generation == 51  # fenced PAST the stored line
            # and the store carries exactly one east digest: ours
            vd = store.observe("fleet").current.value
            digests = [RegionDigest.from_dentry_parts(d.prefix.show,
                                                      d.dst.show)
                       for d in vd.dtab]
            digests = [d for d in digests if d is not None]
            assert [(d.leader, d.generation) for d in digests] == \
                [("e0", 51)]

        run(go())

    def test_region_fence_latches_and_clears_only_on_republish(self):
        async def go():
            store = InMemoryDtabStore({})
            e1 = _exchange(store, "e1", gen=1)
            e1._led_region = True  # this instance HAS led the region
            # a successor's newer-generation digest arrives (store
            # ingest path) while we believe we lead: zombie latch
            prefix, dst = _digest(region="east", leader="zz", gen=10,
                                  seq=1).to_dentry_parts()
            e1.ingest_dtab(Dtab.read(f"{prefix} => {dst} ;"))
            assert e1.region_fenced
            # legitimate re-take: fresh quorum + CAS takeover (the
            # successor's dentry is in the store, so the publish bumps
            # past generation 10) clears the latch
            from linkerd_tpu.control.reactor import cas_modify
            await cas_modify(
                LocalStoreClient(store), "fleet",
                lambda d: Dtab.read(f"{prefix} => {dst} ;"),
                create_if_missing=Dtab.empty())
            assert await e1.publish_digest_once()
            assert not e1.region_fenced
            assert e1.regions.get("east").generation == 11

        run(go())


# ---- partition -> local-actuate -> heal -> reconcile -----------------------


class TestPartitionHealOrdering:
    def test_partition_books_heal_publishes_exactly_once(self):
        """The full ordering on one reactor: WAN cut + SICK books a
        LOCAL override (zero store writes), routers see it through the
        LocalOverrideBook, heal publishes the booked dentry exactly
        once and empties the book."""
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _Board()
            metrics = MetricsTree()
            ex = _exchange(store, "e0")
            client = _CuttableClient(store)
            book = LocalOverrideBook()
            r = _region_reactor(store, board, ex, metrics=metrics,
                                client=client, book=book)

            client.cut = True
            board.levels["/svc/web"] = 0.95
            for t in range(1, 8):
                await r.step(now=float(t))
            flat = metrics.flatten()
            assert client.writes == []  # NOT ONE write while cut
            assert flat["control/reactor/local_actuations"] == 1
            assert flat["control/reactor/partitioned"] == 1.0
            assert "/svc/web" in r.booked
            # the data plane actuation: requests for the sick cluster
            # pick up the booked dentry, unrelated paths never do
            assert len(book.dtab_for(Path.read("/svc/web/GET"))) == 1
            assert len(book.dtab_for(Path.read("/svc/other"))) == 0
            vd = store.observe("default").current.value
            assert "web-b" not in vd.dtab.show

            client.cut = False
            await r.step(now=10.0)
            flat = metrics.flatten()
            assert flat["control/reactor/heal_reconciles"] == 1
            assert flat["control/reactor/overrides_published"] == 1
            assert r.booked == {} and len(book) == 0
            assert r.last_heal_reconcile_ms is not None
            vd = store.observe("default").current.value
            assert vd.dtab.show.count("/svc/web => /svc/web-b") == 1

            # recovery: the published override reverts exactly
            board.levels["/svc/web"] = 0.05
            for t in range(11, 15):
                await r.step(now=float(t))
            flat = metrics.flatten()
            assert flat["control/reactor/overrides_reverted"] == 1
            assert flat["control/reactor/overrides_published"] == 1
            vd = store.observe("default").current.value
            assert vd.dtab.show == Dtab.read(BASE_DTAB).show

        run(go())


    def test_divergent_target_adopts_the_peers_dentry(self):
        """Two reactors trip for the SAME cluster with DIFFERENT
        targets (region digest views diverge under WAN staleness: the
        peer saw west fresh and published cross-region, we did not and
        chose the local failover). The second actuator must ADOPT the
        peer's dentry — never stack a second dentry for the prefix,
        which would let publish order pick the serving target — and
        its revert must remove the ADOPTED dentry exactly."""
        async def go():
            peer = Dtab.read(BASE_DTAB + " /svc/web => /svc/web-west ;")
            store = InMemoryDtabStore({"default": peer})
            board = _Board()
            metrics = MetricsTree()
            r = _region_reactor(store, board, _exchange(store, "e1"),
                                metrics=metrics)

            board.levels["/svc/web"] = 0.95
            for t in range(1, 4):
                await r.step(now=float(t))
            flat = metrics.flatten()
            assert flat["control/reactor/overrides_adopted"] == 1
            assert flat.get("control/reactor/overrides_published", 0) == 0
            assert r.active["/svc/web"].show == "/svc/web => /svc/web-west"
            vd = store.observe("default").current.value
            assert vd.dtab.show.count("/svc/web =>") == 1  # ONE dentry
            assert "web-b" not in vd.dtab.show

            board.levels["/svc/web"] = 0.05
            for t in range(5, 9):
                await r.step(now=float(t))
            vd = store.observe("default").current.value
            assert vd.dtab.show == Dtab.read(BASE_DTAB).show

        run(go())

    def test_heal_racing_successor_publish_adopts_not_duplicates(self):
        """Pinned interleaving: the heal probe's fetch returns the
        PRE-takeover namespace; a fleet peer publishes the same
        failover dentry in the gap before our booked publish fetches.
        Adopt-if-present must absorb it — one dentry in the store, our
        publish count stays zero."""
        from linkerd_tpu.testing.schedules import DeterministicScheduler

        store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
        board = _Board()
        metrics = MetricsTree()
        ex = _exchange(store, "e0")
        book = LocalOverrideBook()
        sched = DeterministicScheduler(
            order=["fetch-1", "peer-publish", "fetch-2"])

        class _Gated(_CuttableClient):
            def __init__(self, store):
                super().__init__(store)
                self.fetches = 0

            async def fetch(self, ns):
                self.fetches += 1
                await sched.point(f"fetch-{self.fetches}")
                return await super().fetch(ns)

        client = _Gated(store)
        r = _region_reactor(store, board, ex, metrics=metrics,
                            client=client, book=book)
        # partitioned with a booked override, now healing
        r._partitioned = True
        r._partitioned_at = 0.0
        r.booked["/svc/web"] = Dtab.read(
            "/svc/web => /svc/web-b ;")[0]
        book.set("/svc/web", r.booked["/svc/web"])
        board.levels["/svc/web"] = 0.95

        async def peer_publish():
            await sched.point("peer-publish")
            peer = LocalStoreClient(store)
            vd = await peer.fetch("default")
            await peer.cas("default",
                           vd.dtab + Dtab.read(
                               "/svc/web => /svc/web-b ;"),
                           vd.version)
            return True

        sched.run_sync(r.step(now=50.0), peer_publish())
        flat = metrics.flatten()
        assert flat["control/reactor/heal_reconciles"] == 1
        assert flat["control/reactor/overrides_adopted"] == 1
        assert flat["control/reactor/overrides_published"] == 0
        assert r.booked == {} and len(book) == 0
        vd = store.observe("default").current.value
        assert vd.dtab.show.count("/svc/web => /svc/web-b") == 1

    def test_healed_zombie_region_never_reverts_successors_override(self):
        """The zombie-region pin: this instance led east, got cut off
        with a booked override, and a successor took the region over
        (newer-generation digest + its own override in the store). On
        heal the fetched state is ingested BEFORE any write — the
        region fence latches, the book drops, and the zombie makes
        ZERO store writes, now or on later steps."""
        async def go():
            successor_dtab = (
                BASE_DTAB + " /svc/web => /svc/web-b ; "
                + "%s => %s ;" % _digest(
                    region="east", leader="zz", gen=99,
                    seq=1).to_dentry_parts())
            store = InMemoryDtabStore(
                {"default": Dtab.read(successor_dtab)})
            board = _Board()
            metrics = MetricsTree()
            ex = _exchange(store, "e0")
            ex._led_region = True  # we led east before the cut
            client = _CuttableClient(store)
            book = LocalOverrideBook()
            r = _region_reactor(store, board, ex, metrics=metrics,
                                client=client, book=book)
            r._partitioned = True
            r._partitioned_at = 0.0
            r.booked["/svc/web"] = Dtab.read(
                "/svc/web => /svc/web-b ;")[0]
            book.set("/svc/web", r.booked["/svc/web"])
            board.levels["/svc/web"] = 0.95

            for t in range(50, 56):
                await r.step(now=float(t))
            assert ex.region_fenced  # the successor's digest latched it
            assert client.writes == []  # NOT ONE write, ever
            assert r.booked == {} and len(book) == 0
            assert r.active == {}  # nothing to revert with, either
            vd = store.observe("default").current.value
            assert Dtab.read(successor_dtab).show == vd.dtab.show
            # ... and the healthy verdict cannot revert the successor's
            # override either (the classic zombie failure mode)
            board.levels["/svc/web"] = 0.0
            for t in range(60, 64):
                await r.step(now=float(t))
            assert client.writes == []
            vd = store.observe("default").current.value
            assert "/svc/web => /svc/web-b" in vd.dtab.show

        run(go())


# ---- end to end on the real binaries ---------------------------------------


class TestRegionEndToEnd:
    def test_partition_local_actuation_heal_and_xregion_failover(self):
        """2 regions x 3 linkerds + namerd as subprocesses, east's
        store/digest traffic riding a cuttable WAN proxy. The drill:

        1. east-quorum fault, WAN up: exactly ONE cross-region publish
           (east's traffic lands on west's replica set), exact revert
           on recovery;
        2. WAN cut, same fault: east books a LOCAL override on its
           region-local quorum — traffic shifts to the local replica
           set with ZERO store writes;
        3. heal: the booked override publishes to the store exactly
           once (adopt-if-present absorbs the second east instance),
           recovery reverts to the exact base namespace.

        Two publishes total across the whole drill = zero flaps.
        Governor values are the measured ones from the flat fleet e2e
        (see TestFleetEndToEnd in test_fleet.py for the diagnosis):
        warmup 300 / enter 0.6 / exit 0.45 / streak 20."""
        from linkerd_tpu.testing.fleet import RegionFleetHarness, _http

        async def go():
            # wan_ttl_s 8.0: under full-suite CPU contention the 0.5s
            # digest roll-up can lag multiple cycles; with the default
            # 3.0s TTL west's digest goes momentarily stale at the
            # moment east's governor trips, and the reactor (correctly)
            # falls back to the LOCAL failover instead of cross-region.
            # The test wants the cross-region path, so give the WAN an
            # honest-to-load freshness horizon.
            h = RegionFleetHarness(east=2, west=1, wan_ttl_s=8.0,
                                   warmup_batches=300,
                                   governor_quorum=20, enter=0.6,
                                   exit=0.45)
            await h.start()
            try:
                h.start_traffic(interval_s=0.02)
                await h.warm(settle_s=3.0)
                east = [h.instance_ids[i] for i in h.region_insts("east")]

                def west_fresh() -> bool:
                    # sync: wait_for runs predicates in a worker thread
                    for i in h.region_insts("east"):
                        _, body = _http("GET", "http://127.0.0.1:"
                                        f"{h.admin_ports[i]}/regions.json")
                        w = json.loads(body).get("regions", {}).get("west")
                        if not (w and w["fresh"]):
                            return False
                    return True

                # -- 1. cross-region failover, WAN up -------------------
                # don't inject until every east instance sees a FRESH
                # west digest — whichever reactor trips first must have
                # the cross-region target in view
                await h.wait_for(west_fresh, 30,
                                 "west digest fresh at both east insts")
                h.primary.fault_insts = set(east)
                await h.wait_metric(
                    "control/reactor/overrides_published", 1, 90)
                await h.wait_for(lambda: h._route_sync(0) == b"W", 30,
                                 "east traffic on west's replica set")
                assert await h.fleet_metric_sum(
                    "control/reactor/xregion_overrides") == 1
                assert await h.flap_count() == 1

                h.primary.fault_insts = set()
                await h.wait_metric(
                    "control/reactor/overrides_reverted", 1, 90)
                await h.wait_for(lambda: h._route_sync(0) == b"A", 30,
                                 "east traffic back on the primary")
                assert await h.flap_count() == 1  # revert, not re-publish
                await asyncio.sleep(3.0)  # governor streaks drain

                # -- 2. WAN cut + fault: LOCAL actuation ----------------
                await h.partition_east()
                await asyncio.sleep(h.wan_ttl_s + 1.0)  # digests stale
                h.primary.fault_insts = set(east)
                await h.wait_metric(
                    "control/reactor/local_actuations", 1, 90)
                await h.wait_for(lambda: h._route_sync(0) == b"B", 30,
                                 "east traffic on the LOCAL replica set")
                assert await h.flap_count() == 1  # NOT ONE store write

                # -- 3. heal: booked publish exactly once ---------------
                rev0 = await h.fleet_metric_sum(
                    "control/reactor/overrides_reverted")
                await h.heal_east()
                await h.wait_metric(
                    "control/reactor/heal_reconciles", 1, 60)
                await h.wait_metric(
                    "control/reactor/overrides_published", 2, 60)
                assert await h.flap_count() == 2

                h.primary.fault_insts = set()
                await h.wait_metric("control/reactor/overrides_reverted",
                                    rev0 + 1, 90)
                await h.wait_for(lambda: h._route_sync(0) == b"A", 30,
                                 "east traffic back on the primary")
                assert await h.flap_count() == 2  # zero flaps end to end

                def namespace_is_base() -> bool:
                    _, body = _http(
                        "GET", h._namerd_url("/api/1/dtabs/default"))
                    return json.loads(body) == [
                        {"prefix": "/svc", "dst": "/#/io.l5d.fs"}]

                await h.wait_for(namespace_is_base, 10,
                                 "exact namespace revert")

                # the region tier saw itself: every instance knows its
                # region, east observed west's digest and vice versa
                for i in range(h.n):
                    st = await h.region_status(i)
                    assert st["region"] == h.region_of(i), st
                    peer = "west" if h.region_of(i) == "east" else "east"
                    assert peer in st["regions"], st
            finally:
                await h.stop()

        run(go(), timeout=420)


# ---- static-gate coverage ---------------------------------------------------


class TestStaticGateCoverage:
    def test_region_tier_is_inside_the_race_gate_scope(self):
        # the tier-1 race gate (test_race_analysis.TestRepoGate) scans
        # DEFAULT_SCOPE; the region tier must never drop out of it
        import os

        from tools.analysis.core import Project
        from tools.analysis.race import DEFAULT_SCOPE

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        project = Project(repo, [p for p in DEFAULT_SCOPE
                                 if os.path.exists(os.path.join(repo, p))])
        rels = {s.rel for s in project.sources}
        assert "linkerd_tpu/fleet/regions.py" in rels
        assert "linkerd_tpu/control/reactor.py" in rels
        assert "linkerd_tpu/fleet/exchange.py" in rels
