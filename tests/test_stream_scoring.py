"""Stream sentinel: incremental scoring + mid-stream actuation.

Covers the Python plane of linkerd_tpu/streams/ end to end — the
frame-delta tracker (pinned bit-identical against the engines' C
accumulator), the bounded sentinel table under hostile stream churn,
specialist-head (route) pinning at stream open, the h2 frame observer's
sampling cadence and shed actuation, a chaos leg where one sick stream
is detected and shed mid-flight while its neighbors finish untouched,
the h1 tunnel passthrough (101 Upgrade / CONNECT byte relay with pool
handoff), and the h2 client's GOAWAY drain (in-flight streams below
last_stream_id finish on the old connection instead of being aborted).
"""

import asyncio
import itertools

import numpy as np
import pytest

from linkerd_tpu import native
from linkerd_tpu.protocol.h2.client import H2Client
from linkerd_tpu.protocol.h2.frames import ENHANCE_YOUR_CALM
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response
from linkerd_tpu.protocol.h2.server import H2Server
from linkerd_tpu.protocol.h2.stream import DataFrame, H2Stream, StreamReset
from linkerd_tpu.router.service import FnService
from linkerd_tpu.streams import (
    ACTION_OBSERVE, ACTION_RST, FRAME_ANOMALY, FRAME_DATA,
    FRAME_WINDOW_UPDATE, H2FrameObserver, StreamSentinel, StreamTracker,
    fold_key, stream_feature_vector,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


# ── C vs Python featurization parity ─────────────────────────────────────


@pytest.mark.skipif(not native.available(), reason="needs libl5d_native")
class TestFeaturizationParity:
    """The Python tracker must reproduce the engines' float32 EWMA
    arithmetic BIT-FOR-BIT: the in-plane scorer and the Python-side
    sentinel see the same stream, so their features must agree exactly
    or the two governors drift apart."""

    def trace(self, seed, n=500):
        rng = np.random.default_rng(seed)
        kinds = rng.integers(0, 3, size=n).astype(np.int32)
        gaps = (rng.random(n, dtype=np.float32) * 250.0).astype(np.float32)
        sizes = (rng.random(n, dtype=np.float32) * 65536.0).astype(
            np.float32)
        return kinds, gaps, sizes

    @pytest.mark.parametrize("seed", [7, 1234, 99991])
    def test_bit_identical_accumulators(self, seed):
        kinds, gaps, sizes = self.trace(seed)
        want = native.stream_accum(kinds, gaps, sizes)
        t = StreamTracker()
        for k, g, s in zip(kinds, gaps, sizes):
            t.frame(int(k), float(g), float(s))
        got = t.as_row()
        # uint32 view: equality of every BIT, not approximate closeness
        assert got.dtype == np.float32 and want.dtype == np.float32
        assert np.array_equal(got.view(np.uint32), want.view(np.uint32)), \
            f"C={want} py={got}"

    def test_data_only_trace_bit_identical(self):
        n = 256
        kinds = np.zeros(n, np.int32)
        gaps = np.linspace(0.5, 900.0, n).astype(np.float32)
        sizes = np.geomspace(1.0, 1e6, n).astype(np.float32)
        want = native.stream_accum(kinds, gaps, sizes)
        t = StreamTracker()
        for g, s in zip(gaps, sizes):
            t.frame(FRAME_DATA, float(g), float(s))
        assert np.array_equal(t.as_row().view(np.uint32),
                              want.view(np.uint32))


class TestStreamTracker:
    def test_frame_kinds_update_the_right_counters(self):
        t = StreamTracker()
        t.frame(FRAME_DATA, 10.0, 100.0)
        t.frame(FRAME_WINDOW_UPDATE, 5.0)
        t.frame(FRAME_ANOMALY, 1.0)
        assert (t.frames, t.data_frames, t.wu_frames, t.anomalies) == \
            (3, 1, 1, 1)
        assert t.bytes == 100

    def test_first_frame_seeds_the_ewmas(self):
        t = StreamTracker()
        t.frame(FRAME_DATA, 42.0, 1000.0)
        assert float(t.gap_ewma_ms) == 42.0
        assert float(t.bpf_ewma) == 1000.0
        assert float(t.gap_dev_ms) == 0.0

    def test_feature_vector_reflects_anomalies(self):
        t = StreamTracker()
        t.frame(FRAME_DATA, 10.0, 100.0)
        x_ok = stream_feature_vector(t, "/svc/a")
        t.frame(FRAME_ANOMALY, 1.0)
        x_bad = stream_feature_vector(t, "/svc/a")
        # status one-hot: 2xx while clean, 5xx once the stream misbehaves
        assert x_ok[2] == 1.0 and x_bad[5] == 1.0

    def test_fold_key_is_24_bit_and_never_zero(self):
        assert fold_key(0x1FFFFFF) == 0xFFFFFF
        assert fold_key(0x1000000) == 1  # folds to 0 -> reserved 1
        assert fold_key(42) == 42


# ── sentinel: governor + bounded table ───────────────────────────────────


class TestStreamSentinel:
    def mk(self, **kw):
        kw.setdefault("enter", 0.7)
        kw.setdefault("exit", 0.3)
        kw.setdefault("quorum", 2)
        kw.setdefault("dwell_s", 0.0)
        return StreamSentinel(**kw)

    def test_sick_edge_fires_rst_exactly_once(self):
        shed = []
        s = self.mk(on_rst=shed.append)
        t = 100.0
        for i in range(12):
            s.observe(5, 1.0, now=t + i)
        assert [e.key for e in shed] == [5]
        assert s.sick_transitions == 1 and s.actions_fired == 1

    def test_quorum_gates_flappy_scores(self):
        shed = []
        s = self.mk(on_rst=shed.append, quorum=3)
        t = 100.0
        # alternate high/low: EWMA never holds above enter for 3 in a row
        for i in range(30):
            s.observe(9, 1.0 if i % 2 == 0 else 0.0, now=t + i)
        assert shed == []

    def test_observe_action_never_fires_callbacks(self):
        shed = []
        s = self.mk(action=ACTION_OBSERVE, on_rst=shed.append)
        for i in range(12):
            got = s.observe(1, 1.0, now=100.0 + i)
        assert got is None or got == ACTION_OBSERVE
        assert shed == [] and s.sick_transitions == 1
        assert s.actions_fired == 0

    def test_unscored_samples_never_move_the_governor(self):
        shed = []
        s = self.mk(on_rst=shed.append)
        for i in range(20):
            s.observe(3, 1.0, scored=False, now=100.0 + i)
        assert shed == [] and s.sick_transitions == 0
        assert s.entry(3).samples == 20 and s.entry(3).scored == 0

    def test_hostile_churn_stays_bounded(self):
        # a client opening and abandoning streams must buy eviction of
        # the stalest CLOSED entries, never table growth
        s = self.mk(table_cap=64)
        for k in range(1, 10_001):
            s.open(k, now=float(k))
            s.observe(k, 0.1, now=float(k))
            s.close(k, now=float(k))
        assert len(s) <= 64
        assert s.evicted == 10_000 - 64
        # the governor table was forget()-ed along the way too
        assert len(s._gov.keys()) <= 64

    def test_live_streams_are_never_evicted(self):
        s = self.mk(table_cap=8)
        for k in range(1, 9):
            s.open(k, now=float(k))          # 8 live entries at cap
        for k in range(100, 200):
            s.open(k, now=float(k))
            s.close(k, now=float(k))         # churn through closed ones
        assert all(s.entry(k) is not None for k in range(1, 9)), \
            "a live stream was evicted"

    def test_route_pinned_at_open(self):
        # the specialist head scoring a stream is chosen at stream open
        # and must not flip mid-stream when routing changes
        s = self.mk()
        s.open(7, route="/svc/a", now=1.0)
        s.open(7, route="/svc/b", now=2.0)   # re-open: liveness refresh
        assert s.entry(7).route == "/svc/a"

    def test_ingest_rows_skips_request_rows_and_fires_on_streams(self):
        from linkerd_tpu.telemetry.linerate import (
            NATIVE_COL_KIND, NATIVE_COL_SCORE, NATIVE_COL_SCORED,
            NATIVE_COL_SEQ, NATIVE_COL_STREAM, NATIVE_ROW_WIDTH)
        shed = []
        s = self.mk(on_rst=shed.append)
        rows = np.zeros((14, NATIVE_ROW_WIDTH), np.float32)
        rows[0, NATIVE_COL_KIND] = 0.0       # request row: ignored
        rows[1, NATIVE_COL_KIND] = 1.0       # stream row, key 0: ignored
        for i in range(2, 14):
            rows[i, NATIVE_COL_KIND] = 1.0
            rows[i, NATIVE_COL_STREAM] = 77.0
            rows[i, NATIVE_COL_SEQ] = float(i * 8)
            rows[i, NATIVE_COL_SCORE] = 1.0
            rows[i, NATIVE_COL_SCORED] = 1.0
        fired = s.ingest_rows(rows, now=100.0)
        assert fired == 1 and [e.key for e in shed] == [77]
        assert s.entry(77).frames == 13 * 8
        assert len(s._streams) == 1

    def test_snapshot_shape_matches_native_streams_json(self):
        s = self.mk()
        s.open(3, route="/svc/x", now=1.0)
        s.observe(3, 0.4, now=2.0)
        snap = s.snapshot()
        assert snap["enabled"] is True and snap["count"] == 1
        ent = snap["by_stream"]["3"]
        for field in ("kind", "samples", "scored", "score_ewma",
                      "frames", "bytes", "sick", "live"):
            assert field in ent
        assert ent["route"] == "/svc/x"

    def test_score_ewma_matches_native_alpha(self):
        # alpha 1/4 in float32, same as the engines' gov_observe
        s = self.mk()
        want = np.float32(0.0)
        for i, score in enumerate([1.0, 0.5, 0.25, 1.0]):
            s.observe(1, score, now=100.0 + i)
            want = np.float32(want + np.float32(
                np.float32(0.25) * np.float32(np.float32(score) - want)))
        assert s.entry(1).score_ewma.view(np.uint32) == want.view(np.uint32)


# ── h2 frame observer (unit, stub connection) ────────────────────────────


class _StubConn:
    def __init__(self):
        self.sheds = []

    def shed_stream(self, sid, code=ENHANCE_YOUR_CALM):
        self.sheds.append((sid, code))
        return True


def mk_observer(scorer=None, action="rst", **sent_kw):
    sent_kw.setdefault("enter", 0.7)
    sent_kw.setdefault("exit", 0.3)
    sent_kw.setdefault("quorum", 2)
    sent_kw.setdefault("dwell_s", 0.0)
    sent = StreamSentinel(action=ACTION_RST if action == "rst"
                          else ACTION_OBSERVE, **sent_kw)
    keys = itertools.count(1)
    obs = H2FrameObserver(sent, next_skey=lambda: next(keys),
                          scorer=scorer, sample_every_frames=2,
                          min_gap_ms=0, action=action)
    conn = _StubConn()
    return obs.bind(conn), conn, sent


class TestH2FrameObserver:
    def test_sampling_cadence_respects_frame_budget(self):
        samples = []
        obs, _, _ = mk_observer(scorer=lambda x: samples.append(1) or 0.0)
        for i in range(10):
            obs.on_frame(1, FRAME_DATA, 10, now=float(i))
        assert len(samples) == 5  # every 2nd frame

    def test_min_gap_bounds_sampling_rate(self):
        samples = []
        obs, _, _ = mk_observer(scorer=lambda x: samples.append(1) or 0.0)
        obs.min_gap_s = 1.0
        for i in range(10):
            obs.on_frame(1, FRAME_DATA, 10, now=100.0 + i * 0.01)
        assert len(samples) == 1  # all frames inside one gap window

    def test_sick_stream_is_shed_and_closed(self):
        obs, conn, sent = mk_observer(scorer=lambda x: 1.0)
        for i in range(40):
            obs.on_frame(9, FRAME_DATA, 100, now=100.0 + i)
            if conn.sheds:
                break
        assert conn.sheds and conn.sheds[0][0] == 9
        assert conn.sheds[0][1] == ENHANCE_YOUR_CALM
        assert obs.sheds == 1
        assert 9 not in obs._slots  # slot retired with the stream

    def test_observe_action_detects_but_never_sheds(self):
        obs, conn, sent = mk_observer(scorer=lambda x: 1.0,
                                      action="observe")
        for i in range(40):
            obs.on_frame(9, FRAME_DATA, 100, now=100.0 + i)
        assert sent.sick_transitions == 1
        assert conn.sheds == [] and obs.sheds == 0

    def test_no_scorer_never_sheds(self):
        obs, conn, _ = mk_observer(scorer=None)
        for i in range(40):
            obs.on_frame(9, FRAME_DATA, 100, now=100.0 + i)
        assert conn.sheds == []

    def test_close_marks_all_streams_closed(self):
        obs, _, sent = mk_observer()
        for sid in (1, 3, 5):
            obs.on_frame(sid, FRAME_DATA, 10, now=100.0)
        obs.close()
        assert obs._slots == {}
        assert all(not e.live for e in sent._streams.values())

    def test_chaos_one_sick_stream_neighbors_untouched(self):
        # the chaos contract: the sick stream is detected and shed while
        # every neighbor completes — neighbor success must hold >= 0.99
        big = np.log1p(10_000.0)
        obs, conn, sent = mk_observer(
            scorer=lambda x: 1.0 if x[8] > big else 0.0)
        healthy = list(range(1, 41, 2))[:20]  # 20 odd sids
        sick = 99
        for i in range(40):
            now = 100.0 + i
            for sid in healthy:
                obs.on_frame(sid, FRAME_DATA, 64, now=now)
            obs.on_frame(sick, FRAME_DATA, 60_000, now=now)
        # only the sick stream is ever shed (the stub conn can't
        # actually stop it, so its re-created slot may trip again)
        assert conn.sheds and {s for s, _ in conn.sheds} == {sick}
        shed_neighbors = sum(1 for s, _ in conn.sheds if s != sick)
        assert 1.0 - shed_neighbors / len(healthy) >= 0.99


# ── e2e: mid-stream shed on the Python h2 data plane ─────────────────────


class TestH2MidStreamShed:
    def serve(self, scorer):
        sent = StreamSentinel(enter=0.7, exit=0.3, quorum=2, dwell_s=0.0)
        keys = itertools.count(1)

        def factory():
            return H2FrameObserver(
                sent, next_skey=lambda: next(keys), scorer=scorer,
                sample_every_frames=2, min_gap_ms=0, action="rst")

        async def handler(req: H2Request) -> H2Response:
            body, _ = await req.stream.read_all()
            return H2Response(status=200,
                              body=b"got:%d" % len(body))

        server = H2Server(FnService(handler),
                          stream_observer_factory=factory)
        return server, sent

    def test_sick_stream_shed_while_neighbors_complete(self):
        big = np.log1p(10_000.0)
        server, sent = self.serve(
            scorer=lambda x: 1.0 if x[8] > big else 0.0)

        async def one(client, sid_payload, frames):
            src = H2Stream()
            task = asyncio.ensure_future(client(H2Request(
                method="POST", path="/s", authority="t", stream=src)))
            for _ in range(frames):
                src.offer(DataFrame(sid_payload))
                await asyncio.sleep(0.001)
            src.offer(DataFrame(b"", eos=True))
            rsp = await task
            body, _ = await rsp.stream.read_all()
            return rsp.status, body

        async def go():
            await server.start()
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                healthy = [one(client, b"x" * 64, 24) for _ in range(10)]
                sick = asyncio.ensure_future(
                    one(client, b"y" * 60_000, 24))
                results = await asyncio.gather(*healthy)
                with pytest.raises(StreamReset) as ei:
                    await sick
                assert ei.value.error_code == ENHANCE_YOUR_CALM
                # every neighbor finished clean: success 1.0 >= 0.99
                ok = sum(1 for st, body in results
                         if st == 200 and body == b"got:%d" % (64 * 24))
                assert ok / len(results) >= 0.99
                assert sent.sick_transitions == 1
            finally:
                await client.close()
                await server.close()

        run(go())

    def test_healthy_streams_only_no_actuation(self):
        server, sent = self.serve(scorer=lambda x: 0.0)

        async def go():
            await server.start()
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                rsp = await client(H2Request(
                    method="POST", path="/s", authority="t",
                    body=b"k" * 4096))
                body, _ = await rsp.stream.read_all()
                assert body == b"got:4096"
                assert sent.sick_transitions == 0
                # the table saw the stream (DATA frames were tracked)
                assert len(sent) >= 1
            finally:
                await client.close()
                await server.close()

        run(go())


# ── h2 client GOAWAY drain (regression pin) ──────────────────────────────


class TestGoawayDrain:
    def test_inflight_stream_drains_not_aborts(self):
        """A GOAWAY'd singleton conn must keep serving its in-flight
        streams (at/below last_stream_id) while NEW requests ride a
        fresh connection; the old conn closes only once it empties."""
        gate = asyncio.Event()

        async def handler(req: H2Request) -> H2Response:
            if req.path == "/slow":
                await gate.wait()
            body, _ = await req.stream.read_all()
            return H2Response(status=200, body=b"ok:" + req.path.encode())

        async def go():
            server = await H2Server(FnService(handler)).start()
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                # warm the conn, then hold one stream in flight
                await (await client(H2Request(
                    path="/warm", authority="t"))).stream.read_all()
                old = client._conn
                slow = asyncio.ensure_future(
                    client(H2Request(path="/slow", authority="t")))
                while old.active_streams == 0:
                    await asyncio.sleep(0.01)
                # the peer says goodbye covering the in-flight stream
                old.goaway_received = True
                # a new request must NOT abort the in-flight one: it
                # rides a fresh conn; the old conn parks for drain
                r2 = await client(H2Request(path="/new", authority="t"))
                b2, _ = await r2.stream.read_all()
                assert b2 == b"ok:/new"
                assert client._conn is not old
                assert old in client._draining
                assert not old.is_closed and not slow.done(), \
                    "drain must not abort in-flight streams"
                # let the held stream finish on the OLD conn
                gate.set()
                rsp = await slow
                body, _ = await rsp.stream.read_all()
                assert body == b"ok:/slow"
                # ...after which the drain watcher retires it
                for _ in range(100):
                    if old.is_closed and old not in client._draining:
                        break
                    await asyncio.sleep(0.02)
                assert old.is_closed and old not in client._draining
            finally:
                await client.close()
                await server.close()

        run(go())

    def test_close_tears_down_draining_conns(self):
        gate = asyncio.Event()

        async def handler(req: H2Request) -> H2Response:
            if req.path == "/slow":
                await gate.wait()
            body, _ = await req.stream.read_all()
            return H2Response(status=200, body=b"ok")

        async def go():
            server = await H2Server(FnService(handler)).start()
            client = H2Client("127.0.0.1", server.bound_port)
            try:
                await (await client(H2Request(
                    path="/a", authority="t"))).stream.read_all()
                old = client._conn
                slow = asyncio.ensure_future(
                    client(H2Request(path="/slow", authority="t")))
                while old.active_streams == 0:
                    await asyncio.sleep(0.01)
                old.goaway_received = True
                await (await client(H2Request(
                    path="/b", authority="t"))).stream.read_all()
                # the held stream keeps the old conn parked in drain
                assert old in client._draining
            finally:
                # close() with the gate still shut: the draining conn
                # must be torn down, not leaked
                await client.close()
                gate.set()
                await server.close()
            assert client._draining == [] and old.is_closed
            slow.cancel()
            try:
                await slow
            except (asyncio.CancelledError, Exception):
                pass

        run(go())


# ── h1 tunnels: 101 Upgrade / CONNECT byte relay ─────────────────────────


async def _echo_upstream():
    """A raw upstream that speaks 101-upgrade and CONNECT, then echoes
    every byte prefixed with ``echo:``."""

    async def on_conn(reader, writer):
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = await reader.read(1024)
            if not chunk:
                writer.close()
                return
            data += chunk
        head = data.split(b"\r\n", 1)[0]
        if head.startswith(b"CONNECT"):
            writer.write(b"HTTP/1.1 200 Connection established\r\n\r\n")
        elif b"no-upgrade" in data:
            # misbehaving upstream: 101 nobody asked for
            writer.write(b"HTTP/1.1 101 Switching Protocols\r\n"
                         b"Upgrade: echo\r\nConnection: Upgrade\r\n\r\n")
        else:
            writer.write(b"HTTP/1.1 101 Switching Protocols\r\n"
                         b"Upgrade: echo\r\nConnection: Upgrade\r\n\r\n")
        await writer.drain()
        while True:
            chunk = await reader.read(65536)
            if not chunk:
                break
            writer.write(b"echo:" + chunk)
            await writer.drain()
        writer.close()

    return await asyncio.start_server(on_conn, "127.0.0.1", 0)


class TestH1Tunnels:
    async def _front(self):
        from linkerd_tpu.protocol.http.client import HttpClient
        from linkerd_tpu.protocol.http.server import HttpServer

        upstream = await _echo_upstream()
        up_port = upstream.sockets[0].getsockname()[1]
        client = HttpClient("127.0.0.1", up_port, max_connections=2)
        front = await HttpServer(client).start()
        return upstream, client, front

    async def _raw(self, port, head: bytes):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(head)
        await writer.drain()
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = await reader.read(1024)
            assert chunk, f"closed before response head: {data!r}"
            data += chunk
        head_end = data.index(b"\r\n\r\n") + 4
        return reader, writer, data[:head_end], data[head_end:]

    def test_websocket_style_upgrade_tunnels_bytes(self):
        async def go():
            upstream, client, front = await self._front()
            try:
                reader, writer, head, rest = await self._raw(
                    front.bound_port,
                    b"GET /ws HTTP/1.1\r\nHost: x\r\n"
                    b"Connection: Upgrade\r\nUpgrade: echo\r\n\r\n")
                assert b"101" in head.split(b"\r\n")[0]
                writer.write(b"hello")
                await writer.drain()
                got = rest
                while len(got) < len(b"echo:hello"):
                    got += await reader.read(1024)
                assert got == b"echo:hello"
                writer.close()
                # the relay ends and the pooled slot is released
                for _ in range(100):
                    if client._n_open == 0:
                        break
                    await asyncio.sleep(0.02)
                assert client._n_open == 0
            finally:
                await front.close()
                await client.close()
                upstream.close()

        run(go())

    def test_connect_tunnels_bytes(self):
        async def go():
            upstream, client, front = await self._front()
            try:
                reader, writer, head, rest = await self._raw(
                    front.bound_port,
                    b"CONNECT example.test:443 HTTP/1.1\r\n"
                    b"Host: example.test:443\r\n\r\n")
                assert b" 200" in head.split(b"\r\n")[0]
                writer.write(b"tls-ish bytes")
                await writer.drain()
                got = rest
                while len(got) < len(b"echo:tls-ish bytes"):
                    got += await reader.read(1024)
                assert got == b"echo:tls-ish bytes"
                writer.close()
            finally:
                await front.close()
                await client.close()
                upstream.close()

        run(go())

    def test_unsolicited_101_is_a_gateway_error(self):
        # the upstream switches protocols without being asked: the
        # front must answer 502, not relay bytes the client can't frame
        async def go():
            upstream, client, front = await self._front()
            try:
                _, writer, head, _ = await self._raw(
                    front.bound_port,
                    b"GET /no-upgrade HTTP/1.1\r\nHost: x\r\n\r\n")
                assert b"502" in head.split(b"\r\n")[0]
                writer.close()
                for _ in range(100):
                    if client._n_open == 0:
                        break
                    await asyncio.sleep(0.02)
                assert client._n_open == 0  # pool slot not leaked
            finally:
                await front.close()
                await client.close()
                upstream.close()

        run(go())

    def test_plain_requests_still_pool(self):
        # the tunnel branch must not disturb ordinary keep-alive reuse
        async def go():
            async def on_conn(reader, writer):
                while True:
                    data = b""
                    while b"\r\n\r\n" not in data:
                        chunk = await reader.read(1024)
                        if not chunk:
                            writer.close()
                            return
                        data += chunk
                    writer.write(b"HTTP/1.1 200 OK\r\n"
                                 b"Content-Length: 2\r\n\r\nok")
                    await writer.drain()

            from linkerd_tpu.protocol.http.client import HttpClient
            from linkerd_tpu.protocol.http.message import Request
            upstream = await asyncio.start_server(on_conn, "127.0.0.1", 0)
            port = upstream.sockets[0].getsockname()[1]
            client = HttpClient("127.0.0.1", port)
            try:
                for _ in range(3):
                    rsp = await client(Request(method="GET", uri="/"))
                    assert rsp.status == 200 and rsp.body == b"ok"
                assert client._n_open == 1  # one conn, reused
            finally:
                await client.close()
                upstream.close()

        run(go())


# ── admin surface ────────────────────────────────────────────────────────


class TestStreamsAdminEndpoint:
    def test_streams_json_exposes_sentinel_state(self):
        from linkerd_tpu.admin.handlers import linkerd_admin_handlers
        from linkerd_tpu.admin.server import AdminServer
        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.http.client import HttpClient
        from linkerd_tpu.protocol.http.message import Request

        import json

        cfg = """
routers:
- protocol: h2
  label: grpc
  dtab: |
    /svc => /$/inet/127.0.0.1/1 ;
  servers: [{port: 0}]
  streamScoring:
    sampleEveryFrames: 4
    enter: 0.9
    exit: 0.6
"""

        async def go():
            linker = load_linker(cfg)
            await linker.start()
            admin = AdminServer(linker.metrics, linker.config_dict,
                                port=0)
            admin.add_handlers(linkerd_admin_handlers(linker))
            await admin.start()
            try:
                client = HttpClient("127.0.0.1", admin.bound_port)
                rsp = await client(Request(method="GET",
                                           uri="/streams.json"))
                assert rsp.status == 200
                doc = json.loads(rsp.body)
                sent = doc["grpc"]["sentinel"]
                assert sent["enabled"] is True
                assert sent["action"] == "rst" and sent["count"] == 0
                await client.close()
            finally:
                await admin.close()
                await linker.close()

        run(go())


# ── native engine config surface (no traffic) ────────────────────────────


@pytest.mark.skipif(not native.available(), reason="needs libl5d_native")
class TestNativeStreamConfig:
    def test_stream_cfg_accepted_and_snapshot_enabled(self):
        eng = native.FastPathEngine()
        eng.set_stream_cfg(enabled=True, sample_every_frames=4,
                           min_gap_ms=5, table_cap=128, enter=0.8,
                           exit=0.4, quorum=2, dwell_ms=100,
                           action="observe")
        snap = eng.streams()
        assert snap.get("enabled") and snap.get("count", 0) == 0
        eng.close()

    def test_bad_stream_action_rejected(self):
        eng = native.FastPathEngine()
        with pytest.raises(ValueError):
            eng.set_stream_cfg(action="nuke")
        eng.close()

    def test_tunnel_guard_is_h1_only(self):
        eng = native.FastPathEngine()
        eng.set_tunnel_guard(idle_ms=1000, max_bytes=1 << 20)
        eng.close()
        h2 = native.H2FastPathEngine()
        with pytest.raises(RuntimeError):
            h2.set_tunnel_guard(idle_ms=1000)
        h2.close()
