"""Native TLS termination/origination on the fastpath engines.

The epoll engines (native/fastpath.cpp, native/h2_fastpath.cpp) now
terminate and originate TLS through the dlopen'd OpenSSL runtime
(native/tls_shim.h memory-BIO pump): ALPN selects the protocol, session
tickets resume, handshake failures are accounted, and a TLS'd exchange
is byte-identical to its cleartext twin. Python stays the control plane
(cert/key config via the ``tls:`` linker block, stats export) — and when
the OpenSSL runtime is absent, a fastPath router that needs TLS falls
back to the Python data plane instead of failing the load.
"""

import asyncio
import socket
import ssl
import subprocess
import time

import pytest

from linkerd_tpu import native
from linkerd_tpu.protocol.h2.client import H2Client
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response, Headers
from linkerd_tpu.protocol.h2.server import H2Server
from linkerd_tpu.router.service import FnService

pytestmark = pytest.mark.skipif(
    not (native.ensure_built()
         and native.FastPathEngine.tls_runtime_available()),
    reason="native toolchain or OpenSSL runtime unavailable")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60))


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """Self-signed localhost cert (openssl CLI; the repo adds no
    cert-generation dependency)."""
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048",
             "-keyout", key, "-out", cert, "-days", "2", "-nodes",
             "-subj", "/CN=localhost",
             "-addext", "subjectAltName=DNS:localhost,DNS:echo"],
            check=True, capture_output=True, timeout=60)
    except (OSError, subprocess.SubprocessError):
        pytest.skip("openssl CLI unavailable")
    return cert, key


def client_ctx(cert: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_verify_locations(cert)
    return ctx


def h1_get(sock, host=b"echo") -> bytes:
    sock.sendall(b"GET / HTTP/1.1\r\nHost: " + host + b"\r\n\r\n")
    buf = b""
    while b"\r\n\r\n" not in buf or not buf.endswith(b"ok"):
        d = sock.recv(4096)
        if not d:
            break
        buf += d
    return buf


@pytest.fixture
def h1_backend():
    """Threaded keep-alive HTTP/1.1 backend with a fixed response."""
    import threading

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(16)

    def serve():
        while True:
            try:
                c, _ = lsock.accept()
            except OSError:
                return

            def one(c=c):
                buf = b""
                while True:
                    try:
                        d = c.recv(4096)
                    except OSError:
                        return
                    if not d:
                        return
                    buf += d
                    while b"\r\n\r\n" in buf:
                        buf = buf.split(b"\r\n\r\n", 1)[1]
                        c.sendall(b"HTTP/1.1 200 OK\r\n"
                                  b"Content-Length: 2\r\n\r\nok")

            threading.Thread(target=one, daemon=True).start()

    threading.Thread(target=serve, daemon=True).start()
    yield lsock.getsockname()[1]
    lsock.close()


class TestAlpnNegotiation:
    def test_h1_engine_selects_http11(self, certs, h1_backend):
        cert, key = certs
        eng = native.FastPathEngine()
        eng.set_tls(cert, key)
        port = eng.listen_tls("127.0.0.1", 0)
        eng.start()
        eng.set_route("echo", [("127.0.0.1", h1_backend)])
        try:
            ctx = client_ctx(cert)
            ctx.set_alpn_protocols(["h2", "http/1.1"])
            with socket.create_connection(("127.0.0.1", port)) as s:
                with ctx.wrap_socket(s, server_hostname="localhost") as ts:
                    assert ts.selected_alpn_protocol() == "http/1.1"
                    assert b"200 OK" in h1_get(ts)
            tls = eng.stats()["tls"]
            assert tls["alpn_http1"] == 1
            assert tls["handshakes"] == 1
        finally:
            eng.close()

    def test_h2_engine_selects_h2(self, certs):
        cert, key = certs

        async def go():
            async def echo(req):
                body, _ = await req.stream.read_all(max_bytes=1 << 20)
                return H2Response(status=200, body=body)

            backend = await H2Server(FnService(echo)).start()
            eng = native.H2FastPathEngine()
            eng.set_tls(cert, key)
            port = eng.listen_tls("127.0.0.1", 0)
            eng.start()
            eng.set_route("echo", [("127.0.0.1", backend.bound_port)])
            try:
                ctx = client_ctx(cert)
                # H2Client pins ALPN to ["h2"]; the engine must select it
                h2c = H2Client("127.0.0.1", port, ssl_context=ctx,
                               server_hostname="localhost")
                rsp = await h2c(H2Request(method="POST", path="/x",
                                          authority="echo", body=b"alpn"))
                body, _ = await rsp.stream.read_all(max_bytes=1 << 20)
                assert (rsp.status, body) == (200, b"alpn")
                await h2c.close()
                tls = eng.stats()["tls"]
                assert tls["alpn_h2"] == 1
                assert tls["handshakes"] == 1
            finally:
                eng.close()
                await backend.close()

        run(go())


class TestH1Tls:
    def test_byte_identical_tls_vs_cleartext(self, certs, h1_backend):
        cert, key = certs
        eng = native.FastPathEngine()
        eng.set_tls(cert, key)
        tls_port = eng.listen_tls("127.0.0.1", 0)
        clear_port = eng.listen("127.0.0.1", 0)
        eng.start()
        eng.set_route("echo", [("127.0.0.1", h1_backend)])
        try:
            ctx = client_ctx(cert)
            with socket.create_connection(("127.0.0.1", tls_port)) as s:
                with ctx.wrap_socket(s, server_hostname="localhost") as ts:
                    via_tls = h1_get(ts)
                    # keep-alive: a second exchange on the same TLS conn
                    assert h1_get(ts) == via_tls
            with socket.create_connection(("127.0.0.1", clear_port)) as s:
                via_clear = h1_get(s)
            assert via_tls == via_clear
            assert b"200 OK" in via_tls
        finally:
            eng.close()

    def test_handshake_failure_accounted(self, certs, h1_backend):
        cert, key = certs
        eng = native.FastPathEngine()
        eng.set_tls(cert, key)
        port = eng.listen_tls("127.0.0.1", 0)
        eng.start()
        try:
            # cleartext HTTP at a TLS listener is not a handshake
            with socket.create_connection(("127.0.0.1", port)) as s:
                s.sendall(b"GET / HTTP/1.1\r\nHost: echo\r\n\r\n")
                assert s.recv(4096) == b""  # closed, no plaintext answer
            for _ in range(100):
                if eng.stats()["tls"]["failures"]:
                    break
                time.sleep(0.02)
            tls = eng.stats()["tls"]
            assert tls["failures"] == 1
            assert tls["handshakes"] == 0
        finally:
            eng.close()

    def test_session_resumption(self, certs, h1_backend):
        cert, key = certs
        eng = native.FastPathEngine()
        eng.set_tls(cert, key)
        port = eng.listen_tls("127.0.0.1", 0)
        eng.start()
        eng.set_route("echo", [("127.0.0.1", h1_backend)])
        try:
            ctx = client_ctx(cert)
            with socket.create_connection(("127.0.0.1", port)) as s:
                with ctx.wrap_socket(s, server_hostname="localhost") as ts:
                    assert b"200 OK" in h1_get(ts)
                    session = ts.session  # ticket arrived with the data
            with socket.create_connection(("127.0.0.1", port)) as s:
                with ctx.wrap_socket(s, server_hostname="localhost",
                                     session=session) as ts:
                    assert b"200 OK" in h1_get(ts)
            tls = eng.stats()["tls"]
            assert tls["handshakes"] == 2
            assert tls["resumed"] == 1
        finally:
            eng.close()


class TestH2Tls:
    def test_byte_identical_tls_vs_cleartext(self, certs):
        cert, key = certs

        async def go():
            async def echo(req):
                body, _ = await req.stream.read_all(max_bytes=1 << 20)
                return H2Response(status=200, body=b"rsp:" + body,
                                  headers=Headers([("x-via", "backend")]))

            backend = await H2Server(FnService(echo)).start()
            eng = native.H2FastPathEngine()
            eng.set_tls(cert, key)
            tls_port = eng.listen_tls("127.0.0.1", 0)
            clear_port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("echo", [("127.0.0.1", backend.bound_port)])

            async def fetch(port, **kw):
                h2c = H2Client("127.0.0.1", port, **kw)
                rsp = await h2c(H2Request(method="POST", path="/x",
                                          authority="echo", body=b"b"))
                body, _ = await rsp.stream.read_all(max_bytes=1 << 20)
                hdrs = sorted((k, v) for k, v in rsp.headers.items()
                              if not k.startswith(":"))
                await h2c.close()
                return rsp.status, hdrs, body

            try:
                via_tls = await fetch(
                    tls_port, ssl_context=client_ctx(cert),
                    server_hostname="localhost")
                via_clear = await fetch(clear_port)
                assert via_tls == via_clear
                assert via_tls[2] == b"rsp:b"
            finally:
                eng.close()
                await backend.close()

        run(go())

    def test_upstream_tls_origination_and_resumption(self, certs):
        """The engine originates TLS to a TLS backend (route authority =
        SNI = verified name) and, after the multiplexed upstream conn
        dies, the replacement conn resumes the cached session."""
        cert, key = certs

        async def go():
            async def echo(req):
                body, _ = await req.stream.read_all(max_bytes=1 << 20)
                return H2Response(status=200, body=body)

            sctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            sctx.load_cert_chain(cert, key)
            backend = await H2Server(FnService(echo),
                                     ssl_context=sctx).start()
            eng = native.H2FastPathEngine()
            eng.set_client_tls(verify=True, ca_path=cert)
            port = eng.listen("127.0.0.1", 0)
            eng.start()
            eng.set_route("echo", [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            try:
                rsp = await h2c(H2Request(method="POST", path="/x",
                                          authority="echo", body=b"one"))
                body, _ = await rsp.stream.read_all(max_bytes=1 << 20)
                assert body == b"one"
                # kill the engine's upstream conn (GOAWAY + FIN); the
                # close harvests the ticket for the endpoint cache
                for conn in list(backend._conns):
                    await conn.close()
                await asyncio.sleep(0.05)
                rsp = await h2c(H2Request(method="POST", path="/x",
                                          authority="echo", body=b"two"))
                body, _ = await rsp.stream.read_all(max_bytes=1 << 20)
                assert body == b"two"
                tls = eng.stats()["tls"]
                assert tls["upstream_handshakes"] == 2
                assert tls["upstream_resumed"] >= 1
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())

    def test_bad_upstream_cert_fails_request(self, certs, tmp_path):
        """Verification is real: an upstream presenting a cert the
        engine does not trust must not receive the request."""
        cert, key = certs
        other = str(tmp_path / "other.pem"), str(tmp_path / "other.key")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048",
             "-keyout", other[1], "-out", other[0], "-days", "2",
             "-nodes", "-subj", "/CN=echo"],
            check=True, capture_output=True, timeout=60)

        async def go():
            async def echo(req):
                return H2Response(status=200, body=b"never")

            sctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            sctx.load_cert_chain(other[0], other[1])  # untrusted issuer
            backend = await H2Server(FnService(echo),
                                     ssl_context=sctx).start()
            eng = native.H2FastPathEngine()
            eng.set_client_tls(verify=True, ca_path=cert)
            port = eng.listen("127.0.0.1", 0)
            eng.set_response_timeout_ms(500)
            eng.start()
            eng.set_route("echo", [("127.0.0.1", backend.bound_port)])
            h2c = H2Client("127.0.0.1", port)
            try:
                rsp = await asyncio.wait_for(
                    h2c(H2Request(method="POST", path="/x",
                                  authority="echo", body=b"x")), 15)
                assert rsp.status in (502, 504)
                for _ in range(100):
                    if eng.stats()["tls"]["upstream_failures"]:
                        break
                    await asyncio.sleep(0.02)
                assert eng.stats()["tls"]["upstream_failures"] >= 1
            finally:
                await h2c.close()
                eng.close()
                await backend.close()

        run(go())


class TestLinkerTls:
    def mk_cfg(self, disco, cert, key, client_tls=True) -> str:
        client = (f"""
  client:
    tls:
      trustCerts: [{cert}]
""" if client_tls else "")
        return f"""
routers:
- protocol: h2
  label: h2tls
  fastPath: true
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
    tls:
      certPath: {cert}
      keyPath: {key}
{client}
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""

    def test_tls_both_legs_through_assembled_linker(self, certs, tmp_path):
        """TLS in -> native proxy -> TLS out, with handshake counters in
        the MetricsTree (the operator-visible proof the NATIVE engine —
        not a Python fallback — served the TLS traffic)."""
        from linkerd_tpu.linker import load_linker

        cert, key = certs
        disco = tmp_path / "disco"
        disco.mkdir()

        async def go():
            async def echo(req):
                body, _ = await req.stream.read_all(max_bytes=1 << 20)
                return H2Response(status=200, body=b"lk:" + body)

            sctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            sctx.load_cert_chain(cert, key)
            backend = await H2Server(FnService(echo),
                                     ssl_context=sctx).start()
            (disco / "echo").write_text(f"127.0.0.1 {backend.bound_port}\n")
            linker = load_linker(self.mk_cfg(disco, cert, key))
            await linker.start()
            port = linker.routers[0].server_ports[0]
            h2c = H2Client("127.0.0.1", port, ssl_context=client_ctx(cert),
                           server_hostname="localhost")
            try:
                rsp = await h2c(H2Request(method="POST", path="/x",
                                          authority="echo", body=b"e2e"))
                body, _ = await rsp.stream.read_all(max_bytes=1 << 20)
                assert body == b"lk:e2e"
                await asyncio.sleep(1.2)  # one stats poll
                flat = linker.metrics.flatten()
                assert flat.get("rt/h2tls/fastpath/tls/handshakes", 0) >= 1
                assert flat.get(
                    "rt/h2tls/fastpath/tls/upstream_handshakes", 0) >= 1
            finally:
                await h2c.close()
                await linker.close()
                await backend.close()

        run(go())

    def test_python_fallback_when_runtime_unavailable(
            self, certs, tmp_path, monkeypatch):
        """No OpenSSL runtime: the fastPath router gracefully falls back
        to the Python data plane, which still serves the TLS config."""
        from linkerd_tpu.linker import _FastPathRouter, load_linker

        cert, key = certs
        disco = tmp_path / "disco"
        disco.mkdir()
        monkeypatch.setattr(native.H2FastPathEngine,
                            "tls_runtime_available",
                            classmethod(lambda cls: False))

        async def go():
            async def echo(req):
                body, _ = await req.stream.read_all(max_bytes=1 << 20)
                return H2Response(status=200, body=b"py:" + body)

            backend = await H2Server(FnService(echo)).start()
            (disco / "echo").write_text(f"127.0.0.1 {backend.bound_port}\n")
            linker = load_linker(
                self.mk_cfg(disco, cert, key, client_tls=False))
            assert not isinstance(linker.routers[0], _FastPathRouter)
            await linker.start()
            port = linker.routers[0].server_ports[0]
            h2c = H2Client("127.0.0.1", port, ssl_context=client_ctx(cert),
                           server_hostname="localhost")
            try:
                rsp = await h2c(H2Request(method="POST", path="/x",
                                          authority="echo", body=b"fb"))
                body, _ = await rsp.stream.read_all(max_bytes=1 << 20)
                assert body == b"py:fb"
            finally:
                await h2c.close()
                await linker.close()
                await backend.close()

        run(go())

    def test_no_cert_stays_native_cleartext(self, certs, tmp_path):
        """A fastPath router WITHOUT a tls block keeps the native
        cleartext engine (no accidental Python fallback, TLS contexts
        disabled)."""
        from linkerd_tpu.linker import _FastPathRouter, load_linker

        disco = tmp_path / "disco"
        disco.mkdir()
        cfg = f"""
routers:
- protocol: h2
  label: h2c
  fastPath: true
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
        linker = load_linker(cfg)
        try:
            router = linker.routers[0]
            assert isinstance(router, _FastPathRouter)
            tls = router.controller.engine.stats()["tls"]
            assert tls["enabled"] is False
            assert tls["client_enabled"] is False
        finally:
            run(linker.close())

    def test_unsupported_tls_subsets_fail_load(self, certs, tmp_path):
        """commonName templates, clientAuth, per-prefix TLS, and server
        caCertPath have no native seam — they must fail the load, not
        silently downgrade."""
        from linkerd_tpu.config import ConfigError
        from linkerd_tpu.linker import load_linker

        cert, key = certs
        disco = tmp_path / "disco"
        disco.mkdir()
        base = f"""
routers:
- protocol: h2
  label: bad
  fastPath: true
  {{extra}}
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers:
  - port: 0
    {{server_extra}}
namers:
- kind: io.l5d.fs
  rootDir: {disco}
"""
        cases = [
            ("client: {tls: {commonName: x}}", "", "commonName"),
            ("client: {tls: {disableValidation: true, clientAuth: "
             f"{{certPath: {cert}, keyPath: {key}}}}}}}", "",
             "clientAuth"),
            ("client: {kind: io.l5d.static, configs: "
             "[{prefix: /svc, tls: {disableValidation: true}}]}", "",
             "per-prefix"),
            ("", f"tls: {{certPath: {cert}, keyPath: {key}, "
             f"caCertPath: {cert}}}", "caCertPath"),
        ]
        for extra, server_extra, msg in cases:
            with pytest.raises(ConfigError, match=msg):
                load_linker(base.format(extra=extra,
                                        server_extra=server_extra))
