"""Native codec parity: C++ fast paths must match the pure-Python rules.

The smuggling-defence cases mirror the reference's FramingFilter /
strict-parsing posture; huffman parity is fuzzed against hpack.py.
Skipped cleanly when no toolchain is available (pure-Python fallback).
"""

import random

import pytest

from linkerd_tpu import native
from linkerd_tpu.protocol.h2 import hpack

pytestmark = pytest.mark.skipif(
    not native.ensure_built(), reason="native toolchain unavailable")


class TestHeadParser:
    def test_good_request(self):
        got = native.parse_http1_head(
            b"POST /p?q=1 HTTP/1.1\r\nHost: h\r\nA: b  \r\n\r\n")
        assert got == ("POST", "/p?q=1", "HTTP/1.1",
                       [("Host", "h"), ("A", "b")])

    @pytest.mark.parametrize("head", [
        b"GET /x\r\nA: HTTP/1.1\r\n\r\n",           # CRLF smuggling in URI
        b"GET / HTTP/1.1\r\nHost: a\r\n X: v\r\n\r\n",  # obs-fold
        b"GET / HTTP/1.1\r\nX E: v\r\n\r\n",        # ws in header name
        b"GET /a\tb HTTP/1.1\r\n\r\n",              # tab in request line
        b"GET /a b HTTP/1.1\r\n\r\n",               # four tokens
        b"GET /" + b"a" * 9000 + b" HTTP/1.1\r\n\r\n",  # line too long
        b"GET / HTTP/1.1\r\nNoColon\r\n\r\n",
    ])
    def test_rejects(self, head):
        assert native.parse_http1_head(head) is None


class TestHuffmanParity:
    def test_fuzz_roundtrip_matches_python(self):
        random.seed(11)
        for _ in range(200):
            data = bytes(random.randrange(256)
                         for _ in range(random.randrange(300)))
            enc_py = hpack.huffman_encode(data)
            assert native.huffman_encode(data) == enc_py
            assert native.huffman_decode(enc_py) == data

    def test_invalid_padding_rejected_like_python(self):
        bad = bytes([0b00011110])  # 'a' + padding containing a 0-bit
        with pytest.raises(hpack.HpackError):
            saved = hpack._native
            hpack._native = None
            try:
                hpack.huffman_decode(bad)
            finally:
                hpack._native = saved
        assert native.huffman_decode(bad) is None


class TestCrlfStrictness:
    @pytest.mark.parametrize("head", [
        b"GET / HTTP/1.1\nHost: a\r\n\r\n",       # bare-LF request line
        b"GET / HTTP/1.1\r\nA: 1\n\nTE: x\r\n\r\n",  # LF-LF fake blank
        b"GET / HTTP/1.1\r\nHost: a\n\r\n",       # bare-LF header line
    ])
    def test_bare_lf_rejected(self, head):
        assert native.parse_http1_head(head) is None

    def test_value_trim_matches_python_strip(self):
        got = native.parse_http1_head(
            b"GET / HTTP/1.1\r\nX-A: \x0cv\x0c \r\n\r\n")
        assert got is not None
        # python: " \x0cv\x0c ".strip() == "v"; native must agree
        assert got[3] == [("X-A", "v")]
