"""Model lifecycle subsystem tests: checkpoint store round-trips (incl.
sharded<->single-device), promotion gate accept/reject/rollback, drift
gauges, sidecar Snapshot/Restore, and the end-to-end acceptance loop:
train -> checkpoint -> kill/recreate -> restore -> bitwise-identical
scores; poisoned candidate rejected while the serving version keeps
scoring."""

import asyncio
import os

import numpy as np
import pytest

from linkerd_tpu.lifecycle import (
    CheckpointCorruptError, CheckpointStore, DriftMonitor, EvalReport,
    GatePolicy, LifecycleConfig, ModelLifecycleManager, PromotionGate,
    ReplayWindow, decode_snapshot, encode_snapshot, evaluate_snapshot,
)
from linkerd_tpu.telemetry.anomaly import InProcessScorer
from linkerd_tpu.telemetry.metrics import MetricsTree


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 300))


def one_device():
    import jax
    return [jax.devices()[0]]


def mk_data(n=128, anom_frac=0.25, seed=0, dim=36):
    """Synthetic labeled window: normal rows ~N(0,1), anomalous rows
    shifted +4 sigma in half the dims."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, dim)).astype(np.float32)
    labels = np.zeros(n, np.float32)
    n_anom = int(n * anom_frac)
    x[:n_anom, : dim // 2] += 4.0
    labels[:n_anom] = 1.0
    mask = np.ones(n, np.float32)
    perm = rng.permutation(n)
    return x[perm], labels[perm], mask[perm]


async def train(scorer, x, labels, mask, rounds=4):
    for _ in range(rounds):
        await scorer.fit(x, labels, mask)


class TestSnapshotCodec:
    def test_roundtrip_is_exact(self):
        scorer = InProcessScorer(seed=3, devices=one_device())
        snap = scorer.snapshot()
        back = decode_snapshot(encode_snapshot(snap))
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(snap.params),
                        jax.tree_util.tree_leaves(back.params)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        assert len(back.opt_leaves) == len(snap.opt_leaves)
        assert (back.mu == snap.mu).all() and (back.var == snap.var).all()
        assert back.step == snap.step
        assert back.cfg_dict() == snap.cfg_dict()

    def test_corruption_detected(self):
        scorer = InProcessScorer(seed=3, devices=one_device())
        data = bytearray(encode_snapshot(scorer.snapshot()))
        data[len(data) // 2] ^= 0xFF
        with pytest.raises(CheckpointCorruptError):
            decode_snapshot(bytes(data))
        with pytest.raises(CheckpointCorruptError):
            decode_snapshot(b"NOTACKPT")


class TestCheckpointStore:
    def test_save_load_retention_and_verify(self, tmp_path):
        scorer = InProcessScorer(seed=1, devices=one_device())
        store = CheckpointStore(str(tmp_path), retain=3)
        v1 = store.save(scorer.snapshot(), status="promoted")
        assert store.latest_good() == v1
        versions = [v1]
        for _ in range(4):
            versions.append(store.save(scorer.snapshot(), status="candidate",
                                       parent=v1))
        # retention kept 3, but never pruned the serving version
        kept = [e.version for e in store.versions()]
        assert len(kept) == 3 and v1 in kept
        assert store.verify() == []
        v, snap = store.load()
        assert v == v1 and snap.step == 0

        # a reopened store sees the same manifest
        store2 = CheckpointStore(str(tmp_path), retain=3)
        assert store2.latest_good() == v1

    def test_verify_reports_corruption_orphans_and_missing(self, tmp_path):
        scorer = InProcessScorer(seed=1, devices=one_device())
        store = CheckpointStore(str(tmp_path), retain=5)
        v1 = store.save(scorer.snapshot(), status="promoted")
        v2 = store.save(scorer.snapshot(), status="candidate", parent=v1)
        # corrupt v2's payload on disk
        f2 = tmp_path / store._entry(v2).file
        raw = bytearray(f2.read_bytes())
        raw[100] ^= 0xFF
        f2.write_bytes(bytes(raw))
        # drop an orphan
        (tmp_path / "v999999.ckpt").write_bytes(b"x")
        issues = store.verify()
        assert any("CRC" in i for i in issues), issues
        assert any("orphaned" in i for i in issues), issues
        # corrupted load refuses rather than restoring garbage
        with pytest.raises(CheckpointCorruptError):
            store.load(v2)
        # missing file
        os.unlink(str(f2))
        assert any("missing" in i for i in store.verify())


class TestRestoreRoundTrip:
    def test_kill_recreate_restore_bitwise_identical(self, tmp_path):
        """Acceptance: train in-process -> checkpoint -> kill/recreate
        scorer -> restore -> identical scores (bitwise on CPU)."""
        async def go():
            x, labels, mask = mk_data(seed=5)
            scorer = InProcessScorer(seed=0, devices=one_device())
            await train(scorer, x, labels, mask)
            before = np.asarray(await scorer.score(x))
            store = CheckpointStore(str(tmp_path))
            v = store.save(scorer.snapshot(), status="promoted")
            del scorer  # "kill" the process's scorer

            fresh = InProcessScorer(seed=1234, devices=one_device())
            _, snap = store.load(v)
            fresh.restore(snap)
            after = np.asarray(await fresh.score(x))
            assert before.tobytes() == after.tobytes()
            # training resumes from the checkpointed optimizer state
            assert fresh._step == snap.step
            loss = await fresh.fit(x, labels, mask)
            assert np.isfinite(loss)
            assert fresh._step == snap.step + fresh.fit_steps

        run(go())

    def test_sharded_and_single_device_restores(self, tmp_path):
        """Snapshot portability across topologies: dp-sharded -> single
        device and back, re-placed per the parallel/mesh.py specs."""
        async def go():
            import jax
            devs = jax.devices()
            if len(devs) < 2:
                pytest.skip("needs the virtual multi-device CPU mesh")
            x, labels, mask = mk_data(seed=6, n=64)
            sharded = InProcessScorer(seed=0)
            assert sharded.mesh is not None
            await train(sharded, x, labels, mask, rounds=2)
            snap = sharded.snapshot()

            single = InProcessScorer(seed=7, devices=one_device())
            single.restore(snap)
            a = np.asarray(await sharded.score(x))
            b = np.asarray(await single.score(x))
            np.testing.assert_allclose(a, b, atol=1e-5)

            # single -> sharded, and training continues on the mesh
            await train(single, x, labels, mask, rounds=1)
            sharded.restore(single.snapshot())
            c = np.asarray(await single.score(x))
            d = np.asarray(await sharded.score(x))
            np.testing.assert_allclose(c, d, atol=1e-5)
            assert np.isfinite(await sharded.fit(x, labels, mask))

        run(go())

    def test_restore_rejects_mismatched_config(self):
        from linkerd_tpu.models.anomaly import AnomalyModelConfig
        scorer = InProcessScorer(seed=0, devices=one_device())
        snap = scorer.snapshot()
        other = InProcessScorer(seed=0, recon_weight=0.11,
                                devices=one_device())
        with pytest.raises(ValueError):
            other.restore(snap)
        assert AnomalyModelConfig().in_dim == snap.cfg.in_dim


class TestPromotionGate:
    def mk_report(self, loss, auc, n_labeled=64):
        return EvalReport(loss=loss, auc=auc, score_mean=0.5,
                          score_std=0.1, n_rows=256, n_labeled=n_labeled)

    def test_decisions(self):
        gate = PromotionGate(GatePolicy(aucTolerance=0.02,
                                        lossTolerance=0.10))
        serving = self.mk_report(1.0, 0.95)
        assert gate.decide(self.mk_report(1.0, 0.95), None).accepted
        assert gate.decide(self.mk_report(1.05, 0.95), serving).accepted
        assert not gate.decide(self.mk_report(1.5, 0.95), serving).accepted
        assert not gate.decide(self.mk_report(1.0, 0.80), serving).accepted
        # too few labels: AUC ignored, loss rules
        d = gate.decide(self.mk_report(1.0, 0.10, n_labeled=2), serving)
        assert d.accepted
        assert not gate.decide(
            self.mk_report(float("nan"), 0.99), serving).accepted

    def test_poisoned_candidate_rejected_and_rolled_back(self, tmp_path):
        """Acceptance: a candidate degraded by training on poisoned
        labels is rejected by the gate; the scorer hot-swaps back to the
        last-good version and keeps scoring identically."""
        async def go():
            x, labels, mask = mk_data(n=192, seed=7)
            scorer = InProcessScorer(seed=0, devices=one_device(),
                                     learning_rate=5e-3)
            await train(scorer, x, labels, mask, rounds=6)

            store = CheckpointStore(str(tmp_path))
            gate = PromotionGate(GatePolicy())
            replay = ReplayWindow(4096)
            replay.add_batch(x, labels, mask)
            mgr = ModelLifecycleManager(store, gate, replay,
                                        min_replay_rows=32)

            # first cycle bootstraps: the trained model becomes serving
            out1 = await mgr.run_cycle(scorer)
            assert out1["action"] == "promoted"
            serving_scores = np.asarray(await scorer.score(x))

            # a little more good training -> promoted again
            await train(scorer, x, labels, mask, rounds=1)
            out2 = await mgr.run_cycle(scorer)
            assert out2["action"] == "promoted"
            assert mgr.serving_version == out2["version"]
            serving_scores = np.asarray(await scorer.score(x))

            # poison: train hard on flipped labels
            await train(scorer, x, 1.0 - labels, mask, rounds=12)
            out3 = await mgr.run_cycle(scorer)
            assert out3["action"] == "rolled_back", out3
            assert mgr.rollbacks == 1 and mgr.rejections == 1
            # the serving version keeps scoring: post-rollback scores are
            # bitwise the promoted version's scores
            restored = np.asarray(await scorer.score(x))
            assert restored.tobytes() == serving_scores.tobytes()
            # the rejected candidate is retained for forensics
            statuses = {e.version: e.status for e in store.versions()}
            assert statuses[out3["rejected_version"]] == "rejected"
            assert statuses[mgr.serving_version] == "promoted"

        run(go())

    def test_shadow_eval_separates_good_from_poisoned(self):
        async def go():
            x, labels, mask = mk_data(n=192, seed=8)
            good = InProcessScorer(seed=0, devices=one_device(),
                                   learning_rate=5e-3)
            await train(good, x, labels, mask, rounds=6)
            bad = InProcessScorer(seed=0, devices=one_device(),
                                  learning_rate=5e-3)
            await train(bad, x, 1.0 - labels, mask, rounds=6)
            rg = evaluate_snapshot(good.snapshot(), x, labels, mask)
            rb = evaluate_snapshot(bad.snapshot(), x, labels, mask)
            assert rg.loss < rb.loss
            assert rg.auc > rb.auc
            assert rg.n_labeled == len(x)

        run(go())


class TestDrift:
    def test_gauges_emitted_via_metrics_registry(self):
        mt = MetricsTree()
        mon = DriftMonitor(mt.scope("anomaly", "drift"), momentum=0.5)
        rng = np.random.default_rng(0)
        base = rng.standard_normal((256, 8)).astype(np.float32)
        mon.observe(base, scores=np.full(256, 0.2, np.float32))
        mon.set_reference(base.mean(axis=0), base.var(axis=0),
                          version=1, step=10)
        flat = mt.flatten()
        assert flat["anomaly/drift/feature_shift"] == pytest.approx(0.0,
                                                                    abs=0.2)
        # shift the population: means move by +3, scores jump
        for _ in range(8):
            mon.observe(base + 3.0, scores=np.full(256, 0.9, np.float32))
        flat = mt.flatten()
        assert flat["anomaly/drift/feature_shift"] > 1.0
        assert flat["anomaly/drift/score_shift"] > 1.0
        snap = mon.snapshot()
        assert snap["reference_version"] == 1
        assert snap["batches_observed"] == 9


class TestSidecarLifecycle:
    def test_snapshot_restore_over_grpc(self):
        pytest.importorskip("grpc")
        from linkerd_tpu.telemetry.sidecar import (
            GrpcScorerClient, ScorerSidecar,
        )

        async def go():
            x, labels, mask = mk_data(n=64, seed=9)
            backend = InProcessScorer(seed=0, devices=one_device())
            sidecar = await ScorerSidecar(scorer=backend).start()
            client = GrpcScorerClient(f"127.0.0.1:{sidecar.port}")
            try:
                for _ in range(3):
                    await client.fit(x, labels, mask)
                before = await client.score(x)
                snap = await client.snapshot()
                assert snap.step == backend._step
                # keep training, then roll the sidecar back over the wire
                await client.fit(x, 1.0 - labels, mask)
                step = await client.restore(snap)
                assert step == snap.step
                after = await client.score(x)
                assert before.tobytes() == after.tobytes()
            finally:
                client.close()
                await sidecar.close()

        run(go())


class TestTelemeterLifecycle:
    def mk_cfg(self, tmp_path, **kw):
        from linkerd_tpu.telemetry.anomaly import JaxAnomalyConfig
        lc = LifecycleConfig(directory=str(tmp_path / "ckpts"),
                             checkpointEveryS=0, minReplayRows=16,
                             **kw)
        return JaxAnomalyConfig(maxBatch=64, trainEveryBatches=1,
                                lifecycle=lc)

    def feed(self, tele, n=48, seed=0, anomalous=False):
        from linkerd_tpu.models.features import FeatureVector
        rng = np.random.default_rng(seed)
        for i in range(n):
            fv = FeatureVector(
                latency_ms=float(rng.gamma(2.0, 200.0 if anomalous else 5.0)),
                status=500 if anomalous else 200,
                dst_path="/svc/web")
            tele.ring.append((fv, 1.0 if anomalous else 0.0))

    def test_yaml_config_wires_lifecycle(self, tmp_path):
        """The YAML lifecycle block flows through the config parser into
        a live manager (linker startup path)."""
        from linkerd_tpu.config.parser import instantiate
        cfg = instantiate("telemeter", {
            "kind": "io.l5d.jaxAnomaly",
            "lifecycle": {"directory": str(tmp_path / "store"),
                          "retain": 7, "aucTolerance": 0.05},
        })
        tele = cfg.mk(MetricsTree())
        assert tele.lifecycle is not None
        assert tele.lifecycle.store.retain == 7
        assert tele.lifecycle.gate.policy.aucTolerance == 0.05
        assert os.path.isdir(str(tmp_path / "store"))
        tele.close()

    def test_replay_window_is_held_out_from_training(self, tmp_path):
        """Shadow-eval batches must be excluded from training: a window
        the candidate trained on (same rows and labels) could not catch
        a poisoned training stream."""
        async def go():
            tele = self.mk_cfg(tmp_path).mk(MetricsTree())
            scorer = tele._ensure_scorer()
            hk = tele.cfg.lifecycle.holdoutEveryBatches
            for i in range(3 * hk):
                self.feed(tele, 8, seed=i)
                step_before = scorer._step
                await tele.drain_once()
                if (tele._batch_i - 1) % hk == 0:
                    # holdout batch: replay grew, no training happened
                    assert scorer._step == step_before
                else:
                    assert scorer._step > step_before
            assert len(tele.lifecycle.replay) == 3 * 8
            tele.close()

        run(go())

    def test_drain_cycle_model_json_and_restart_restore(self, tmp_path):
        """Telemeter-integrated loop: drain feeds the replay window and
        drift gauges; a cycle promotes; /model.json reports state; a NEW
        telemeter (restart) restores the promoted model."""
        async def go():
            mt = MetricsTree()
            tele = self.mk_cfg(tmp_path).mk(mt)
            self.feed(tele, 48, seed=1)
            await tele.drain_once()
            assert len(tele.lifecycle.replay) == 48
            out = await tele.lifecycle_cycle()
            assert out["action"] == "promoted"
            state = tele.model_state()
            assert state["serving_version"] == out["version"]
            assert state["lifecycle_enabled"] is True
            assert state["drift"]["batches_observed"] == 1
            handlers = dict(tele.admin_handlers())
            assert "/model.json" in handlers
            from linkerd_tpu.protocol.http.message import Request
            rsp = await handlers["/model.json"](Request())
            assert rsp.status == 200 and b"serving_version" in rsp.body
            flat = mt.flatten()
            assert flat["anomaly/model/version"] == out["version"]
            x = np.random.default_rng(3).standard_normal(
                (32, tele._scorer.cfg.in_dim)).astype(np.float32)
            before = np.asarray(await tele._scorer.score(x))
            tele.close()  # writes the shutdown candidate snapshot

            # "restart": a fresh telemeter restores last-good on bootstrap
            tele2 = self.mk_cfg(tmp_path).mk(MetricsTree())
            scorer2 = tele2._ensure_scorer()
            restored = await tele2.lifecycle.bootstrap(scorer2)
            assert restored == out["version"]
            after = np.asarray(await scorer2.score(x))
            assert before.tobytes() == after.tobytes()
            tele2.close()

        run(go())
