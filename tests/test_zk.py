"""ZooKeeper family against a wire-level fake ZK server.

The reference's test technique (scripted fake SD backends, SURVEY.md §4
pattern 2) applied to ZK: FakeZkServer speaks the jute protocol so the
real asyncio ZkClient, the three namers, the dtab store, and the
announcer are all exercised over real sockets.
"""

import asyncio
import json

import pytest

from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.core.activity import Ok
from linkerd_tpu.core.addr import Bound
from linkerd_tpu.core.nametree import Leaf, Neg
from linkerd_tpu.namer.zk import (
    CuratorNamer, ServersetNamer, ZkLeaderNamer, shared_zk,
)
from linkerd_tpu.namerd.store import (
    DtabNamespaceDoesNotExist, DtabVersionMismatch, VersionedDtab,
)
from linkerd_tpu.namerd.stores import ZkDtabStore
from linkerd_tpu.testing.zkserver import FakeZkServer
from linkerd_tpu.zk.client import ZkClient, ZkError, ZK_BADVERSION, ZK_NONODE


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def member_json(host, port, status="ALIVE", extra_eps=None):
    return json.dumps({
        "serviceEndpoint": {"host": host, "port": port},
        "additionalEndpoints": extra_eps or {},
        "status": status,
    }).encode()


async def wait_for(fn, timeout=5.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        v = fn()
        if v:
            return v
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError("condition not met in time")
        await asyncio.sleep(interval)


def hosts_of(addr) -> set:
    assert isinstance(addr, Bound), addr
    return {(a.host, a.port) for a in addr.addresses}


class TestZkClient:
    def test_crud_versions_and_watches(self):
        async def go():
            server = await FakeZkServer().start()
            zk = ZkClient(server.hosts).start()
            try:
                await zk.ensure_path("/a/b")
                path = await zk.create("/a/b/n1", b"v0")
                assert path == "/a/b/n1"
                data, stat = await zk.get_data("/a/b/n1")
                assert data == b"v0" and stat.version == 0

                # CAS on znode version
                await zk.set_data("/a/b/n1", b"v1", version=0)
                with pytest.raises(ZkError) as ei:
                    await zk.set_data("/a/b/n1", b"v2", version=0)
                assert ei.value.code == ZK_BADVERSION

                # data watch fires on change
                ev = asyncio.Event()
                data, _ = await zk.get_data("/a/b/n1",
                                            watch=lambda e: ev.set())
                assert data == b"v1"
                await zk.set_data("/a/b/n1", b"v2")
                await asyncio.wait_for(ev.wait(), 5)

                # children watch fires on create; sequential names order
                ev2 = asyncio.Event()
                kids = await zk.get_children("/a/b",
                                             watch=lambda e: ev2.set())
                assert kids == ["n1"]
                s1 = await zk.create("/a/b/seq_", b"", sequential=True)
                s2 = await zk.create("/a/b/seq_", b"", sequential=True)
                assert s1 < s2
                await asyncio.wait_for(ev2.wait(), 5)

                with pytest.raises(ZkError) as ei:
                    await zk.get_data("/nope")
                assert ei.value.code == ZK_NONODE

                await zk.delete("/a/b/n1")
                assert await zk.exists("/a/b/n1") is None
            finally:
                await zk.close()
                await server.close()

        run(go())

    def test_ephemerals_die_with_session(self):
        async def go():
            server = await FakeZkServer().start()
            zk1 = ZkClient(server.hosts).start()
            zk2 = ZkClient(server.hosts).start()
            try:
                await zk1.ensure_path("/ss")
                await zk1.create("/ss/member_", member_json("h1", 1),
                                 ephemeral=True, sequential=True)
                kids = await zk2.get_children("/ss")
                assert len(kids) == 1
                ev = asyncio.Event()
                await zk2.get_children("/ss", watch=lambda e: ev.set())
                await zk1.close()  # session dies -> ephemeral reaped
                await asyncio.wait_for(ev.wait(), 5)
                kids = await zk2.get_children("/ss")
                assert kids == []
            finally:
                await zk2.close()
                await server.close()

        run(go())


class TestServersetNamer:
    def test_bind_scale_and_endpoint(self):
        async def go():
            server = await FakeZkServer().start()
            zk = ZkClient(server.hosts).start()
            namer = ServersetNamer(zk, Path.of("#", "io.l5d.serversets"))
            try:
                server.set_node(
                    "/discovery/prod/web/member_0000000001",
                    member_json("10.0.0.1", 8080,
                                extra_eps={"admin": {"host": "10.0.0.1",
                                                     "port": 9990}}))
                act = namer.lookup(Path.read("/discovery/prod/web"))
                state = await wait_for(
                    lambda: act.current if isinstance(act.current, Ok)
                    else None)
                tree = state.value
                assert isinstance(tree, Leaf)
                bound = tree.value
                assert bound.id_.show == "/#/io.l5d.serversets/discovery/prod/web"
                assert hosts_of(bound.addr.sample()) == {("10.0.0.1", 8080)}

                # scale up: second member joins -> Var updates in place
                server.set_node(
                    "/discovery/prod/web/member_0000000002",
                    member_json("10.0.0.2", 8080))
                await wait_for(lambda: len(
                    hosts_of(bound.addr.sample())) == 2)

                # DEAD members are excluded
                server.set_node(
                    "/discovery/prod/web/member_0000000002",
                    member_json("10.0.0.2", 8080, status="DEAD"))
                await wait_for(lambda: len(
                    hosts_of(bound.addr.sample())) == 1)

                # :endpoint selects additionalEndpoints
                act2 = namer.lookup(Path.read("/discovery/prod/web:admin"))
                state2 = await wait_for(
                    lambda: act2.current if isinstance(act2.current, Ok)
                    else None)
                bound2 = state2.value.value
                assert hosts_of(bound2.addr.sample()) == {("10.0.0.1", 9990)}
            finally:
                namer.close()
                await zk.close()
                await server.close()

        run(go())

    def test_prefix_fallback_residual(self):
        async def go():
            server = await FakeZkServer().start()
            zk = ZkClient(server.hosts).start()
            namer = ServersetNamer(zk, Path.of("#", "io.l5d.serversets"))
            try:
                server.set_node("/discovery/prod/web/member_0000000001",
                                member_json("10.0.0.1", 8080))
                # extra segments fall into the residual
                act = namer.lookup(Path.read("/discovery/prod/web/extra/seg"))
                state = await wait_for(
                    lambda: act.current if isinstance(act.current, Ok)
                    else None)
                bound = state.value.value
                assert bound.residual.show == "/extra/seg"

                # no serverset anywhere on the path -> Neg
                act2 = namer.lookup(Path.read("/not/there"))
                state2 = await wait_for(
                    lambda: act2.current if isinstance(act2.current, Ok)
                    else None)
                assert isinstance(state2.value, Neg)
            finally:
                namer.close()
                await zk.close()
                await server.close()

        run(go())


class TestZkLeaderNamer:
    def test_leader_failover(self):
        async def go():
            server = await FakeZkServer().start()
            zk = ZkClient(server.hosts).start()
            namer = ZkLeaderNamer(zk, Path.of("#", "io.l5d.zkLeader"))
            try:
                server.set_node("/election/svc/c_0000000001",
                                b"10.0.0.1:9001")
                server.set_node("/election/svc/c_0000000002",
                                b"10.0.0.2:9002")
                act = namer.lookup(Path.read("/election/svc"))
                state = await wait_for(
                    lambda: act.current if isinstance(act.current, Ok)
                    else None)
                bound = state.value.value
                assert hosts_of(bound.addr.sample()) == {("10.0.0.1", 9001)}

                # leader dies -> next lowest sequence takes over
                server.delete_node("/election/svc/c_0000000001")
                await wait_for(lambda: hosts_of(
                    bound.addr.sample()) == {("10.0.0.2", 9002)})
            finally:
                namer.close()
                await zk.close()
                await server.close()

        run(go())


class TestCuratorNamer:
    def test_instances_and_ssl(self):
        async def go():
            server = await FakeZkServer().start()
            zk = ZkClient(server.hosts).start()
            namer = CuratorNamer(zk, "/disco", Path.of("#", "io.l5d.curator"))
            try:
                server.set_node("/disco/api/i-1", json.dumps(
                    {"name": "api", "id": "i-1", "address": "10.1.0.1",
                     "port": 8080, "sslPort": None}).encode())
                server.set_node("/disco/api/i-2", json.dumps(
                    {"name": "api", "id": "i-2", "address": "10.1.0.2",
                     "port": 8080, "sslPort": 8443}).encode())
                act = namer.lookup(Path.read("/api/extra"))
                state = await wait_for(
                    lambda: act.current if isinstance(act.current, Ok)
                    else None)
                bound = state.value.value
                # sslPort wins for the instance that has one
                assert hosts_of(bound.addr.sample()) == {
                    ("10.1.0.1", 8080), ("10.1.0.2", 8443)}
                assert bound.residual.show == "/extra"
                assert dict(bound.addr.sample().meta)["ssl"] is True
            finally:
                namer.close()
                await zk.close()
                await server.close()

        run(go())


class TestZkDtabStore:
    def test_crud_cas_watch_and_list(self):
        async def go():
            server = await FakeZkServer().start()
            store = ZkDtabStore(server.hosts, "/dtabs")
            try:
                await store.create("prod", Dtab.read("/svc => /#/io.l5d.fs"))
                act = store.observe("prod")
                state = await wait_for(
                    lambda: act.current
                    if isinstance(act.current, Ok) and act.current.value
                    else None)
                vd: VersionedDtab = state.value
                assert "/svc=>/#/io.l5d.fs" in vd.dtab.show.replace(" ", "")

                # CAS: stale version rejected, current accepted
                with pytest.raises(DtabVersionMismatch):
                    await store.update("prod", Dtab.read("/a => /b"),
                                       b"\x00\x00\x00\x63")
                await store.update("prod", Dtab.read("/a => /b"), vd.version)
                await wait_for(
                    lambda: isinstance(act.current, Ok)
                    and act.current.value
                    and "/a" in act.current.value.dtab.show)

                # list is watch-driven
                names = store.list()
                await wait_for(lambda: "prod" in names.sample())
                await store.put("stage", Dtab.read("/x => /y"))
                await wait_for(lambda: "stage" in names.sample())

                await store.delete("stage")
                await wait_for(lambda: "stage" not in names.sample())
                with pytest.raises(DtabNamespaceDoesNotExist):
                    await store.delete("stage")
            finally:
                store.close()
                from linkerd_tpu.namer.zk import close_shared_zk
                await close_shared_zk()
                await server.close()

        run(go())


class TestZkAnnouncerRoundTrip:
    def test_announce_visible_via_serversets_namer(self):
        async def go():
            from linkerd_tpu.announcer import ZkAnnouncer

            server = await FakeZkServer().start()
            zk = ZkClient(server.hosts).start()
            namer = ServersetNamer(zk, Path.of("#", "io.l5d.serversets"))
            ann = ZkAnnouncer(server.hosts, Path.read("/discovery"),
                              Path.read("/io.l5d.serversets"))
            try:
                closable = ann.announce("10.9.9.9", 4140, Path.read("/web"))
                act = namer.lookup(Path.read("/discovery/web"))
                state = await wait_for(
                    lambda: (act.current
                             if isinstance(act.current, Ok)
                             and isinstance(act.current.value, Leaf)
                             else None))
                bound = state.value.value
                assert hosts_of(bound.addr.sample()) == {("10.9.9.9", 4140)}

                # withdrawal removes the member
                closable.close()
                await wait_for(
                    lambda: not hosts_of(bound.addr.sample()))
            finally:
                namer.close()
                from linkerd_tpu.namer.zk import close_shared_zk
                await close_shared_zk()
                await zk.close()
                await server.close()

        run(go())


class TestZkConfigKinds:
    def test_all_five_kinds_registered(self):
        from linkerd_tpu.config import instantiate
        import linkerd_tpu.linker  # noqa: F401 — loads plugin registrations

        n1 = instantiate("namer", {
            "kind": "io.l5d.serversets",
            "zkAddrs": [{"host": "127.0.0.1", "port": 21810}]})
        n2 = instantiate("namer", {
            "kind": "io.l5d.zkLeader", "hosts": "127.0.0.1:21810"})
        n3 = instantiate("namer", {
            "kind": "io.l5d.curator", "hosts": "127.0.0.1:21810",
            "basePath": "/svc-disco"})
        st = instantiate("dtabStore", {
            "kind": "io.l5d.zk", "hosts": "127.0.0.1:21810",
            "pathPrefix": "/dtabs"})
        an = instantiate("announcer", {
            "kind": "io.l5d.serversets", "hosts": "127.0.0.1:21810",
            "pathPrefix": "/discovery"})
        assert n1.prefix == "/io.l5d.serversets"
        assert n2.prefix == "/io.l5d.zkLeader"
        assert n3.basePath == "/svc-disco"
        assert st.pathPrefix == "/dtabs"
        assert an.pathPrefix == "/discovery"
