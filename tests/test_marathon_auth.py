"""Marathon DC/OS service-account auth + the dcos-bootstrap tool.

Ref: namer/marathon/.../Authenticator.scala:109 (RS256 JWT login, token
cache, 401 re-auth) and namerd/dcos-bootstrap/.../DcosBootstrap.scala:54.
"""

import asyncio
import base64
import json

import pytest

from linkerd_tpu.namer.marathon import DcosAuthenticator, MarathonApi
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def _gen_key_pem() -> str:
    # the RS256 signing flow needs a real key; environments without the
    # optional cryptography lib skip the test rather than erroring
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()).decode()


class FakeDcos:
    """ACS login + a token-guarded marathon endpoint; can expire tokens."""

    def __init__(self, key_pem: str):
        self.key_pem = key_pem
        self.generation = 0
        self.logins = 0

    def _verify_jwt(self, jwt: str) -> dict:
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import padding

        head, payload, sig = jwt.split(".")
        pad = "=" * (-len(sig) % 4)
        key = serialization.load_pem_private_key(
            self.key_pem.encode(), password=None).public_key()
        key.verify(base64.urlsafe_b64decode(sig + pad),
                   f"{head}.{payload}".encode(),
                   padding.PKCS1v15(), hashes.SHA256())
        return json.loads(base64.urlsafe_b64decode(
            payload + "=" * (-len(payload) % 4)))

    def service(self):
        async def handler(req: Request) -> Response:
            if req.uri.startswith("/acs/api/v1/auth/login"):
                body = json.loads(req.body)
                claims = self._verify_jwt(body["token"])  # raises if bad
                assert claims["uid"] == body["uid"]
                self.logins += 1
                return Response(status=200, body=json.dumps(
                    {"token": f"session-{self.generation}"}).encode())
            auth = req.headers.get("Authorization") or ""
            if auth != f"token=session-{self.generation}":
                return Response(status=401, body=b"{}")
            return Response(status=200, body=json.dumps(
                {"tasks": [{"host": "10.0.0.1", "ports": [31001]}]}
            ).encode())
        return FnService(handler)


class TestDcosAuth:
    def test_login_cache_and_reauth_on_expiry(self):
        async def go():
            key = _gen_key_pem()
            dcos = FakeDcos(key)
            server = await HttpServer(dcos.service()).start()
            auth = DcosAuthenticator(
                f"http://127.0.0.1:{server.bound_port}/acs/api/v1/auth/login",
                "svc-acct", key)
            api = MarathonApi("127.0.0.1", server.bound_port,
                              authenticator=auth)
            try:
                status, data = await api.get_json("/v2/apps/web/tasks")
                assert status == 200
                assert data["tasks"][0]["ports"] == [31001]
                # token cached: second call does not re-login
                await api.get_json("/v2/apps/web/tasks")
                assert dcos.logins == 1

                # server expires the session: exactly one re-auth
                dcos.generation += 1
                status, data = await api.get_json("/v2/apps/web/tasks")
                assert status == 200
                assert dcos.logins == 2
            finally:
                await server.close()

        run(go())


class TestDcosBootstrap:
    def test_seeds_default_dtab_into_zk(self):
        async def go():
            from linkerd_tpu.namerd.dcos_bootstrap import bootstrap
            from linkerd_tpu.testing.zkserver import FakeZkServer
            from linkerd_tpu.zk.client import ZkClient

            server = await FakeZkServer().start()
            cfg = f"""
storage:
  kind: io.l5d.zk
  hosts: "{server.hosts}"
  pathPrefix: /dtabs
namers: []
interfaces: []
"""
            msg = await bootstrap(cfg)
            assert "created" in msg
            zk = ZkClient(server.hosts).start()
            data, _ = await zk.get_data("/dtabs/default")
            assert b"io.l5d.marathon" in data
            assert b"domainToPathPfx" in data
            await zk.close()

            # idempotent: second run leaves the dtab alone
            msg2 = await bootstrap(cfg)
            assert "already exists" in msg2
            await server.close()

        run(go())
