"""Fleet coordination tests (linkerd_tpu/fleet/ + MeshReactor fleet
mode + the CAS machinery the exchange rides on).

- FleetDoc/FleetView: wire roundtrip, dtab-dentry encoding, per-instance
  generation fencing, staleness TTLs, the quorum order-statistic;
- CAS conflict regression: concurrent LocalStoreClient AND
  NamerdHttpStoreClient writers racing on ONE namespace converge
  (retry-on-conflict, no lost update, ETag honored);
- FleetExchange: namerd-mediated publish/ingest, gossip push-pull over
  real admin handlers, hostile input dropped;
- quorum-gated actuation: K-of-N evidence required to shift, reverts
  when the quorum dissolves, stale peers lose their vote;
- generation fencing: a restarted instance with a stale generation
  never reverts its successor's override — including when the
  supersede lands MID-step (DeterministicScheduler interleaving);
- scorer replica pool: membership diffs, least-inflight pick, failover,
  fs-announced replicas resolved through a real namer;
- end to end on the REAL binaries: 3 linkerds + namerd, a fault seen by
  1/3 instances shifts nothing, by 2/3 shifts exactly once fleet-wide,
  and recovery reverts exactly (testing/fleet.py harness).
"""

import asyncio
import json
import time

import numpy as np
import pytest

from linkerd_tpu.admin.server import AdminServer
from linkerd_tpu.control.reactor import (
    LocalStoreClient, MeshReactor, NamerdHttpStoreClient, cas_modify,
)
from linkerd_tpu.control.state import HysteresisGovernor
from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.fleet.doc import FleetDoc, FleetView
from linkerd_tpu.fleet.exchange import FleetConfig, FleetExchange
from linkerd_tpu.fleet.gossip import fleet_admin_handlers
from linkerd_tpu.fleet.scorer_pool import (
    ScorerReplicaPool, namer_scorer_activity,
)
from linkerd_tpu.namerd import InMemoryDtabStore, Namerd
from linkerd_tpu.namerd.http_api import HttpControlService
from linkerd_tpu.namerd.store import DtabVersionMismatch
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.telemetry.metrics import MetricsTree


def run(coro, timeout: float = 60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


BASE_DTAB = "/svc => /#/io.l5d.fs ;"
PREFIXES = [Path.read("/io.l5d.fs")]


class _Board:
    degraded = False

    def __init__(self):
        self.levels = {}

    def effective_scores(self):
        return dict(self.levels)


def _doc(inst="peer", gen=1, seq=1, level=0.9, cluster="/svc/web",
         overrides=()):
    return FleetDoc(instance=inst, generation=gen, seq=seq,
                    clusters={cluster: {"level": level}},
                    overrides=list(overrides), ts=0.0)


# ---- FleetDoc --------------------------------------------------------------


class TestFleetDoc:
    def test_json_roundtrip(self):
        d = _doc(overrides=["/svc/web"])
        d2 = FleetDoc.from_json(d.to_json())
        assert d2.instance == "peer" and d2.generation == 1
        assert d2.clusters["/svc/web"]["level"] == 0.9
        assert d2.overrides == ["/svc/web"]

    def test_dentry_roundtrip(self):
        d = _doc(inst="l5d-0", level=0.42)
        prefix, dst = d.to_dentry_parts()
        assert prefix == "/fleet/l5d-0"
        back = FleetDoc.from_dentry_parts(prefix, dst)
        assert back is not None
        assert back.clusters["/svc/web"]["level"] == 0.42

    def test_dentry_rides_a_real_dtab(self):
        d = _doc(inst="l5d-0")
        prefix, dst = d.to_dentry_parts()
        dtab = Dtab.read(f"{prefix} => {dst} ;")
        parsed = FleetDoc.from_dentry_parts(
            dtab[0].prefix.show, dtab[0].dst.show)
        assert parsed is not None and parsed.instance == "l5d-0"

    def test_non_fleet_dentry_ignored(self):
        assert FleetDoc.from_dentry_parts("/svc/web", "/svc/web-b") is None
        assert FleetDoc.from_dentry_parts("/fleet/x", "/svc/web-b") is None

    def test_instance_prefix_mismatch_rejected(self):
        d = _doc(inst="honest")
        _, dst = d.to_dentry_parts()
        # a doc claiming identity "honest" under someone else's prefix
        assert FleetDoc.from_dentry_parts("/fleet/liar", dst) is None

    def test_bad_docs_rejected(self):
        with pytest.raises(ValueError):
            FleetDoc.from_json("[1, 2]")
        with pytest.raises(ValueError):
            FleetDoc.from_json(json.dumps({"i": "bad/slash", "g": 1}))
        with pytest.raises(ValueError):
            FleetDoc.from_json(json.dumps({"i": "x", "c": [1]}))

    def test_malformed_field_types_raise_valueerror_not_typeerror(self):
        # ONE malformed-doc error type: a null/list-valued numeric
        # field must surface as ValueError so every caller's except
        # clause covers it (a TypeError once leaked through the dentry
        # path and a single poison dentry would have broken every
        # instance's publish round forever)
        with pytest.raises(ValueError):
            FleetDoc.from_json(json.dumps(
                {"i": "x", "g": 1, "s": 1,
                 "c": {"/svc/web": {"level": "abc"}}}))
        with pytest.raises(ValueError):
            FleetDoc.from_json(json.dumps({"i": "x", "g": [1]}))
        # nulls coerce to 0 rather than poisoning the doc
        d = FleetDoc.from_json(json.dumps(
            {"i": "x", "g": None, "s": 1,
             "c": {"/svc/web": {"level": None}}}))
        assert d.generation == 0
        assert d.clusters["/svc/web"]["level"] == 0.0

    def test_poison_dentry_never_breaks_publish(self):
        """A dentry whose payload decodes but fails doc validation is
        treated as a non-fleet (operator) dentry: every instance's
        publish round keeps working around it."""
        async def go():
            bad_json = json.dumps({"i": "x", "g": "not-an-int", "s": 1})
            poison = (f"/fleet/x => /d/{bad_json.encode().hex()} ;")
            store = InMemoryDtabStore({"fleet": Dtab.read(poison)})
            ex = _exchange(store, "a")
            ex.set_source(lambda: {"/svc/web": 0.5})
            assert await ex.publish_once()
            vd = await store.observe("fleet").to_future()
            assert "/fleet/a" in vd.dtab.show
            assert "/fleet/x" in vd.dtab.show  # left alone, not eaten

        run(go())

    def test_cluster_count_bounded(self):
        clusters = {f"/svc/c{i}": {"level": 0.1} for i in range(500)}
        d = FleetDoc.from_json(json.dumps(
            {"i": "x", "g": 1, "s": 1, "c": clusters}))
        assert len(d.clusters) <= 64


# ---- FleetView -------------------------------------------------------------


class TestFleetView:
    def test_ordering_fences_stale_docs(self):
        v = FleetView("me", 1)
        assert v.ingest(_doc(gen=2, seq=5), now=0.0)
        assert not v.ingest(_doc(gen=2, seq=5), now=0.0)  # dup
        assert not v.ingest(_doc(gen=2, seq=4), now=0.0)  # older seq
        assert not v.ingest(_doc(gen=1, seq=99), now=0.0)  # older gen
        assert v.fenced == 2
        assert v.ingest(_doc(gen=3, seq=1), now=0.0)

    def test_own_newer_generation_supersedes(self):
        v = FleetView("me", 1)
        assert not v.ingest(_doc(inst="me", gen=1, seq=9), now=0.0)
        assert not v.superseded  # own echo, same incarnation
        v.ingest(_doc(inst="me", gen=2, seq=1), now=0.0)
        assert v.superseded

    def test_staleness_ttl_drops_votes(self):
        v = FleetView("me", 1, ttl_s=1.0)
        v.ingest(_doc(level=0.9), now=0.0)
        assert v.quorum_level("/svc/web", 0.9, 2, now=0.5) == 0.9
        # the peer's doc aged out: quorum of 2 can no longer be met
        assert v.quorum_level("/svc/web", 0.9, 2, now=2.5) == 0.0

    def test_quorum_order_statistic(self):
        v = FleetView("me", 1)
        v.ingest(_doc(inst="a", level=0.8), now=0.0)
        v.ingest(_doc(inst="b", level=0.2), now=0.0)
        # K-th highest of {local, a, b}
        assert v.quorum_level("/svc/web", 0.9, 1, now=0.0) == 0.9
        assert v.quorum_level("/svc/web", 0.9, 2, now=0.0) == 0.8
        assert v.quorum_level("/svc/web", 0.9, 3, now=0.0) == 0.2
        assert v.quorum_level("/svc/web", 0.9, 4, now=0.0) == 0.0

    def test_peer_table_bounded_against_hostile_id_churn(self):
        from linkerd_tpu.fleet.doc import MAX_PEERS
        v = FleetView("me", 1, ttl_s=1.0)
        for i in range(MAX_PEERS):
            assert v.ingest(_doc(inst=f"p{i}"), now=0.0)
        # table full of FRESH peers: a fabricated newcomer is rejected
        assert not v.ingest(_doc(inst="intruder"), now=0.5)
        assert v.rejected == 1
        assert len(v.all_docs()) == MAX_PEERS
        # once entries go stale, a legitimate newcomer displaces the
        # stalest one instead of growing the table
        assert v.ingest(_doc(inst="late-joiner"), now=5.0)
        assert len(v.all_docs()) == MAX_PEERS
        assert any(d.instance == "late-joiner" for d in v.all_docs())

    def test_auto_generation_is_restart_monotonic(self):
        # nanosecond auto-generations: two back-to-back incarnations
        # (a crash-looping supervisor) must never collide
        a = FleetExchange(FleetConfig(instance="x"), None)
        b = FleetExchange(FleetConfig(instance="x"), None)
        assert b.view.generation > a.view.generation

    def test_unreported_cluster_carries_no_vote(self):
        v = FleetView("me", 1)
        v.ingest(_doc(cluster="/svc/other", level=0.99), now=0.0)
        assert v.quorum_level("/svc/web", 0.9, 2, now=0.0) == 0.0
        assert v.sick_votes("/svc/web", 0.9, 0.5, now=0.0) == 1


# ---- CAS conflict regression (the machinery fleet exchange rides on) -------


class TestCasConflictConvergence:
    def test_local_writers_racing_converge_without_lost_update(self):
        """Two LocalStoreClient writers race the same namespace: both
        fetch the same version, one CAS loses — the retry loop must
        re-apply its mutation onto the WINNER's dtab (no lost update)."""
        async def go():
            store = InMemoryDtabStore({"ns": Dtab.read(BASE_DTAB)})
            gate = asyncio.Event()
            fetched = 0

            class _Gated(LocalStoreClient):
                async def fetch(self, ns):
                    nonlocal fetched
                    vd = await super().fetch(ns)
                    fetched += 1
                    if fetched <= 2:
                        # both writers hold the SAME version before
                        # either writes: a guaranteed conflict
                        if fetched == 2:
                            gate.set()
                        await gate.wait()
                    return vd

            conflicts = []

            def writer(tag):
                def mutate(dtab):
                    dentry = Dtab.read(f"/w/{tag} => /x/{tag} ;")[0]
                    return Dtab([d for d in dtab if d != dentry]
                                + [dentry])
                return cas_modify(_Gated(store), "ns", mutate,
                                  on_conflict=lambda: conflicts.append(tag))

            await asyncio.gather(writer("a"), writer("b"))
            vd = await store.observe("ns").to_future()
            assert "/w/a => /x/a" in vd.dtab.show
            assert "/w/b => /x/b" in vd.dtab.show
            assert "/svc => /#/io.l5d.fs" in vd.dtab.show
            assert len(conflicts) >= 1  # the race actually happened

        run(go())

    def test_http_writers_racing_converge_and_etag_is_honored(self):
        """The same race through the REAL namerd HTTP control API:
        If-Match ETags must 412 the loser (never clobber), and the
        retry loop must converge both writers."""
        async def go():
            namerd = Namerd(InMemoryDtabStore({"ns": Dtab.read(BASE_DTAB)}))
            srv = await HttpServer(HttpControlService(namerd)).start()
            addr = f"127.0.0.1:{srv.bound_port}"
            c1, c2 = NamerdHttpStoreClient(addr), NamerdHttpStoreClient(addr)
            try:
                # ETag honored: a stale version must 412 -> typed error
                vd = await c1.fetch("ns")
                await c1.cas("ns", vd.dtab, vd.version)  # bumps version
                with pytest.raises(DtabVersionMismatch):
                    await c2.cas("ns", vd.dtab, vd.version)

                # racing read-modify-write rounds from two HTTP clients
                async def writer(client, tag):
                    for i in range(5):
                        def mutate(dtab, tag=tag, i=i):
                            dentry = Dtab.read(
                                f"/w/{tag}{i} => /x/{tag} ;")[0]
                            return Dtab(list(dtab) + [dentry])
                        await cas_modify(client, "ns", mutate)

                await asyncio.gather(writer(c1, "a"), writer(c2, "b"))
                vd = await c1.fetch("ns")
                for tag in ("a", "b"):
                    for i in range(5):
                        assert f"/w/{tag}{i} => /x/{tag}" in vd.dtab.show, \
                            f"lost update: {tag}{i}"
            finally:
                await c1.aclose()
                await c2.aclose()
                await srv.close()
                await namerd.close()

        run(go())

    def test_create_race_converges(self):
        """Two writers racing the CREATION of a namespace: one wins the
        POST, the loser retries as an update — both dentries land."""
        async def go():
            store = InMemoryDtabStore()

            async def writer(tag):
                def mutate(dtab):
                    return Dtab(list(dtab)
                                + [Dtab.read(f"/w/{tag} => /x/{tag} ;")[0]])
                await cas_modify(LocalStoreClient(store), "fresh", mutate,
                                 create_if_missing=Dtab.empty())

            await asyncio.gather(writer("a"), writer("b"))
            vd = await store.observe("fresh").to_future()
            assert "/w/a" in vd.dtab.show and "/w/b" in vd.dtab.show

        run(go())


# ---- FleetExchange ---------------------------------------------------------


def _exchange(store, inst, gen=1, quorum=2, metrics=None, **kw):
    cfg = FleetConfig(instance=inst, generation=gen, quorum=quorum, **kw)
    node = (metrics.scope("control", "fleet")
            if metrics is not None else None)
    return cfg.mk(LocalStoreClient(store) if store is not None else None,
                  metrics_node=node)


class TestFleetExchange:
    def test_publish_ingests_peers_through_namerd(self):
        async def go():
            store = InMemoryDtabStore()
            m = MetricsTree()
            ex_a = _exchange(store, "a", metrics=m)
            ex_b = _exchange(store, "b")
            ex_a.set_source(lambda: {"/svc/web": 0.9})
            ex_b.set_source(lambda: {"/svc/web": 0.7})
            await ex_a.publish_once()   # creates the namespace
            await ex_b.publish_once()   # sees a's doc
            await ex_a.publish_once()   # sees b's doc
            assert ex_a.view.fresh_count() == 1
            assert ex_b.view.fresh_count() == 1
            assert ex_a.quorum_level("/svc/web", 0.9) == 0.7
            vd = await store.observe("fleet").to_future()
            assert len(vd.dtab) == 2  # one dentry per instance, no dups
            flat = m.flatten()
            assert flat["control/fleet/docs_published"] == 2
            assert flat["control/fleet/peers_fresh"] == 1.0

        run(go())

    def test_republish_replaces_own_dentry(self):
        async def go():
            store = InMemoryDtabStore()
            ex = _exchange(store, "a")
            ex.set_source(lambda: {"/svc/web": 0.5})
            for _ in range(4):
                await ex.publish_once()
            vd = await store.observe("fleet").to_future()
            assert len(vd.dtab) == 1

        run(go())

    def test_operator_dentries_in_namespace_survive(self):
        async def go():
            store = InMemoryDtabStore(
                {"fleet": Dtab.read("/ops => /#/io.l5d.fs/ops ;")})
            ex = _exchange(store, "a")
            await ex.publish_once()
            vd = await store.observe("fleet").to_future()
            assert "/ops => /#/io.l5d.fs/ops" in vd.dtab.show
            assert "/fleet/a" in vd.dtab.show

        run(go())

    def test_gossip_round_exchanges_docs_both_ways(self):
        async def go():
            # instance b serves the admin gossip endpoint
            ex_b = _exchange(None, "b")
            ex_b.set_source(lambda: {"/svc/web": 0.8})
            admin = AdminServer(MetricsTree(), port=0)
            for p, h in fleet_admin_handlers(ex_b):
                admin.add_handler(p, h)
            await admin.start()
            try:
                cfg = FleetConfig(
                    instance="a", generation=1, quorum=2,
                    peers=[f"127.0.0.1:{admin.bound_port}"])
                ex_a = FleetExchange(cfg, None)
                ex_a.set_source(lambda: {"/svc/web": 0.6})
                accepted = await ex_a.gossip_round()
                assert accepted == 1
                assert ex_a.quorum_level("/svc/web", 0.6) == 0.6
                # the push half: b learned a's doc from the POST body
                assert [d.instance for d in ex_b.view.all_docs()] == ["a"]
                await ex_a.aclose()
            finally:
                await admin.close()

        run(go())

    def test_malformed_gossip_input_dropped_not_raised(self):
        ex = _exchange(None, "a")
        assert ex.ingest_objs([{"i": "bad/slash"}, 42, None,
                               {"i": "ok", "g": 1, "s": 1}]) == 1
        assert ex.ingest_objs("nope") == 0

    def test_unwarmed_instance_publishes_identity_only(self):
        ex = _exchange(None, "a")
        ex.set_source(lambda: {"/svc/web": 0.99},
                      warmed_fn=lambda: False)
        doc = ex.build_doc()
        assert doc.clusters == {}
        assert doc.instance == "a"

    def test_config_validation(self):
        with pytest.raises(ValueError):
            FleetExchange(FleetConfig(instance="bad id!"), None)
        with pytest.raises(ValueError):
            FleetExchange(FleetConfig(instance="a", stalenessTtlS=0), None)
        assert FleetConfig(expectInstances=5).effective_quorum() == 3
        assert FleetConfig().effective_quorum() == 2
        assert FleetConfig(quorum=4).effective_quorum() == 4


class TestNamerdWatchIngest:
    """The standing-watch satellite: peer docs arrive through the
    namespace watch stream the moment the store applies them, not on
    this instance's next publish round (gossip stays the primary fast
    path; the watch replaces publish-time-only namerd ingest)."""

    def test_standing_watch_ingests_peer_writes(self):
        async def go():
            store = InMemoryDtabStore()
            m = MetricsTree()
            ex_a = _exchange(store, "a", metrics=m)
            ex_b = _exchange(store, "b")
            ex_b.set_source(lambda: {"/svc/web": 0.7})
            await ex_b.publish_once()  # creates the ns with b's doc
            assert ex_a.start_watch() is True
            assert ex_a.watching
            # the watch delivers the CURRENT state without a publishes
            # round from a
            for _ in range(200):
                if ex_a.view.fresh_count() == 1:
                    break
                await asyncio.sleep(0.01)
            assert ex_a.view.fresh_count() == 1
            (doc,) = ex_a.view.all_docs()
            first_seq = doc.seq
            # a peer write mid-watch lands push-style too
            await ex_b.publish_once()
            for _ in range(200):
                docs = ex_a.view.all_docs()
                if docs and docs[0].seq > first_seq:
                    break
                await asyncio.sleep(0.01)
            (doc,) = ex_a.view.all_docs()
            assert doc.seq > first_seq
            assert m.flatten()["control/fleet/watch_updates"] >= 2
            assert m.flatten()["control/fleet/watching"] == 1.0
            # publish-time ingest is OFF while the watch runs: a's own
            # publish keeps working and the view stays consistent
            ex_a.set_source(lambda: {"/svc/web": 0.9})
            await ex_a.publish_once()
            assert ex_a.view.fresh_count() == 1
            await ex_a.aclose()
            assert not ex_a.watching
            await ex_b.aclose()

        run(go())

    def test_start_watch_without_client_support_is_noop(self):
        async def go():
            class NoWatch:
                async def fetch(self, ns):
                    return None

            ex = FleetExchange(FleetConfig(instance="a", generation=1),
                               NoWatch())
            assert ex.start_watch() is False
            assert not ex.watching
            await ex.aclose()

        run(go())


# ---- quorum-gated actuation -------------------------------------------------


def _fleet_reactor(store, board, exchange, quorum=1, dwell=0.0,
                   metrics=None):
    node = (metrics or MetricsTree()).scope("control", "reactor")
    return MeshReactor(
        board, LocalStoreClient(store), "default",
        {"/svc/web": "/svc/web-b"},
        governor=HysteresisGovernor(enter=0.6, exit=0.2, quorum=quorum,
                                    dwell_s=dwell),
        metrics_node=node, namer_prefixes=PREFIXES, fleet=exchange)


class TestQuorumGatedActuation:
    def test_minority_evidence_never_actuates(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _Board()
            ex = _exchange(store, "me", quorum=2)
            r = _fleet_reactor(store, board, ex)
            board.levels["/svc/web"] = 0.95  # only WE see it
            for t in range(1, 20):
                await r.step(now=float(t))
            assert r.active == {}
            vd = await store.observe("default").to_future()
            assert "web-b" not in vd.dtab.show

        run(go())

    def test_quorum_evidence_actuates_and_reverts(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _Board()
            ex = _exchange(store, "me", quorum=2)
            r = _fleet_reactor(store, board, ex)
            board.levels["/svc/web"] = 0.95
            ex.view.ingest(_doc(inst="peer", level=0.9))
            await r.step(now=1.0)
            assert "/svc/web" in r.active
            # the peer recovers: quorum dissolves, revert
            ex.view.ingest(_doc(inst="peer", seq=2, level=0.05))
            board.levels["/svc/web"] = 0.1
            await r.step(now=2.0)
            assert r.active == {}
            vd = await store.observe("default").to_future()
            assert vd.dtab.show.strip() == Dtab.read(BASE_DTAB).show.strip()

        run(go())

    def test_stale_peer_loses_its_vote(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _Board()
            ex = _exchange(store, "me", quorum=2, stalenessTtlS=0.05)
            r = _fleet_reactor(store, board, ex)
            board.levels["/svc/web"] = 0.95
            ex.view.ingest(_doc(inst="peer", level=0.9))
            await asyncio.sleep(0.1)  # the peer's doc ages out
            await r.step(now=1.0)
            assert r.active == {}  # one live paranoid router: no shift

        run(go())

    def test_control_loop_wires_fleet_from_yaml(self, tmp_path):
        from linkerd_tpu.linker import load_linker
        linker = load_linker(f"""
routers:
- protocol: http
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {tmp_path}
telemetry:
- kind: io.l5d.jaxAnomaly
  control:
    namespace: default
    namerdAddress: 127.0.0.1:4180
    failover:
      /svc/web: /svc/web-b
    fleet:
      instance: l5d-a
      quorum: 2
      expectInstances: 3
      peers: [127.0.0.1:9991]
""")
        tele = linker.telemeters[0]
        assert tele.control.fleet is not None
        assert tele.control.fleet.quorum == 2
        assert tele.control.reactor._fleet is tele.control.fleet
        paths = [p for p, _ in tele.admin_handlers()]
        assert "/fleet.json" in paths
        assert "/fleet/gossip.json" in paths
        run(linker.close())


# ---- generation fencing -----------------------------------------------------


class TestGenerationFencing:
    def test_superseded_instance_stops_actuating(self):
        async def go():
            store = InMemoryDtabStore({"default": Dtab.read(BASE_DTAB)})
            board = _Board()
            m = MetricsTree()
            ex = _exchange(store, "me", quorum=1, metrics=m)
            r = _fleet_reactor(store, board, ex,
                               metrics=m)
            board.levels["/svc/web"] = 0.95
            ex.view.ingest(_doc(inst="me", gen=2))  # successor appeared
            await r.step(now=1.0)
            assert r.active == {}
            assert m.flatten()["control/reactor/fenced_steps"] == 1

        run(go())

    def test_stale_generation_cannot_revert_successors_override(self):
        """The satellite interleaving: the OLD incarnation enters its
        revert (cluster looks healthy to it), parks on the store
        fetch; the NEW incarnation's supersede signal can land at any
        point around it. Invariant over every seeded interleaving: no
        store write is ever DISPATCHED after the supersede was
        ingested (a write that raced ahead of the supersede is
        legitimate — fencing is about what happens once the signal is
        known)."""
        from linkerd_tpu.testing.schedules import explore

        def mk(sched):
            store = InMemoryDtabStore({"default": Dtab.read(
                BASE_DTAB + " /svc/web => /svc/web-b ;")})
            board = _Board()
            ex = _exchange(store, "me", quorum=1)
            writes_after_supersede = []

            class _Gated(LocalStoreClient):
                async def fetch(self, ns):
                    await sched.point("fetch")
                    return await super().fetch(ns)

                async def cas(self, ns, dtab, version):
                    writes_after_supersede.append(ex.superseded)
                    await super().cas(ns, dtab, version)

            r = _fleet_reactor(store, board, ex)
            r._client = _Gated(store)
            # the old incarnation believes it owns the override (it
            # published it before "restarting")
            r.active["/svc/web"] = Dtab.read("/svc/web => /svc/web-b ;")[0]
            board.levels["/svc/web"] = 0.0  # looks healthy to the zombie

            async def zombie_revert():
                await r.step(now=100.0)

            async def supersede():
                await sched.point("supersede")
                ex.view.ingest(_doc(inst="me", gen=2))

            async def check():
                await sched.point("check")
                assert not any(writes_after_supersede), \
                    "zombie dispatched a store write AFTER its " \
                    "supersede was ingested"
                vd = await store.observe("default").to_future()
                if not writes_after_supersede:
                    # no legitimate pre-supersede revert happened: the
                    # successor's dentry must have survived
                    assert "/svc/web => /svc/web-b" in vd.dtab.show
                return True

            return [zombie_revert(), supersede(), check()]

        def invariant(results):
            for res in results:
                if isinstance(res, BaseException):
                    raise AssertionError(repr(res))

        failure = explore(mk, invariant, seeds=range(32), timeout=10.0)
        assert failure is None, f"interleaving violated fencing: {failure}"

    def test_supersede_landing_mid_revert_is_fenced(self):
        """The exact worst-case order, pinned explicitly: the zombie's
        step passes its entry fence check and parks on the store
        fetch; the supersede lands; the fetch resumes — the re-check
        must block the revert (no write, bookkeeping untouched)."""
        from linkerd_tpu.testing.schedules import DeterministicScheduler

        store = InMemoryDtabStore({"default": Dtab.read(
            BASE_DTAB + " /svc/web => /svc/web-b ;")})
        board = _Board()
        ex = _exchange(store, "me", quorum=1)
        sched = DeterministicScheduler(
            order=["supersede", "fetch", "check"])
        wrote = []

        class _Gated(LocalStoreClient):
            async def fetch(self, ns):
                # the zombie parks HERE with its entry check already
                # passed; "supersede" is released before this point is
                await sched.point("fetch")
                return await super().fetch(ns)

            async def cas(self, ns, dtab, version):
                wrote.append(dtab.show)
                await super().cas(ns, dtab, version)

        r = _fleet_reactor(store, board, ex)
        r._client = _Gated(store)
        r.active["/svc/web"] = Dtab.read("/svc/web => /svc/web-b ;")[0]
        board.levels["/svc/web"] = 0.0

        async def supersede():
            await sched.point("supersede")
            ex.view.ingest(_doc(inst="me", gen=2))

        async def check():
            await sched.point("check")
            return True

        sched.run_sync(r.step(now=100.0), supersede(), check())
        assert wrote == []  # the revert never reached the store
        vd = store.observe("default").current.value
        assert "/svc/web => /svc/web-b" in vd.dtab.show
        assert "/svc/web" in r.active  # bookkeeping untouched too


# ---- scorer replica pool ----------------------------------------------------


class _FakeReplica:
    def __init__(self, addr, fail=False):
        self.addr = addr
        self.fail = fail
        self.calls = 0
        self.closed = False
        self.restored = None
        self.last_timing = {"rpc_ms": 1.0}

    async def score(self, x):
        self.calls += 1
        if self.fail:
            raise RuntimeError(f"replica {self.addr} down")
        return np.zeros(len(x), np.float32)

    async def fit(self, x, labels, mask):
        self.calls += 1
        return 0.0

    async def restore(self, snap):
        self.calls += 1
        if self.fail:
            raise RuntimeError(f"replica {self.addr} down")
        self.restored = snap
        return 0

    def close(self):
        self.closed = True


class TestScorerReplicaPool:
    def test_load_spreads_across_replicas(self):
        async def go():
            made = {}

            def mk(addr):
                made[addr] = _FakeReplica(addr)
                return made[addr]

            pool = ScorerReplicaPool(["a:1", "b:2"], mk_client=mk)
            for _ in range(10):
                await pool.score(np.zeros((4, 3), np.float32))
            assert made["a:1"].calls > 0 and made["b:2"].calls > 0

        run(go())

    def test_failover_to_healthy_replica(self):
        async def go():
            made = {}

            def mk(addr):
                made[addr] = _FakeReplica(addr, fail=addr.startswith("bad"))
                return made[addr]

            pool = ScorerReplicaPool(["bad:1", "ok:2"], mk_client=mk)
            for _ in range(6):
                out = await pool.score(np.zeros((2, 3), np.float32))
                assert len(out) == 2
            # dead replica was tried, healthy one carried every call
            assert made["ok:2"].calls >= 6

        run(go())

    def test_broadcast_restore_reaches_every_replica(self):
        """Fleet model coordination: a promote fans the snapshot out to
        EVERY replica (Snapshot/Restore RPCs), one dead replica skipped
        without blocking the rest."""
        async def go():
            made = {}

            def mk(addr):
                made[addr] = _FakeReplica(addr, fail=addr.startswith("bad"))
                return made[addr]

            pool = ScorerReplicaPool(["a:1", "bad:2", "c:3"],
                                     mk_client=mk)
            snap = object()
            assert await pool.broadcast_restore(snap) == 2
            assert made["a:1"].restored is snap
            assert made["c:3"].restored is snap
            assert made["bad:2"].restored is None
            assert pool.status()["replicas"]["bad:2"]["failures"] == 1

        run(go())

    def test_all_replicas_down_raises(self):
        async def go():
            pool = ScorerReplicaPool(
                ["bad:1", "bad:2"],
                mk_client=lambda a: _FakeReplica(a, fail=True))
            with pytest.raises(RuntimeError):
                await pool.score(np.zeros((2, 3), np.float32))

        run(go())

    def test_membership_diff_keeps_surviving_clients(self):
        made = {}

        def mk(addr):
            made[addr] = _FakeReplica(addr)
            return made[addr]

        pool = ScorerReplicaPool(["a:1", "b:2"], mk_client=mk)
        keep = made["a:1"]
        pool.set_addresses(["a:1", "c:3"])
        assert pool.addresses() == ["a:1", "c:3"]
        assert made["b:2"].closed
        assert not keep.closed
        # the surviving client object is the SAME instance (warm channel)
        assert pool._replicas["a:1"].scorer is keep

    def test_announced_replicas_resolve_through_real_namer(self, tmp_path):
        """The announcer half: two scorer 'replicas' fs-announce into a
        disco dir; the pool resolves /#/io.l5d.fs/l5d-scorer through a
        real FsNamer and converges on both addresses."""
        from linkerd_tpu.announcer import FsAnnouncer
        from linkerd_tpu.namer.fs import FsNamer

        async def go():
            ann = FsAnnouncer(str(tmp_path), Path.read("/io.l5d.fs"))
            a1 = ann.announce("127.0.0.1", 7001, Path.read("/l5d-scorer"))
            ann.announce("127.0.0.1", 7002, Path.read("/l5d-scorer"))
            namer = FsNamer(str(tmp_path), poll_interval=0.02)
            act = namer_scorer_activity(
                [(Path.read("/io.l5d.fs"), namer)], "/#/io.l5d.fs/l5d-scorer")
            pool = ScorerReplicaPool(mk_client=_FakeReplica)
            pool.attach_activity(act, poll_interval_s=0.02)
            pool.start_watch()
            try:
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if pool.addresses() == ["127.0.0.1:7001",
                                            "127.0.0.1:7002"]:
                        break
                    await asyncio.sleep(0.02)
                assert pool.addresses() == ["127.0.0.1:7001",
                                            "127.0.0.1:7002"]
                # a replica withdraws: the pool follows
                a1.close()
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if pool.addresses() == ["127.0.0.1:7002"]:
                        break
                    await asyncio.sleep(0.02)
                assert pool.addresses() == ["127.0.0.1:7002"]
            finally:
                pool.close()
                act.close()
                namer.close()

        run(go())

    def test_pool_over_real_grpc_sidecars_fails_over(self):
        """Two REAL gRPC scorer sidecars behind the pool: both serve
        score traffic; killing one fails calls over to the survivor
        within the same call."""
        pytest.importorskip("grpc")
        from linkerd_tpu.telemetry.sidecar import ScorerSidecar

        class _Stub:
            async def score(self, x):
                return np.full(len(x), 0.25, np.float32)

            async def fit(self, x, labels, mask):
                return 0.0

            def close(self):
                pass

        async def go():
            s1 = await ScorerSidecar(scorer=_Stub()).start()
            s2 = await ScorerSidecar(scorer=_Stub()).start()
            pool = ScorerReplicaPool(
                [f"127.0.0.1:{s1.port}", f"127.0.0.1:{s2.port}"])
            try:
                x = np.zeros((8, 4), np.float32)
                for _ in range(6):
                    out = await pool.score(x)
                    assert out.shape == (8,)
                    assert float(out[0]) == 0.25
                calls = {a: r.calls
                         for a, r in pool._replicas.items()}
                assert all(c > 0 for c in calls.values()), calls
                await s1.close()
                for _ in range(4):  # failover carries every call
                    out = await pool.score(x)
                    assert out.shape == (8,)
            finally:
                pool.close()
                await s2.close()

        run(go())

    def test_unknown_namer_path_fails_loudly(self):
        with pytest.raises(ValueError):
            namer_scorer_activity([], "/#/io.l5d.nope/l5d-scorer")
        with pytest.raises(ValueError):
            namer_scorer_activity([], "/svc/l5d-scorer")

    def test_telemeter_builds_pool_for_list_and_path_addresses(self):
        from linkerd_tpu.telemetry.anomaly import (
            JaxAnomalyConfig, JaxAnomalyTelemeter,
        )
        tele = JaxAnomalyTelemeter(
            JaxAnomalyConfig(sidecarAddress="127.0.0.1:1,127.0.0.1:2"),
            MetricsTree())
        client = tele._mk_sidecar_client()
        assert isinstance(client, ScorerReplicaPool)
        assert client.addresses() == ["127.0.0.1:1", "127.0.0.1:2"]
        client.close()
        tele2 = JaxAnomalyTelemeter(
            JaxAnomalyConfig(sidecarAddress="/#/io.l5d.fs/l5d-scorer"),
            MetricsTree())
        client2 = tele2._mk_sidecar_client()
        assert isinstance(client2, ScorerReplicaPool)
        assert client2.addresses() == []
        client2.close()


# ---- end to end on the real binaries ---------------------------------------


class TestFleetEndToEnd:
    def test_quorum_shift_and_exact_revert_on_real_binaries(self):
        """3 linkerds + namerd as subprocesses (assembled binaries): a
        fault observed by 1/3 instances shifts NOTHING; the same fault
        observed by 2/3 triggers exactly ONE fleet-wide dtab shift
        (peers adopt, zero flaps); recovery reverts the namespace to
        exactly its base dtab."""
        from linkerd_tpu.testing.fleet import FleetHarness, _http

        async def go():
            # Flake root cause (diagnosed by snapshotting /fleet.json +
            # /control.json the instant overrides_published went
            # nonzero): with the old warmup_batches=40 the online model
            # was so undertrained that HEALTHY traffic scored 0.5-0.8 —
            # past enter=0.5 — and since the noise is correlated across
            # instances (CPU contention in this 5-process harness slows
            # the shared downstream for everyone at once), the fleet
            # quorum was trivially satisfied and spurious overrides
            # published with no fault injected at all; measurement
            # showed NO threshold separating that noise (max 0.80) from
            # the genuine fault signal (max 0.73). With 300 warmup
            # batches the model separates cleanly — healthy max ~0.47,
            # faulted peak ~0.85 — so enter=0.6/exit=0.25 classify
            # deterministically. The second trap: the model ADAPTS to a
            # sustained fault in ~15s (faulted level decays to ~0.27),
            # so (a) every phase polls its entry CONDITION with a hard
            # deadline instead of sleeping fixed amounts, and (b) the
            # quorum phase faults a FRESH pair of instances — reusing
            # the phase-1 instance, whose model has already learned the
            # fault as normal, would leave quorum forever unreachable.
            # governor_quorum=20 (1s of consecutive 50ms samples past
            # the threshold) filters the sub-second correlated spikes
            # that remain; the fault's ~15s transient sails past it.
            # exit=0.45, not lower: leaving overridden ALSO needs 20
            # consecutive samples (<= exit for a full second), and
            # healthy levels oscillate 0.13-0.48 — against exit=0.25
            # an unbroken second below threshold almost never lines
            # up and reverts stall past any reasonable deadline.
            h = FleetHarness(n=3, quorum=2, warmup_batches=300,
                             enter=0.6, exit=0.45, governor_quorum=20)
            await h.start()
            try:
                h.start_traffic(interval_s=0.02)
                await h.warm(settle_s=3.0)
                def fleet_view(i: int) -> dict:
                    _, body = _http(
                        "GET",
                        f"http://127.0.0.1:{h.admin_ports[i]}/fleet.json")
                    return json.loads(body)

                def reactor_view(i: int) -> dict:
                    _, body = _http(
                        "GET",
                        f"http://127.0.0.1:{h.admin_ports[i]}"
                        f"/control.json")
                    return json.loads(body)["reactor"]

                # the quiescence gate judges the statistic quorum
                # actuation actually folds — the 2nd-highest fresh
                # level — NOT every level: uncorrelated single-instance
                # spikes are normal here and harmless under quorum
                def fleet_quiescent() -> bool:
                    for i in range(3):
                        peers = fleet_view(i)["peers"]
                        if len(peers) != 2 or not all(
                                p["fresh"] for p in peers.values()):
                            return False
                        r = reactor_view(i)
                        if r["active_overrides"]:
                            return False
                        levels = [p["clusters"].get("/svc/web", 0.0)
                                  for p in peers.values()]
                        levels.append(r["levels"].get("/svc/web", 0.0))
                        if sorted(levels, reverse=True)[1] >= h.enter:
                            return False
                    return True

                await h.wait_for(
                    fleet_quiescent, 120,
                    "fleet quiescent: mesh fresh, quorum level calm, "
                    "no active overrides")

                async def baseline() -> tuple:
                    return (
                        await h.fleet_metric_sum(
                            "control/reactor/overrides_published"),
                        await h.fleet_metric_sum(
                            "control/reactor/overrides_adopted"),
                        await h.fleet_metric_sum(
                            "control/reactor/overrides_reverted"))

                # cumulative counters are baselined and asserted as
                # DELTAS, so a residual warmup transient that published
                # and reverted before quiescence cannot masquerade as a
                # fault-driven shift
                base_pub, base_adopt, base_revert = await baseline()

                # phase 1: minority evidence -> no shift. Two measured
                # facts shape the window mechanics: (a) the faulted
                # instance's elevation is TRANSIENT (~0.7 for 2-3s,
                # then the model starts adapting), so each peer's
                # sighting of it is recorded with a STICKY flag rather
                # than demanding both peers see it simultaneously; (b)
                # healthy instances throw 1-5s ambient spikes past
                # enter every ~30s, which is genuine 2-of-3 evidence
                # the quorum is SUPPOSED to act on — but the governor's
                # 1s streak filter absorbs most of them, so a spike
                # only invalidates the verdict when a shift actually
                # happened. Hence: run the window, track co-elevation
                # stickily, and judge afterwards — no shift = pass
                # regardless of spikes; shift + co-elevation = polluted
                # window, re-quiesce and retry; shift with NO
                # co-elevation = the quorum fold itself actuated on one
                # report, the genuine bug this phase exists to catch.
                faulted_id = h.instance_ids[0]

                async def minority_window() -> tuple:
                    """Returns (shifted, polluted) for one fault
                    window against instance 0 alone."""
                    seen = {1: False, 2: False}
                    polluted = False

                    def sample() -> None:
                        nonlocal polluted
                        for i in (1, 2):
                            try:
                                p = fleet_view(i)["peers"].get(
                                    faulted_id)
                                local = reactor_view(i)["levels"].get(
                                    "/svc/web", 0.0)
                            except Exception:  # noqa: BLE001 — probe
                                continue       # hiccup, not evidence
                            if (p is not None and p["fresh"]
                                    and p["clusters"].get(
                                        "/svc/web", 0.0) >= h.enter):
                                seen[i] = True
                            if local >= h.enter:
                                polluted = True

                    h.primary.fault_insts = {faulted_id}
                    try:
                        deadline = time.monotonic() + 30
                        while not all(seen.values()):
                            if time.monotonic() > deadline:
                                raise AssertionError(
                                    "minority evidence never became "
                                    f"visible at both peers ({seen})")
                            await asyncio.to_thread(sample)
                            await asyncio.sleep(0.2)
                        # hold: > governor streak window (1s) + dwell,
                        # ample time for a broken fold to (wrongly) act
                        hold_until = time.monotonic() + 4.0
                        while time.monotonic() < hold_until:
                            await asyncio.to_thread(sample)
                            await asyncio.sleep(0.2)
                    finally:
                        h.primary.fault_insts = set()
                    pub = await h.fleet_metric_sum(
                        "control/reactor/overrides_published")
                    return pub != base_pub, polluted

                for attempt in range(4):
                    shifted, polluted = await minority_window()
                    if not shifted:
                        break
                    assert polluted, "shifted on minority evidence"
                    # the ambient spike made it 2-of-3 for a full
                    # governor streak — a legitimate shift, not the
                    # fold acting on one report: settle, re-baseline,
                    # try again
                    await h.wait_for(
                        fleet_quiescent, 90,
                        f"re-quiesce after polluted minority window "
                        f"{attempt}")
                    base_pub, base_adopt, base_revert = await baseline()
                else:
                    raise AssertionError(
                        "4 consecutive minority windows shifted under "
                        "ambient co-elevation — environment too noisy")

                # phase 2: quorum evidence -> exactly one fleet shift.
                # Fault a FRESH pair: instance 0's model has been
                # learning the fault as its new normal since phase 1,
                # so its level has decayed and could never re-vote; 1+2
                # both report fresh (undecayed) evidence.
                h.primary.fault_insts = {h.instance_ids[1],
                                         h.instance_ids[2]}
                await h.wait_metric(
                    "control/reactor/overrides_published",
                    base_pub + 1, 90)
                # the shift is FLEET-wide: visible at the UNfaulted
                # instance too
                await h.wait_for(
                    lambda: h._route_sync(0) == b"B", 20,
                    "shift visible at the unfaulted instance")
                assert await h.fleet_metric_sum(
                    "control/reactor/overrides_published") == base_pub + 1
                # peers ADOPT the published dentry instead of stacking
                # duplicates (their governors trip within the same
                # evidence window; the count is cumulative, so a
                # bounded wait observes it without racing them)
                await h.wait_metric(
                    "control/reactor/overrides_adopted",
                    base_adopt + 1, 20)

                # phase 3: recovery -> exact revert, zero flaps
                h.primary.fault_insts = set()
                await h.wait_metric(
                    "control/reactor/overrides_reverted",
                    base_revert + 1, 90)
                await h.wait_for(
                    lambda: h._route_sync(0) == b"A", 20,
                    "traffic back on the primary")
                assert await h.fleet_metric_sum(
                    "control/reactor/overrides_published") \
                    == base_pub + 1, "flapped"

                def namespace_is_base() -> bool:
                    _, body = _http(
                        "GET", h._namerd_url("/api/1/dtabs/default"))
                    dentries = json.loads(body)
                    return dentries == [
                        {"prefix": "/svc", "dst": "/#/io.l5d.fs"}]

                await h.wait_for(namespace_is_base, 10,
                                 "namespace reverted to exactly base")

                # the fleet saw each other: every instance ingested docs
                for i in range(3):
                    st = await h.admin_json(i, "/fleet.json")
                    assert len(st["peers"]) == 2, st
            finally:
                await h.stop()

        run(go(), timeout=420)
