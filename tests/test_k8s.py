"""k8s namer against a scripted fake API server.

The reference's test technique exactly (k8s/src/test/.../EndpointsNamerTest
.scala:15-56): a fake HTTP service replays captured list/watch JSON —
init, scale-up, scale-down, watch-expiry — and the namer's Var[Addr] is
asserted through each transition.
"""

import asyncio
import json

import pytest

from linkerd_tpu.core import Path
from linkerd_tpu.core.addr import Bound
from linkerd_tpu.core.nametree import Leaf
from linkerd_tpu.k8s.client import K8sApi, Watcher
from linkerd_tpu.k8s.namer import EndpointsNamer, ServiceNamer
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.router.service import FnService


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


def endpoints_obj(version: str, ips, port=8080, port_name="http"):
    return {
        "kind": "Endpoints",
        "metadata": {"resourceVersion": version,
                     "name": "web", "namespace": "prod"},
        "subsets": [{
            "addresses": [{"ip": ip} for ip in ips],
            "ports": [{"name": port_name, "port": port}],
        }],
    }


class FakeK8sApi:
    """Scripted fake: serves one endpoints object + a watch event queue."""

    def __init__(self):
        self.obj = endpoints_obj("100", ["10.0.0.1", "10.0.0.2"])
        self.events: asyncio.Queue = asyncio.Queue()
        self.list_count = 0
        self.watch_count = 0

    def service(self):
        async def handler(req: Request) -> Response:
            assert "/api/v1/namespaces/prod/endpoints/web" in req.uri
            if "watch=true" not in req.uri:
                self.list_count += 1
                return Response(status=200,
                                body=json.dumps(self.obj).encode())
            self.watch_count += 1

            async def gen():
                while True:
                    evt = await self.events.get()
                    if evt is None:  # close stream
                        return
                    yield (json.dumps(evt) + "\n").encode()
            return Response(status=200, body_stream=gen())
        return FnService(handler)

    def push(self, evt):
        self.events.put_nowait(evt)


class TestEndpointsNamer:
    def test_init_scale_up_down_and_expiry_relist(self):
        async def go():
            fake = FakeK8sApi()
            server = await HttpServer(fake.service()).start()
            api = K8sApi("127.0.0.1", server.bound_port, use_tls=False)
            namer = EndpointsNamer(api)

            act = namer.lookup(Path.read("/prod/http/web/extra"))
            # wait for the initial list to land
            for _ in range(100):
                from linkerd_tpu.core.activity import Ok
                if isinstance(act.current, Ok):
                    break
                await asyncio.sleep(0.02)
            tree = act.sample()
            assert isinstance(tree, Leaf)
            bn = tree.value
            assert bn.id_.show == "/#/io.l5d.k8s/prod/http/web"
            assert bn.residual.show == "/extra"
            addr = bn.addr.sample()
            assert isinstance(addr, Bound)
            assert sorted(a.host for a in addr.addresses) == [
                "10.0.0.1", "10.0.0.2"]
            assert all(a.port == 8080 for a in addr.addresses)

            # scale up via watch event
            fake.push({"type": "MODIFIED", "object": endpoints_obj(
                "101", ["10.0.0.1", "10.0.0.2", "10.0.0.3"])})
            for _ in range(100):
                if len(bn.addr.sample().addresses) == 3:
                    break
                await asyncio.sleep(0.02)
            assert len(bn.addr.sample().addresses) == 3

            # scale down
            fake.push({"type": "MODIFIED",
                       "object": endpoints_obj("102", ["10.0.0.3"])})
            for _ in range(100):
                if len(bn.addr.sample().addresses) == 1:
                    break
                await asyncio.sleep(0.02)
            assert [a.host for a in bn.addr.sample().addresses] == ["10.0.0.3"]

            # watch expiry: in-stream 410 -> re-list -> new state visible
            fake.obj = endpoints_obj("200", ["10.9.9.9"])
            fake.push({"type": "ERROR",
                       "object": {"kind": "Status", "code": 410}})
            for _ in range(200):
                addrs = bn.addr.sample().addresses
                if [a.host for a in addrs] == ["10.9.9.9"]:
                    break
                await asyncio.sleep(0.02)
            assert [a.host for a in bn.addr.sample().addresses] == ["10.9.9.9"]
            assert fake.list_count >= 2  # re-listed after Gone

            namer.close()
            await server.close()
        run(go())

    def test_numeric_port_and_missing_port(self):
        obj = endpoints_obj("1", ["10.0.0.1"], port=9090, port_name="admin")
        from linkerd_tpu.k8s.namer import _endpoints_addrs
        by_num = _endpoints_addrs(obj, "9090")
        assert [a.port for a in by_num.addresses] == [9090]
        by_name = _endpoints_addrs(obj, "admin")
        assert [a.port for a in by_name.addresses] == [9090]
        none = _endpoints_addrs(obj, "http")
        assert none.addresses == frozenset()

    def test_service_namer_lb_ingress(self):
        from linkerd_tpu.k8s.namer import _lb_addrs
        svc = {
            "kind": "Service",
            "spec": {"ports": [{"name": "https", "port": 443}]},
            "status": {"loadBalancer": {"ingress": [
                {"ip": "35.1.2.3"}, {"hostname": "lb.example.com"}]}},
        }
        bound = _lb_addrs(svc, "https")
        assert sorted(a.host for a in bound.addresses) == [
            "35.1.2.3", "lb.example.com"]
        assert all(a.port == 443 for a in bound.addresses)


class TestRouterWithK8sNamer:
    def test_linker_routes_via_k8s_endpoints(self):
        """Full slice: http router + io.l5d.k8s namer + fake API + live
        downstream (HttpEndToEndTest style with the k8s backend)."""
        from linkerd_tpu.linker import load_linker
        from linkerd_tpu.protocol.http.client import HttpClient
        from linkerd_tpu.protocol.http.server import serve

        async def go():
            async def hello(req):
                return Response(status=200, body=b"from-pod")
            downstream = await serve(FnService(hello))

            fake = FakeK8sApi()
            fake.obj = {
                "kind": "Endpoints",
                "metadata": {"resourceVersion": "1", "name": "web",
                             "namespace": "prod"},
                "subsets": [{
                    "addresses": [{"ip": "127.0.0.1"}],
                    "ports": [{"name": "http",
                               "port": downstream.bound_port}],
                }],
            }
            k8s_srv = await HttpServer(fake.service()).start()

            cfg = f"""
routers:
- protocol: http
  label: k8sout
  dtab: |
    /svc => /#/io.l5d.k8s/prod/http ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.k8s
  host: 127.0.0.1
  port: {k8s_srv.bound_port}
  useTls: false
"""
            linker = load_linker(cfg)
            await linker.start()
            proxy = HttpClient("127.0.0.1",
                               linker.routers[0].server_ports[0])
            try:
                req = Request(uri="/")
                req.headers.set("Host", "web")
                rsp = await proxy(req)
                assert (rsp.status, rsp.body) == (200, b"from-pod")
            finally:
                await proxy.close()
                await linker.close()
                await k8s_srv.close()
                await downstream.close()
        run(go())


class TestMissingService:
    def test_404_service_resolves_neg(self):
        """A nonexistent service must bind Neg (falling through dtab
        alternatives), not hang Pending (ref: Api 404 -> Status)."""
        async def handler(req: Request) -> Response:
            return Response(status=404, body=json.dumps(
                {"kind": "Status", "code": 404,
                 "message": "endpoints \"ghost\" not found"}).encode())

        async def go():
            server = await HttpServer(FnService(handler)).start()
            api = K8sApi("127.0.0.1", server.bound_port, use_tls=False)
            namer = EndpointsNamer(api)
            act = namer.lookup(Path.read("/prod/http/ghost"))
            from linkerd_tpu.core.activity import Ok
            from linkerd_tpu.core.nametree import Neg
            for _ in range(100):
                if isinstance(act.current, Ok):
                    break
                await asyncio.sleep(0.02)
            assert isinstance(act.sample(), Neg)
            namer.close()
            await server.close()
        run(go())


class TestLabelSelector:
    def test_label_value_segment_filters_watch(self):
        """With labelSelector configured, paths carry a trailing label
        value and the endpoints watch filters by label=value
        (ref: EndpointsNamer.scala labelSelector handling)."""
        seen_paths = []

        class SelectorFake(FakeK8sApi):
            def __init__(self):
                super().__init__()
                # the pods behind this service carry version=v1
                self.obj["metadata"]["labels"] = {"version": "v1"}

            def service(self):
                inner = super().service()

                async def handler(req):
                    seen_paths.append(req.uri)
                    return await inner(req)
                return FnService(handler)

        async def go():
            fake = SelectorFake()
            server = await HttpServer(fake.service()).start()
            api = K8sApi("127.0.0.1", server.bound_port, use_tls=False)
            namer = EndpointsNamer(api, label_name="version")
            try:
                act = namer.lookup(Path.read("/prod/http/web/v1/rest"))
                for _ in range(100):
                    from linkerd_tpu.core.activity import Ok
                    if isinstance(act.current, Ok):
                        break
                    await asyncio.sleep(0.02)
                tree = act.sample()
                assert isinstance(tree, Leaf)
                bn = tree.value
                assert bn.id_.show == "/#/io.l5d.k8s/prod/http/web/v1"
                assert bn.residual.show == "/rest"
                assert any("labelSelector=version%3Dv1" in p
                           for p in seen_paths), seen_paths

                # too-short path (no label value) -> Neg
                from linkerd_tpu.core.nametree import Neg
                act2 = namer.lookup(Path.read("/prod/http/web"))
                assert isinstance(act2.sample(), Neg)

                # non-matching label value filters CLIENT-side too (real
                # API servers ignore labelSelector on single-object GETs)
                act3 = namer.lookup(Path.read("/prod/http/web/v9"))
                for _ in range(100):
                    from linkerd_tpu.core.activity import Ok
                    if isinstance(act3.current, Ok):
                        break
                    await asyncio.sleep(0.02)
                assert isinstance(act3.sample(), Neg)
            finally:
                namer.close()
                await server.close()

        run(go())
