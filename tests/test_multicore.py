"""Multi-core native data plane: SO_REUSEPORT-sharded engines.

Proves the sharding contract end to end:

- N worker engines share the router's ports and the kernel's
  per-connection spread reaches every worker;
- per-core stats slabs merge at scrape time (merged == sum of
  per-worker, histograms added element-wise, route ids in lockstep);
- ONE publish into the shared read-only weight slab fans out to every
  worker atomically (each worker's ``native_scorer`` block reports the
  same version; rows retired on every core come back pre-scored);
- per-tenant quotas split N ways (floor division: the global cap is
  never exceeded — and a limit below N sheds the tenant entirely,
  which l5dcheck's ``fastpath-workers`` rule warns about);
- ``workers=1`` keeps today's exact behavior (legacy bind, embedded
  slab, unmerged stats shape);
- the Python data plane's SNI half of ``tenantIdentifier: sni``
  (PR satellite): the asyncio TLS servers stamp ``req.ctx["sni"]``,
  and the extracted tenant hashes bit-identically to the engines'.
"""

import asyncio
import os
import subprocess

import numpy as np
import pytest

native = pytest.importorskip("linkerd_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """Self-signed localhost cert (openssl CLI)."""
    d = tmp_path_factory.mktemp("mc-tls")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048",
             "-keyout", key, "-out", cert, "-days", "2", "-nodes",
             "-subj", "/CN=localhost"],
            check=True, capture_output=True, timeout=60)
    except (OSError, subprocess.SubprocessError):
        pytest.skip("openssl CLI unavailable")
    return cert, key


async def _echo_backend():
    async def handle(r, w):
        try:
            while True:
                await r.readuntil(b"\r\n\r\n")
                w.write(b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok")
                await w.drain()
        except Exception:  # noqa: BLE001 — client went away
            pass

    srv = await asyncio.start_server(handle, "127.0.0.1", 0)
    return srv, srv.sockets[0].getsockname()[1]


async def _one_shot(port: int, host: str = "svc",
                    headers: str = "") -> bytes:
    """One request on a FRESH connection (a fresh 4-tuple, so the
    kernel's REUSEPORT hash keeps spreading across workers)."""
    r, w = await asyncio.open_connection("127.0.0.1", port)
    w.write(f"GET / HTTP/1.1\r\nHost: {host}\r\n{headers}"
            f"Connection: close\r\n\r\n".encode())
    await w.drain()
    data = await r.read(65536)
    w.close()
    try:
        await w.wait_closed()
    except Exception:  # noqa: BLE001
        pass
    return data


class TestShardedEngine:
    def test_both_workers_serve_and_merged_equals_sum(self):
        async def go():
            srv, bport = await _echo_backend()
            eng = native.FastPathEngine(workers=2)
            try:
                port = eng.listen("127.0.0.1", 0)
                eng.start()
                eng.set_route("svc", [("127.0.0.1", bport)])
                n = 80
                ok = 0
                for _ in range(n):
                    if b"200 OK" in await _one_shot(port):
                        ok += 1
                assert ok == n
                st = eng.stats()
                per = [s.get("routes", {}).get("svc", {})
                       for s in st["workers"]]
                reqs = [int(p.get("requests", 0)) for p in per]
                # the kernel spread must reach BOTH workers (80 fresh
                # 4-tuples: all-on-one-worker is ~2^-80)
                assert all(r > 0 for r in reqs), reqs
                assert st["routes"]["svc"]["requests"] == sum(reqs) == n
                # histograms merge element-wise
                assert sum(st["routes"]["svc"]["hist"]) == n
                # accepted merges too
                assert st["accepted"] == sum(
                    int(s.get("accepted", 0)) for s in st["workers"])
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_route_ids_lockstep_across_workers(self):
        eng = native.FastPathEngine(workers=3)
        try:
            for host in ("alpha", "beta", "gamma"):
                eng.set_route(host, [("127.0.0.1", 9)])
            eng.remove_route("beta")
            eng.set_route("beta", [("127.0.0.1", 9)])  # re-add: new id
            st = eng.stats()
            for host in ("alpha", "beta", "gamma"):
                ids = {s["routes"][host]["id"] for s in st["workers"]}
                assert len(ids) == 1, (host, ids)
        finally:
            eng.close()

    def test_single_publish_fans_out_to_all_workers(self):
        async def go():
            srv, bport = await _echo_backend()
            eng = native.FastPathEngine(workers=2)
            try:
                port = eng.listen("127.0.0.1", 0)
                eng.start()
                eng.set_route("svc", [("127.0.0.1", bport)])
                eng.set_route_feature("svc", 14, 1.0)
                # ONE publish into the shared slab
                eng.publish_weights(
                    native.score_test_blob(version=7, seed=3))
                n = 60
                for _ in range(n):
                    await _one_shot(port)
                await asyncio.sleep(0.1)
                rows = eng.drain_features()
                assert len(rows) == n
                # every row pre-scored, regardless of which core
                # retired it
                assert int((rows[:, 7] > 0.5).sum()) == n
                st = eng.stats()
                ns = [s["native_scorer"] for s in st["workers"]]
                assert all(x["version"] == 7 and x["weights"]
                           for x in ns), ns
                # both cores actually evaluated (scored > 0 each)
                assert all(int(x["scored"]) > 0 for x in ns), ns
                merged = st["native_scorer"]
                assert merged["scored"] == sum(
                    int(x["scored"]) for x in ns) == n
                # hot-swap: the next publish flips EVERY worker
                eng.publish_weights(
                    native.score_test_blob(version=8, seed=4))
                st = eng.stats()
                assert all(s["native_scorer"]["version"] == 8
                           for s in st["workers"])
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_quota_splits_and_zero_per_worker_sheds_all(self):
        async def go():
            from linkerd_tpu.router.tenancy import tenant_hash
            srv, bport = await _echo_backend()
            eng = native.FastPathEngine(workers=2)
            eng.set_tenant("header", "l5d-tenant")
            try:
                port = eng.listen("127.0.0.1", 0)
                eng.start()
                eng.set_route("svc", [("127.0.0.1", bport)])
                # limit 4 across 2 workers -> 2 per worker
                eng.set_tenant_quota(tenant_hash("t-a"), 4)
                ok = 0
                for _ in range(10):
                    if b"200 OK" in await _one_shot(
                            port, headers="l5d-tenant: t-a\r\n"):
                        ok += 1
                assert ok == 10  # sequential: never over quota
                st = eng.stats()
                quotas = [
                    s["tenants"]["by_tenant"][
                        str(tenant_hash("t-a"))]["quota"]
                    for s in st["workers"]
                    if s["tenants"]["by_tenant"]]
                assert quotas and all(q == 2 for q in quotas), quotas
                # merged view reports the global cap (sum of splits)
                assert st["tenants"]["by_tenant"][
                    str(tenant_hash("t-a"))]["quota"] == 4
                # limit 1 across 2 workers -> 0 per worker: shed ALL
                # (the shape l5dcheck's fastpath-workers rule warns on)
                eng.set_tenant_quota(tenant_hash("t-b"), 1)
                shed = 0
                for _ in range(6):
                    if b"503" in await _one_shot(
                            port, headers="l5d-tenant: t-b\r\n"):
                        shed += 1
                assert shed == 6
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_workers1_keeps_single_engine_stats_shape(self):
        eng = native.FastPathEngine()  # default workers=1
        try:
            assert eng.workers == 1
            eng.set_route("svc", [("127.0.0.1", 9)])
            st = eng.stats()
            assert "workers" not in st  # unmerged legacy shape
            assert "svc" in st["routes"]
        finally:
            eng.close()

    def test_drain_features_into_fans_in_across_workers(self):
        async def go():
            srv, bport = await _echo_backend()
            eng = native.FastPathEngine(workers=2)
            try:
                port = eng.listen("127.0.0.1", 0)
                eng.start()
                eng.set_route("svc", [("127.0.0.1", bport)])
                n = 40
                for _ in range(n):
                    await _one_shot(port)
                await asyncio.sleep(0.1)
                out = np.zeros((n, eng.FEATURE_DIM), np.float32)
                got = eng.drain_features_into(out)
                assert got == n
                # every row is a real feature row (status col == 200)
                assert np.all(out[:n, 2] == 200.0)
            finally:
                eng.close()
                srv.close()
                await srv.wait_closed()

        run(go())

    def test_h2_shard_group_shares_slab(self):
        eng = native.H2FastPathEngine(workers=2)
        try:
            port = eng.listen("127.0.0.1", 0)
            assert port > 0
            eng.start()
            eng.publish_weights(native.score_test_blob(version=5, seed=1))
            st = eng.stats()
            assert len(st["workers"]) == 2
            assert all(s["native_scorer"]["version"] == 5
                       for s in st["workers"])
        finally:
            eng.close()

    def test_invalid_worker_counts_rejected(self):
        with pytest.raises(ValueError):
            native.FastPathEngine(workers=0)
        with pytest.raises(ValueError):
            native.FastPathEngine(workers=65)


class TestShardedLinker:
    def test_workers_requires_fastpath(self):
        from linkerd_tpu.config import ConfigError
        from linkerd_tpu.linker import load_linker
        with pytest.raises(ConfigError, match="workers"):
            load_linker("""
routers:
- protocol: http
  workers: 2
  servers: [{port: 0}]
""")

    def test_workers_out_of_range_rejected(self):
        from linkerd_tpu.config import ConfigError
        from linkerd_tpu.linker import load_linker
        with pytest.raises(ConfigError, match="workers"):
            load_linker("""
routers:
- protocol: http
  fastPath: true
  workers: 9999
  servers: [{port: 0}]
""")

    def test_sharded_router_serves_and_exports_per_worker(self, tmp_path):
        """Assembled (in-process) linker with ``workers: 2``: traffic
        reaches both workers, the controller exports
        rt/*/fastpath/worker/<i>/* breakdowns, and the merged route
        counter equals their sum."""
        async def go():
            from linkerd_tpu.linker import load_linker
            srv, bport = await _echo_backend()
            disco = tmp_path / "disco"
            disco.mkdir()
            (disco / "web").write_text(f"127.0.0.1 {bport}\n")
            linker = load_linker(f"""
routers:
- protocol: http
  label: mc
  fastPath: true
  workers: 2
  dtab: |
    /svc => /#/io.l5d.fs ;
  servers: [{{port: 0}}]
namers:
- kind: io.l5d.fs
  rootDir: {disco}
""")
            await linker.start()
            try:
                port = linker.routers[0].server_ports[0]
                assert linker.routers[0].controller.engine.workers == 2
                # first request parks on a miss; the controller
                # resolves + broadcasts the route
                for _ in range(3):
                    if b"200 OK" in await _one_shot(port, host="web"):
                        break
                    await asyncio.sleep(0.3)
                n = 60
                ok = 0
                for _ in range(n):
                    if b"200 OK" in await _one_shot(port, host="web"):
                        ok += 1
                assert ok == n
                # the stats loop runs at 1s: wait for the export
                for _ in range(80):
                    flat = linker.metrics.flatten()
                    w0 = flat.get("rt/mc/fastpath/worker/0/requests", 0)
                    w1 = flat.get("rt/mc/fastpath/worker/1/requests", 0)
                    if w0 + w1 >= n:
                        break
                    await asyncio.sleep(0.25)
                assert w0 > 0 and w1 > 0, (w0, w1)
                merged = flat.get("rt/mc/fastpath/route/web/requests", 0)
                assert merged == w0 + w1, (merged, w0, w1)
            finally:
                await linker.close()
                srv.close()
                await srv.wait_closed()

        run(go())


class TestPythonSniExtraction:
    """The asyncio TLS data plane's half of ``tenantIdentifier: sni``
    (ROADMAP item 5 remainder): the server surfaces the handshake's
    server name into ``req.ctx["sni"]`` and TenantTagFilter's hash is
    bit-identical to the engines' C extraction."""

    def test_http_server_surfaces_sni_parity_with_engine(self, certs):
        async def go():
            import ssl

            from linkerd_tpu.protocol.http import Response
            from linkerd_tpu.protocol.http.server import HttpServer
            from linkerd_tpu.protocol.tls import TlsServerConfig
            from linkerd_tpu.router.service import FnService
            from linkerd_tpu.router.tenancy import (
                TenantIdentifierSpec, tenant_hash,
            )

            seen = {}
            spec = TenantIdentifierSpec(kind="sni")

            async def h(req):
                seen["sni"] = req.ctx.get("sni")
                seen["tenant"] = spec.extract(req)
                return Response(200, body=b"ok")

            srv = await HttpServer(
                FnService(h),
                ssl_context=TlsServerConfig(*certs).mk_context()).start()
            try:
                cctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                cctx.check_hostname = False
                cctx.verify_mode = ssl.CERT_NONE
                r, w = await asyncio.open_connection(
                    "127.0.0.1", srv.bound_port, ssl=cctx,
                    server_hostname="tenant-a.example")
                w.write(b"GET / HTTP/1.1\r\nHost: x\r\n"
                        b"Connection: close\r\n\r\n")
                await w.drain()
                await r.read(4096)
                w.close()
            finally:
                await srv.close()
            assert seen["sni"] == "tenant-a.example"
            assert seen["tenant"] == "tenant-a.example"
            # parity: the Python hash of the extracted SNI equals the
            # C engines' FNV-1a over the same bytes
            assert tenant_hash(seen["tenant"]) == \
                native.tenant_hash_native(b"tenant-a.example")

        run(go())

    def test_h2_server_surfaces_sni(self, certs):
        async def go():
            from linkerd_tpu.protocol.h2.client import H2Client
            from linkerd_tpu.protocol.h2.messages import H2Response
            from linkerd_tpu.protocol.h2.server import H2Server
            from linkerd_tpu.protocol.h2.stream import stream_of
            from linkerd_tpu.protocol.tls import TlsServerConfig
            from linkerd_tpu.router.service import FnService
            import ssl

            seen = {}

            async def h(req):
                seen["sni"] = req.ctx.get("sni")
                return H2Response(status=200, stream=stream_of(b"ok"))

            srv = await H2Server(
                FnService(h),
                ssl_context=TlsServerConfig(*certs).mk_context()).start()
            cctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            cctx.check_hostname = False
            cctx.verify_mode = ssl.CERT_NONE
            cctx.set_alpn_protocols(["h2"])
            client = H2Client("127.0.0.1", srv.bound_port,
                              ssl_context=cctx,
                              server_hostname="tenant-b.example")
            try:
                from linkerd_tpu.protocol.h2.messages import H2Request
                rsp = await client(H2Request(
                    method="GET", path="/", authority="x",
                    stream=stream_of(b"")))
                assert rsp.status == 200
            finally:
                await client.close()
                await srv.close()
            assert seen["sni"] == "tenant-b.example"

        run(go())

    def test_cleartext_conn_has_no_sni(self):
        async def go():
            from linkerd_tpu.protocol.http import Response
            from linkerd_tpu.protocol.http.server import HttpServer
            from linkerd_tpu.router.service import FnService

            seen = {}

            async def h(req):
                seen["sni"] = req.ctx.get("sni")
                return Response(200, body=b"ok")

            srv = await HttpServer(FnService(h)).start()
            try:
                await _one_shot(srv.bound_port, host="x")
            finally:
                await srv.close()
            assert seen["sni"] is None

        run(go())
